"""Serving attention ops: incremental, speculative, and tree-verify MHA.

TPU-native re-design of the reference's serve hot path (reference:
``src/ops/inc_multihead_self_attention.{cc,cu}``,
``spec_inc_multihead_self_attention.cu``,
``tree_inc_multihead_self_attention.cu`` — fused QKV projection + RoPE +
KV-cache append + masked attention + output projection, with the KV cache
living in each op's ``IncMultiHeadSelfAttentionMeta``).

Design differences from the CUDA original, driven by TPU/XLA:

* One op class serves all three modes; the mode is picked by the *type* of the
  batch config shipped with the step (``BatchConfig`` → incremental,
  ``TreeSearchBatchConfig`` → draft-tree expansion,
  ``TreeVerifyBatchConfig`` → commit + tree-mask verification).  Each mode is
  a distinct static shape/program, so XLA compiles each exactly once — the
  analogue of the reference registering three task variants.
* The KV cache is functional state threaded through the jitted step (donated
  buffers), not a mutable ``OpMeta`` member.
* QKV is ONE fused weight in kv-head-major layout ``[embed, kv_heads,
  q_per_kv + 2, head_dim]``: a single MXU GEMM computes Q, K and V, and
  tensor parallelism is a plain shard of the ``kv_heads`` dim (GQA groups
  stay intact per shard).  The output projection is row-parallel; its result
  is marked a partial sum over the head axes so the PCG normalizer inserts
  the AllReduce — the same Megatron-style cut the reference reaches via its
  ``Reduction`` parallel op.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.graph import ParamSpec, TensorSpec
from ..core.op import Op, OpContext, ShardingSolution, bias_once, register_op
from ..core.sharding import TensorSharding
from .batch_config import (
    BatchConfig,
    PrefillBatchConfig,
    TreeSearchBatchConfig,
    TreeVerifyBatchConfig,
)

NEG_INF = -1e30


def _page_rows_pos(pages, rows, pos):
    """Translate LOGICAL cache coordinates (row, position) to PHYSICAL ones
    through a paged-KV block table (serve/kv_paged.py's ``PageTable``,
    shipped per step at ``ctx.extras["pages"]``).

    The physical buffers keep the slot-contiguous ``[R+1, KV, S, D]``
    shape; a page id addresses ``(row, page-slot) = divmod(pid,
    pages_per_row)``, so every existing write path (DUS chain, scatter,
    per-tile block DUS) runs unchanged on the translated coordinates —
    the indirection is pure index arithmetic, which is what makes the
    paged path bit-identical to the contiguous one.
    """
    ps, ppr = pages.page_size, pages.pages_per_row
    rows = jnp.clip(rows.astype(jnp.int32), 0, pages.table.shape[0] - 1)
    col = jnp.clip(pos.astype(jnp.int32) // ps, 0, ppr - 1)
    pid = pages.table[rows, col]
    return pid // ppr, (pid % ppr) * ps + pos.astype(jnp.int32) % ps


def _gather_logical_rows(cache, pages, rows):
    """``cache[rows]`` reconstructed through the block table: each token's
    LOGICAL cache row assembled from its physical pages ([T, KV, S(, D)]).
    The materialization cost matches the slot-contiguous gather fallback
    this replaces — it is the oracle path the Pallas kernels' in-VMEM
    indirection is tested against."""
    ps, ppr = pages.page_size, pages.pages_per_row
    r1 = cache.shape[0]
    pids = pages.table[jnp.clip(rows.astype(jnp.int32), 0,
                                pages.table.shape[0] - 1)]  # [T, ppr]
    prow, pslot = pids // ppr, pids % ppr
    if cache.ndim == 4:
        kvh, s, d = cache.shape[1:]
        cr = cache.reshape(r1, kvh, ppr, ps, d)
        # advanced indices split by a slice: indexed dims lead -> [T, ppr,
        # KV, ps, D]
        pg = cr[prow, :, pslot]
        return pg.transpose(0, 2, 1, 3, 4).reshape(rows.shape[0], kvh, s, d)
    kvh, s = cache.shape[1:]
    cr = cache.reshape(r1, kvh, ppr, ps)
    pg = cr[prow, :, pslot]                      # [T, ppr, KV, ps]
    return pg.transpose(0, 2, 1, 3).reshape(rows.shape[0], kvh, s)

# token-count cutoff between the per-token dynamic-update-slice chain and a
# single XLA scatter for KV-cache writes (see _scatter_rows_pos).  The
# switch is on the CAPACITY-PADDED batch length (max_tokens_per_batch),
# not the live token count: any InferenceManager whose max_tokens exceeds
# this silently takes the scatter path, whose layout choice forces a
# per-step full-cache relayout inside the decode/spec scans —
# SpecDecodeScan and InferenceManager.decode_scan check their capacities.
DUS_MAX_TOKENS = 128


def alibi_slopes(num_heads: int) -> jax.Array:
    """ALiBi per-head slopes (Press et al.; matches HF's power-of-2 recipe)."""
    import math as _math

    n = 2 ** _math.floor(_math.log2(num_heads))
    base = jnp.arange(1, n + 1, dtype=jnp.float32)
    slopes = 2.0 ** (-8.0 * base / n)
    if n < num_heads:  # interleave the overflow heads at half offsets
        extra = jnp.arange(1, 2 * (num_heads - n) + 1, 2, dtype=jnp.float32)
        slopes = jnp.concatenate([slopes, 2.0 ** (-4.0 * extra / n)])
    return slopes[:num_heads]


def apply_rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x: [T, ..., D] with positions [T]."""
    d = x.shape[-1]
    half = d // 2
    freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freq  # [T, half]
    # broadcast over middle dims
    shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (half,)
    cos = jnp.cos(angles).reshape(shape)
    sin = jnp.sin(angles).reshape(shape)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


@register_op
class IncMultiHeadSelfAttention(Op):
    """KV-cached multi-head/grouped-query self-attention over flat token batches.

    Input:  ``x [max_tokens, embed_dim]`` (flat step tokens).
    Output: ``y [max_tokens, embed_dim]``.
    State:  ``k/v`` committed caches ``[max_requests+1, max_seq, kv_heads,
    head_dim]`` (row ``max_requests`` is the pad-token scratch row) and, when
    speculation is enabled, ``sk/sv`` spec-tree buffers
    ``[max_requests+1, max_spec, kv_heads, head_dim]``.
    """

    type_name = "inc_multihead_self_attention"
    stateful = True

    # KV-cache storage dtype override, registered by the InferenceManager
    # (``kv_dtype="int8"``): the committed k/v caches store int8 with
    # per-(row, head, position) f32 scales in sibling ``k_scale``/``v_scale``
    # buffers — quantize-on-write in the KV-update paths, dequant FUSED into
    # the Pallas kernels' score/value contractions (never a bf16 round trip
    # through HBM).  None = caches in the op's compute dtype.  The spec-tree
    # buffers (sk/sv) stay in the compute dtype: they hold <= max_spec
    # tokens per request and are rewritten every macro-step, so quantizing
    # them saves ~nothing; accepted speculative KV is quantized when
    # _commit() copies it into the committed cache.
    kv_dtype: Optional[str] = None

    # set by the InferenceManager on the graph's FIRST attention op when the
    # prefill software-pipelining prologue is recognized: lower() then takes
    # q/k/v from ctx.extras["qkv0"] (the scan carry) when present instead of
    # projecting — see project_qkv / InferenceManager._project_chunk0.
    qkv0_consumer: bool = False

    def __init__(
        self,
        embed_dim: int,
        num_q_heads: int,
        num_kv_heads: Optional[int] = None,
        head_dim: Optional[int] = None,
        rotary_embedding: bool = True,
        rope_theta: float = 10000.0,
        use_bias: bool = False,
        scaling_factor: Optional[float] = None,
        use_alibi: bool = False,
        dtype=jnp.float32,
    ):
        self.embed_dim = int(embed_dim)
        self.num_q_heads = int(num_q_heads)
        self.num_kv_heads = int(num_kv_heads or num_q_heads)
        self.head_dim = int(head_dim or embed_dim // num_q_heads)
        if self.num_q_heads % self.num_kv_heads:
            raise ValueError("num_q_heads must be a multiple of num_kv_heads")
        self.q_per_kv = self.num_q_heads // self.num_kv_heads
        self.rotary_embedding = bool(rotary_embedding)
        self.rope_theta = float(rope_theta)
        self.use_bias = bool(use_bias)
        self.use_alibi = bool(use_alibi)
        self.scaling_factor = (
            float(scaling_factor)
            if scaling_factor is not None
            else 1.0 / math.sqrt(self.head_dim)
        )
        self.dtype = jnp.dtype(dtype).name

    # ---- shapes / params ----------------------------------------------
    def infer_shapes(self, in_specs):
        x = in_specs[0]
        if x.shape[-1] != self.embed_dim:
            raise ValueError(f"expected embed_dim {self.embed_dim}, got {x}")
        return [TensorSpec(x.shape, jnp.dtype(self.dtype))]

    def params(self) -> List[ParamSpec]:
        g = self.q_per_kv + 2  # per kv group: q_per_kv query heads + K + V
        ps = [
            ParamSpec(
                "qkv",
                TensorSpec(
                    (self.embed_dim, self.num_kv_heads, g, self.head_dim),
                    jnp.dtype(self.dtype),
                ),
            ),
            ParamSpec(
                "o_proj",
                TensorSpec(
                    (self.num_q_heads * self.head_dim, self.embed_dim),
                    jnp.dtype(self.dtype),
                ),
            ),
        ]
        if self.use_bias:
            ps.append(
                ParamSpec(
                    "qkv_bias",
                    TensorSpec(
                        (self.num_kv_heads, g, self.head_dim),
                        jnp.dtype(self.dtype),
                    ),
                )
            )
            ps.append(
                ParamSpec(
                    "o_bias",
                    TensorSpec((self.embed_dim,), jnp.dtype(self.dtype)),
                )
            )
        return ps

    # ---- state ---------------------------------------------------------
    def state_specs(
        self,
        max_requests: int,
        max_seq_len: int,
        max_spec_tokens: int = 0,
        head_axes: Tuple[str, ...] = (),
    ) -> Dict[str, Tuple[Tuple[int, ...], str, TensorSharding]]:
        """{name: (shape, dtype, sharding)} for this op's cache buffers.

        Caches are **kv-head-major** ``[rows, KV, S, D]`` so the Pallas
        decode kernel streams contiguous per-head blocks (see
        ``ops/pallas/attention.py``); the head shard axis is dim 1.
        """
        kv_shape = (max_requests + 1, self.num_kv_heads, max_seq_len, self.head_dim)
        sh = TensorSharding.from_axes(4, {1: head_axes} if head_axes else {})
        kv_dt = self.kv_dtype or self.dtype
        out = {
            "k": (kv_shape, kv_dt, sh),
            "v": (kv_shape, kv_dt, sh),
        }
        if kv_dt == "int8":
            # per-(row, head, position) f32 dequant scales; sharded over the
            # kv-head dim (dim 1) exactly like the caches they describe.
            # Zero-init (allocate_kv_cache zeros everything): an untouched
            # position dequantizes to 0 * 0 = 0, matching the fp cache's
            # zeros, so the tiled/flat write-path equivalence is preserved.
            sc_shape = kv_shape[:3]
            sc_sh = TensorSharding.from_axes(
                3, {1: head_axes} if head_axes else {}
            )
            out["k_scale"] = (sc_shape, "float32", sc_sh)
            out["v_scale"] = (sc_shape, "float32", sc_sh)
        if max_spec_tokens:
            sp_shape = (
                max_requests + 1,
                self.num_kv_heads,
                max_spec_tokens,
                self.head_dim,
            )
            out["sk"] = (sp_shape, self.dtype, sh)
            out["sv"] = (sp_shape, self.dtype, sh)
            if self.use_alibi:
                # absolute position of each spec-buffer slot (ALiBi needs key
                # positions; rope bakes them into sk at write time instead);
                # [rows, max_spec_tokens] — no head dim
                out["spec_pos"] = (
                    (sp_shape[0], sp_shape[2]), "int32",
                    TensorSharding.replicated(2),
                )
        return out

    # ---- compute -------------------------------------------------------
    def lower(self, ctx: OpContext, inputs, params):
        bc = ctx.extras.get("batch_config")
        state = ctx.extras.get("state")
        if bc is None or state is None:
            raise ValueError(
                f"{self.type_name} requires a batch_config and cache state "
                "(run it through the InferenceManager)"
            )
        x = inputs[0]  # [T, E]
        # cross-chunk software pipelining (InferenceManager.prefill_scan):
        # the FIRST attention op of the graph (qkv0_consumer, set by the
        # manager when the embedding->norm->attention prologue is
        # recognized) takes its q/k/v from the scan carry — the projection
        # was issued during the PREVIOUS chunk's step, so its weight fetch
        # can overlap that chunk's attention/MLP tail instead of stalling
        # at the while-loop iteration boundary.  The carried values are
        # computed by the same op lowers (_project_chunk0), so the paths
        # are bit-identical.
        pre = ctx.extras.get("qkv0") if self.qkv0_consumer else None
        if pre is not None:
            q, k, v = pre
        else:
            q, k, v = self.project_qkv(x, params, bc)

        if isinstance(bc, TreeVerifyBatchConfig):
            state = self._commit(state, bc,
                                 ctx.extras.get("pages") if ctx else None)
            out, state = self._tree_attend(q, k, v, state, bc, ctx)
        elif isinstance(bc, TreeSearchBatchConfig):
            out, state = self._tree_attend(q, k, v, state, bc, ctx)
        elif isinstance(bc, PrefillBatchConfig):
            out, state = self._prefill_attend(q, k, v, state, bc, ctx)
        else:
            out, state = self._inc_attend(q, k, v, state, bc, ctx)

        ctx.extras["state_out"] = state
        # [T, QH, D] -> [T, QH*D] -> o_proj (row-parallel under TP)
        t = out.shape[0]
        o_w = params["o_proj"]
        if o_w.dtype == jnp.int8:  # weight-only int8 (serve/quant.py)
            from .quant import dequant

            o_w = dequant(o_w, params["o_proj_scale"], out.dtype)
        y = jnp.dot(
            out.reshape(t, self.num_q_heads * self.head_dim),
            o_w,
            preferred_element_type=jnp.float32,
        )
        if self.use_bias:
            head = tuple(ctx.config.get("head", ())) if ctx.config else ()
            y = y + bias_once(params["o_bias"], head, ctx)
        return [y.astype(self.dtype)]

    def project_qkv(self, x, params, bc):
        """QKV projection (+ dequant + RoPE) for a step's flat tokens.

        The first stage of :meth:`lower`, also called by the
        InferenceManager's prefill software pipelining to issue the NEXT
        chunk's layer-0 projection inside the current scan step — one
        code path, so the pipelined and plain scans stay bit-identical.
        """
        qkv_w = params["qkv"]
        if qkv_w.dtype == jnp.int8:  # weight-only int8 (serve/quant.py)
            from .quant import dequant

            qkv_w = dequant(qkv_w, params["qkv_scale"], x.dtype)
        return self._project(x, qkv_w, params.get("qkv_bias"), bc)

    def _project(self, x, qkv_w, qkv_b, bc):
        base = bc.base if not isinstance(bc, BatchConfig) else bc
        t = x.shape[0]
        # one MXU GEMM for Q,K,V: [T,E] x [E, KV, G, D] -> [T, KV, G, D]
        qkv = jnp.einsum(
            "te,ekgd->tkgd", x, qkv_w, preferred_element_type=jnp.float32
        ).astype(x.dtype)
        if qkv_b is not None:
            qkv = qkv + qkv_b
        q = qkv[:, :, : self.q_per_kv, :]          # [T, KV, Gq, D]
        k = qkv[:, :, self.q_per_kv, :]            # [T, KV, D]
        v = qkv[:, :, self.q_per_kv + 1, :]        # [T, KV, D]
        if self.rotary_embedding:
            pos = base.token_position
            q = apply_rope(q, pos, self.rope_theta)
            k = apply_rope(k, pos, self.rope_theta)
        return q, k, v

    def _rows(self, bc_base: BatchConfig, max_requests: int):
        """Cache row per flat token; pad tokens land in the scratch row."""
        r = bc_base.request_index
        return jnp.where(r >= 0, r, max_requests)

    @staticmethod
    def _scatter_rows_pos(cache, rows, pos, updates):
        """``cache[rows[t], :, pos[t]] = updates[t]`` without transposes.

        ``cache.at[rows, :, pos].set(...)`` is advanced indices split by a
        slice — NumPy semantics force jnp to transpose the whole cache to
        put the indexed dims together, which inside the decode scan copied
        the multi-GB cache every step.  A per-token ``dynamic_update_slice``
        chain updates in place AND is layout-agnostic: an XLA ``scatter``
        here makes layout assignment pick a non-default cache layout for
        the decode-scan carry, forcing a full-cache relayout copy per step
        to feed the Pallas kernel's default-layout operand.
        For large token counts (prefill chunks) the unrolled DUS chain would
        bloat compile time and serialize, so fall back to one XLA scatter —
        the layout concern only bites inside the decode/spec scans, whose
        batches are at most ``max_requests`` tokens (decode) or the commit
        descriptor's ``max_requests*(depth+1)`` entries (spec macro-step);
        the DUS_MAX_TOKENS threshold keeps both on the DUS path.
        cache: [R, H, S, D], updates: [T, H, D].
        """
        t, h, d = updates.shape
        upd = updates.astype(cache.dtype)
        # Clip so both paths share the DUS path's clamped out-of-range
        # semantics: PROMISE_IN_BOUNDS on the scatter would otherwise be
        # undefined behavior for a hand-built BatchConfig with bad positions.
        rows = jnp.clip(rows.astype(jnp.int32), 0, cache.shape[0] - 1)
        pos = jnp.clip(pos.astype(jnp.int32), 0, cache.shape[2] - 1)
        if t > DUS_MAX_TOKENS:
            idx = jnp.stack([rows, pos], axis=-1)
            dnums = jax.lax.ScatterDimensionNumbers(
                update_window_dims=(1, 2),
                inserted_window_dims=(0, 2),
                scatter_dims_to_operand_dims=(0, 2),
            )
            return jax.lax.scatter(
                cache, idx, upd, dnums,
                mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
            )
        for i in range(t):
            cache = jax.lax.dynamic_update_slice(
                cache, upd[i].reshape(1, h, 1, d),
                (rows[i], jnp.int32(0), pos[i], jnp.int32(0)),
            )
        return cache

    # ---- int8 KV cache (kv_dtype="int8") -------------------------------
    @staticmethod
    def _kv_quant(x):
        """Per-vector symmetric int8 quantization of fresh K/V entries.

        ``x``: [T, KV, D] compute-dtype vectors.  Returns ``(q int8[T,KV,D],
        scale f32[T,KV])`` with ``q * scale ~= x`` — one scale per (token,
        head) vector, the per-head variant the KV literature defaults to
        (per-channel would need static key statistics; per-vector absmax is
        exact-by-construction and costs 4 bytes per 2*D-byte pair).
        """
        xf = x.astype(jnp.float32)
        scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0        # [T, KV]
        denom = jnp.where(scale > 0, scale, 1.0)
        q = jnp.clip(jnp.round(xf / denom[..., None]), -127, 127)
        return q.astype(jnp.int8), scale

    @staticmethod
    def _scatter_scale(cache, rows, pos, updates):
        """``cache[rows[t], :, pos[t]] = updates[t]`` for scale buffers.

        ``cache``: [R, KV, S] f32, ``updates``: [T, KV] — the 3-D sibling of
        :meth:`_scatter_rows_pos` (same DUS-vs-scatter reasoning and clamped
        out-of-range semantics).
        """
        t, h = updates.shape
        upd = updates.astype(cache.dtype)
        rows = jnp.clip(rows.astype(jnp.int32), 0, cache.shape[0] - 1)
        pos = jnp.clip(pos.astype(jnp.int32), 0, cache.shape[2] - 1)
        if t > DUS_MAX_TOKENS:
            idx = jnp.stack([rows, pos], axis=-1)
            dnums = jax.lax.ScatterDimensionNumbers(
                update_window_dims=(1,),
                inserted_window_dims=(0, 2),
                scatter_dims_to_operand_dims=(0, 2),
            )
            return jax.lax.scatter(
                cache, idx, upd, dnums,
                mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
            )
        for i in range(t):
            cache = jax.lax.dynamic_update_slice(
                cache, upd[i].reshape(1, h, 1),
                (rows[i], jnp.int32(0), pos[i]),
            )
        return cache

    def _write_kv(self, state, rows, pos, k, v, pages=None):
        """Write this step's K/V vectors into the committed caches,
        quantizing on write when the caches are int8.  Returns the updated
        buffers as a dict of the state keys that changed.  ``pages``
        (paged KV) translates the logical (row, position) coordinates to
        physical ones first — the scale planes ride the SAME translation,
        so int8 scales page alongside their K/V values."""
        if pages is not None:
            rows, pos = _page_rows_pos(pages, rows, pos)
        kc, vc = state["k"], state["v"]
        if kc.dtype == jnp.int8:
            kq, ks = self._kv_quant(k)
            vq, vs = self._kv_quant(v)
            return {
                "k": self._scatter_rows_pos(kc, rows, pos, kq),
                "v": self._scatter_rows_pos(vc, rows, pos, vq),
                "k_scale": self._scatter_scale(state["k_scale"], rows, pos, ks),
                "v_scale": self._scatter_scale(state["v_scale"], rows, pos, vs),
            }
        return {
            "k": self._scatter_rows_pos(kc, rows, pos, k),
            "v": self._scatter_rows_pos(vc, rows, pos, v),
        }

    @staticmethod
    def _dequant_rows(cache_tok, sc_tok, dtype):
        """Gather-path dequant: ``cache_tok`` = the gathered [T, KV, S, D]
        int8 rows, ``sc_tok`` their [T, KV, S] scales gathered the same way
        (logical reconstruction under paging).  The materialization is
        acceptable here — this is the fallback/oracle path; the Pallas
        kernels fuse the same math in VMEM."""
        return (cache_tok.astype(jnp.float32)
                * sc_tok[..., None]).astype(dtype)

    @staticmethod
    def _gather_rows_pos(cache, rows, pos):
        """``[T, H, D] = cache[rows[t], :, pos[t]]`` (same no-transpose
        reasoning as :meth:`_scatter_rows_pos`)."""
        idx = jnp.stack(
            [jnp.clip(rows.astype(jnp.int32), 0, cache.shape[0] - 1),
             jnp.clip(pos.astype(jnp.int32), 0, cache.shape[2] - 1)], axis=-1
        )
        dnums = jax.lax.GatherDimensionNumbers(
            offset_dims=(1, 2),
            collapsed_slice_dims=(0, 2),
            start_index_map=(0, 2),
        )
        return jax.lax.gather(
            cache, idx, dnums,
            slice_sizes=(1, cache.shape[1], 1, cache.shape[3]),
            mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
        )

    @staticmethod
    def _head_shard_map(ctx, head_axes, in_specs, out_specs):
        """shard_map wrapper for a Pallas attention call under GSPMD.

        Returns the identity when the mesh is trivial (plain single-device
        call), a ``shard_map`` partial over the kv-head axis when every
        non-trivial mesh axis is a head axis (Megatron serve TP: GQA groups
        stay intact per shard, so the kernel runs unchanged on local
        shapes), and ``None`` when the sharding is unsupported — the caller
        falls back to the gather path.
        """
        mesh = ctx.mesh if ctx is not None else None
        if mesh is None or all(mesh.shape[a] == 1 for a in mesh.axis_names):
            return lambda f: f
        nontrivial = {a for a in mesh.axis_names if mesh.shape[a] > 1}
        if not head_axes or not nontrivial.issubset(set(head_axes)):
            return None
        from ..compat import shard_map

        def wrap(f):
            return shard_map(
                f, mesh=mesh, in_specs=tuple(in_specs),
                out_specs=out_specs,
            )

        return wrap

    def _config_head_axes(self, ctx):
        return tuple(ctx.config.get("head", ())) if ctx and ctx.config else ()

    def _inc_attend(self, q, k, v, state, bc: BatchConfig, ctx=None):
        kc = state["k"]  # [R+1, KV, S, D]
        nreq = kc.shape[0] - 1
        rows = self._rows(bc, nreq)
        pos = bc.token_position
        pages = ctx.extras.get("pages") if ctx is not None else None
        writes = self._write_kv(state, rows, pos, k, v, pages)
        kc, vc = writes["k"], writes["v"]
        kv_q = kc.dtype == jnp.int8
        if ctx is not None and ctx.extras.get("pallas_decode"):
            from jax.sharding import PartitionSpec as P

            from ..ops.pallas.attention import decode_attention

            t = q.shape[0]
            interp = bool(ctx.extras.get("pallas_interpret"))
            # pad tokens (scratch row) otherwise stream a full cache row
            # each — their position is whatever the builder left there, and
            # the kernel's DMA clamp follows it; zero it so they fetch one
            # block (outputs are discarded anyway)
            pos = jnp.where(rows == nreq, 0, pos)
            slopes = alibi_slopes(self.num_q_heads).reshape(
                self.num_kv_heads, self.q_per_kv
            )  # [KV, gq]: shardable over the kv-head dim
            scales = (writes["k_scale"], writes["v_scale"]) if kv_q else ()
            pg = (pages.table,) if pages is not None else ()
            pg_size = pages.page_size if pages is not None else 0

            def attend(q_, kc_, vc_, rows_, pos_, slopes_, *rest):
                kv_l, gq = q_.shape[1], q_.shape[2]
                scales_ = rest[:len(scales)]
                pt_ = rest[len(scales)] if pg else None
                return decode_attention(
                    q_.reshape(t, kv_l * gq, self.head_dim),
                    kc_, vc_, rows_, pos_,
                    scale=self.scaling_factor,
                    slopes=slopes_.reshape(-1) if self.use_alibi else None,
                    use_alibi=self.use_alibi, interpret=interp,
                    k_scale=scales_[0] if scales_ else None,
                    v_scale=scales_[1] if scales_ else None,
                    page_table=pt_, page_size=pg_size,
                ).reshape(t, kv_l, gq, self.head_dim)

            h = self._config_head_axes(ctx)
            sm = self._head_shard_map(
                ctx, h,
                [P(None, h), P(None, h), P(None, h), P(), P(), P(h)]
                + [P(None, h)] * len(scales) + [P()] * len(pg),
                P(None, h),
            )
            if sm is not None:
                out = sm(attend)(q, kc, vc, rows, pos, slopes, *scales, *pg)
                out = out.reshape(t, self.num_q_heads, self.head_dim)
                new_state = dict(state)
                new_state.update(writes)
                return out, new_state
        # fallback: gather each token's cache row: [T, KV, S, D] (logical
        # reconstruction through the block table under paging)
        if pages is not None:
            k_tok = _gather_logical_rows(kc, pages, rows)
            v_tok = _gather_logical_rows(vc, pages, rows)
        else:
            k_tok = kc[rows]
            v_tok = vc[rows]
        if kv_q:  # dequant (the Pallas path fuses this in-kernel instead)
            ks_tok = (_gather_logical_rows(writes["k_scale"], pages, rows)
                      if pages is not None else writes["k_scale"][rows])
            vs_tok = (_gather_logical_rows(writes["v_scale"], pages, rows)
                      if pages is not None else writes["v_scale"][rows])
            k_tok = self._dequant_rows(k_tok, ks_tok, q.dtype)
            v_tok = self._dequant_rows(v_tok, vs_tok, q.dtype)
        s = k_tok.shape[2]
        # causal over absolute positions (covers prefill + decode uniformly)
        mask = jnp.arange(s)[None, :] <= pos[:, None]  # [T, S]
        scores = jnp.einsum(
            "tkgd,tksd->tkgs", q, k_tok, preferred_element_type=jnp.float32
        )
        scores = scores * self.scaling_factor
        if self.use_alibi:
            slopes = alibi_slopes(self.num_q_heads).reshape(
                self.num_kv_heads, self.q_per_kv
            )
            rel = (jnp.arange(s)[None, :] - pos[:, None]).astype(jnp.float32)
            scores = scores + slopes[None, :, :, None] * rel[:, None, None, :]
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
        w = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "tkgs,tksd->tkgd", w, v_tok.astype(w.dtype),
            preferred_element_type=jnp.float32,
        )
        t = q.shape[0]
        out = out.reshape(t, self.num_q_heads, self.head_dim).astype(q.dtype)
        new_state = dict(state)
        new_state.update(writes)
        return out, new_state

    def _prefill_attend(self, q, k, v, state, bc: PrefillBatchConfig, ctx):
        """Prompt-phase attention over request-homogeneous query tiles.

        Routes to the Q-tiled Pallas prefill kernel (prefix blocks stream
        once per TILE, not once per token — see
        ``ops/pallas/attention.py:prefill_attention``); falls back to the
        flat gather path (``_inc_attend``) for ALiBi models or shardings
        the kernel can't express — the fallback is also the equality oracle
        the prefill tests compare against.
        """
        base = bc.base
        use_kernel = (
            ctx is not None
            and ctx.extras.get("pallas_decode")
            and not self.use_alibi
        )
        if not use_kernel:
            return self._inc_attend(q, k, v, state, base, ctx)
        from jax.sharding import PartitionSpec as P

        from ..ops.pallas.attention import prefill_attention

        kc, vc = state["k"], state["v"]
        nreq = kc.shape[0] - 1
        rows = self._rows(base, nreq)
        pos = base.token_position
        pages = ctx.extras.get("pages") if ctx is not None else None

        t = q.shape[0]
        bq = bc.tile_size
        g = t // bq
        interp = bool(ctx.extras.get("pallas_interpret"))
        kv_q = kc.dtype == jnp.int8
        h = self._config_head_axes(ctx)
        sm = self._head_shard_map(
            ctx, h,
            [P(None, h), P(None, h), P(None, h), P(), P()]
            + [P(None, h)] * (2 if kv_q else 0)
            + [P()] * (1 if pages is not None else 0),
            P(None, h),
        )
        if sm is None:  # unsupported sharding: flat gather fallback
            return self._inc_attend(q, k, v, state, base, ctx)
        # tile row: real slots sit at the tile head, pads map to the scratch
        # row nreq (the largest index), so min() recovers the tile's request
        tile_rows = jnp.min(rows.reshape(g, bq), axis=1)
        pstart = pos.reshape(g, bq)[:, 0]
        if pages is not None:
            # physical coordinates for the per-tile block DUS: a tile sits
            # inside ONE page (tile-aligned start, tile divides page — the
            # manager validates page % prefill_tile == 0), so translating
            # the tile's start translates the whole block
            w_rows, w_start = _page_rows_pos(pages, tile_rows, pstart)
        else:
            w_rows, w_start = tile_rows, pstart
        # KV-cache write as G per-tile BLOCK dynamic-update-slices instead of
        # a flat-token scatter: a prefill chunk carries max_tokens (>
        # DUS_MAX_TOKENS) tokens, so _scatter_rows_pos would take the XLA
        # scatter path — whose layout choice forces a full-cache relayout
        # copy per prefill_scan step (the same hazard _scatter_rows_pos
        # documents for the decode scan, ~2x the chunk's whole HBM traffic
        # at the 7B bench shape).  PrefillBatchConfig's contract makes the
        # block write exact for real tokens: tile g is one request, its
        # positions contiguous from a TILE-ALIGNED pstart (RequestManager
        # only advances prefill_offset by whole tiles until completion), so
        # the DUS start is never clamp-shifted.  Tail-pad slots write ZEROS
        # at the request's next positions (junk-free: fresh caches are
        # zeros, so the tiled and flat paths stay bit-identical); even a
        # non-zero value there would be benign, since every future step
        # WRITES position p before any token's causal frontier reaches p
        # (the scratch-row behavior of fully-pad tiles is unchanged: min()
        # maps them to row nreq).
        if kv_q:
            # quantize-on-write: the int8 VALUES ride the same per-tile
            # block DUS as the fp path; the per-(token, head) scales ride a
            # matching [1, KV, bq] block DUS into the scale caches.  Tile
            # pads write value 0 AND scale 0, so they dequantize to the
            # zeros the fp path writes (the tiled/flat bit-identity note
            # above carries over to the quantized representation).
            k, ks = self._kv_quant(k)   # int8 [T, KV, D], f32 [T, KV]
            v, vs = self._kv_quant(v)
            ksc, vsc = state["k_scale"], state["v_scale"]  # [R+1, KV, S]
            valid_s = (base.request_index >= 0).reshape(g, 1, bq)
            ksb = jnp.where(
                valid_s, ks.reshape(g, bq, self.num_kv_heads)
                .transpose(0, 2, 1), 0.0)
            vsb = jnp.where(
                valid_s, vs.reshape(g, bq, self.num_kv_heads)
                .transpose(0, 2, 1), 0.0)
        valid = (base.request_index >= 0).reshape(g, 1, bq, 1)
        kb = k.reshape(g, bq, self.num_kv_heads, self.head_dim) \
             .transpose(0, 2, 1, 3).astype(kc.dtype)
        vb = v.reshape(g, bq, self.num_kv_heads, self.head_dim) \
             .transpose(0, 2, 1, 3).astype(vc.dtype)
        kb = jnp.where(valid, kb, 0)
        vb = jnp.where(valid, vb, 0)
        zero = jnp.int32(0)
        for i in range(g):
            at = (w_rows[i], zero, w_start[i], zero)
            kc = jax.lax.dynamic_update_slice(kc, kb[i][None], at)
            vc = jax.lax.dynamic_update_slice(vc, vb[i][None], at)
            if kv_q:
                ksc = jax.lax.dynamic_update_slice(
                    ksc, ksb[i][None], at[:3])
                vsc = jax.lax.dynamic_update_slice(
                    vsc, vsb[i][None], at[:3])
        scales = (ksc, vsc) if kv_q else ()
        pg = (pages.table,) if pages is not None else ()
        pg_size = pages.page_size if pages is not None else 0

        def attend(q_, kc_, vc_, rows_, pstart_, *rest):
            kv_l, gq = q_.shape[1], q_.shape[2]
            scales_ = rest[:len(scales)]
            pt_ = rest[len(scales)] if pg else None
            return prefill_attention(
                q_.reshape(t, kv_l * gq, self.head_dim).reshape(
                    g, bq, kv_l * gq, self.head_dim
                ),
                kc_, vc_, rows_, pstart_,
                scale=self.scaling_factor, interpret=interp,
                k_scale=scales_[0] if scales_ else None,
                v_scale=scales_[1] if scales_ else None,
                page_table=pt_, page_size=pg_size,
            ).reshape(t, kv_l, gq, self.head_dim)

        out = sm(attend)(q, kc, vc, tile_rows, pstart, *scales, *pg)
        out = out.reshape(t, self.num_q_heads, self.head_dim)
        new_state = dict(state)
        new_state["k"], new_state["v"] = kc, vc
        if kv_q:
            new_state["k_scale"], new_state["v_scale"] = ksc, vsc
        return out, new_state

    def _commit(self, state, bc: TreeVerifyBatchConfig, pages=None):
        """Copy accepted speculative KV (spec buffer → committed cache).

        Reference: the ``committed_tokens`` handling at the top of
        ``tree_inc_multihead_self_attention.cu`` — the verified tokens of the
        previous macro-step become part of the causal past before the new
        tree is scored.
        """
        kc, sk, sv = state["k"], state["sk"], state["sv"]
        nreq = kc.shape[0] - 1
        rows = jnp.where(bc.commit_request_index >= 0, bc.commit_request_index, nreq)
        # _scatter/_gather_rows_pos clip rows/pos internally.  The spec
        # buffers hold compute-dtype KV; with an int8 committed cache,
        # _write_kv quantizes the accepted vectors here — the same
        # quantizer the incremental path applies, so a token's cache entry
        # is bit-identical whichever path wrote it.  The spec-buffer READ
        # stays slot-contiguous (sk/sv are never paged); only the committed
        # destination translates through the block table.
        src = bc.commit_src_spec_index
        dst = bc.commit_dst_position
        new_state = dict(state)
        new_state.update(self._write_kv(
            state, rows, dst,
            self._gather_rows_pos(sk, rows, src),
            self._gather_rows_pos(sv, rows, src),
            pages,
        ))
        return new_state

    def _tree_attend(self, q, k, v, state, bc, ctx=None):
        """Attend over committed cache (causal) + spec-tree buffer (ancestor mask).

        Used by both the draft model's expansion steps (SpecInc) and the
        LLM's verification step (TreeInc): the math is identical; only the
        batch-config contents differ.
        """
        base = bc.base
        kc, vc, sk, sv = state["k"], state["v"], state["sk"], state["sv"]
        nreq = kc.shape[0] - 1
        rows = self._rows(base, nreq)
        pages = ctx.extras.get("pages") if ctx is not None else None
        spec_idx = jnp.clip(bc.spec_index, 0, sk.shape[2] - 1)
        sk = self._scatter_rows_pos(sk, rows, spec_idx, k)
        sv = self._scatter_rows_pos(sv, rows, spec_idx, v)
        spec_pos = None
        if self.use_alibi:
            spec_pos = state["spec_pos"].at[rows, spec_idx].set(
                base.token_position
            )
        if (ctx is not None and ctx.extras.get("pallas_decode")
                and not self.use_alibi):
            from jax.sharding import PartitionSpec as P

            from ..ops.pallas.attention import (
                tree_attention,
                tree_attention_batched,
            )

            t = q.shape[0]
            interp = bool(ctx.extras.get("pallas_interpret"))
            # scratch-row (pad) tokens get a zero committed frontier so the
            # kernel's DMA clamp fetches one block for them, not the full
            # cache depth of whatever request the index clamp landed on
            clens = jnp.where(rows == nreq, 0, bc.committed_lens[rows])
            amask = bc.ancestor_mask[rows, spec_idx]
            # fixed [R, P] token layout (the on-device spec scan): all P
            # tree tokens of a request share one kernel grid row, so the
            # committed cache streams once per REQUEST, not once per token
            layout = ctx.extras.get("tree_layout")
            kv_q = kc.dtype == jnp.int8
            scales = (state["k_scale"], state["v_scale"]) if kv_q else ()
            pg = (pages.table,) if pages is not None else ()
            pg_size = pages.page_size if pages is not None else 0

            def attend(q_, kc_, vc_, sk_, sv_, rows_, clens_, amask_,
                       *rest):
                kv_l, gq = q_.shape[1], q_.shape[2]
                d = self.head_dim
                scales_ = rest[:len(scales)]
                pt_ = rest[len(scales)] if pg else None
                ks_ = scales_[0] if scales_ else None
                vs_ = scales_[1] if scales_ else None
                if layout:
                    r_t, p_t = layout
                    used = r_t * p_t
                    qf = q_.reshape(t, kv_l * gq, d)
                    ob = tree_attention_batched(
                        qf[:used].reshape(r_t, p_t, kv_l * gq, d),
                        kc_, vc_, sk_, sv_,
                        rows_[:used:p_t], clens_[:used:p_t],
                        amask_[:used].reshape(r_t, p_t, -1),
                        scale=self.scaling_factor, interpret=interp,
                        k_scale=ks_, v_scale=vs_,
                        page_table=pt_, page_size=pg_size,
                    ).reshape(used, kv_l * gq, d)
                    if used < t:  # capacity-pad tokens: outputs are ignored
                        ob = jnp.zeros((t, kv_l * gq, d), ob.dtype) \
                            .at[:used].set(ob)
                    return ob.reshape(t, kv_l, gq, d)
                return tree_attention(
                    q_.reshape(t, kv_l * gq, d),
                    kc_, vc_, sk_, sv_, rows_, clens_, amask_,
                    scale=self.scaling_factor, interpret=interp,
                    k_scale=ks_, v_scale=vs_,
                    page_table=pt_, page_size=pg_size,
                ).reshape(t, kv_l, gq, d)

            h = self._config_head_axes(ctx)
            sm = self._head_shard_map(
                ctx, h,
                [P(None, h)] * 5 + [P(), P(), P()]
                + [P(None, h)] * len(scales) + [P()] * len(pg),
                P(None, h),
            )
            if sm is not None:
                out = sm(attend)(q, kc, vc, sk, sv, rows, clens, amask,
                                 *scales, *pg)
                out = out.reshape(t, self.num_q_heads, self.head_dim)
                new_state = dict(state)
                new_state["sk"], new_state["sv"] = sk, sv
                return out, new_state

        if pages is not None:  # logical reconstruction of committed rows
            k_cache_tok = _gather_logical_rows(kc, pages, rows)
            v_cache_tok = _gather_logical_rows(vc, pages, rows)
        else:
            k_cache_tok = kc[rows]   # [T, KV, S, D]
            v_cache_tok = vc[rows]
        if kc.dtype == jnp.int8:  # dequant (Pallas path fuses this instead)
            ks_tok = (_gather_logical_rows(state["k_scale"], pages, rows)
                      if pages is not None else state["k_scale"][rows])
            vs_tok = (_gather_logical_rows(state["v_scale"], pages, rows)
                      if pages is not None else state["v_scale"][rows])
            k_cache_tok = self._dequant_rows(k_cache_tok, ks_tok, q.dtype)
            v_cache_tok = self._dequant_rows(v_cache_tok, vs_tok, q.dtype)
        k_spec_tok = sk[rows]    # [T, KV, P, D]
        v_spec_tok = sv[rows]
        s = k_cache_tok.shape[2]

        # committed part: strictly below the committed frontier
        cmask = jnp.arange(s)[None, :] < bc.committed_lens[rows][:, None]
        # spec part: tree-topology ancestors (mask rows gathered per token)
        amask = bc.ancestor_mask[rows, spec_idx]  # [T, P]

        sc_c = jnp.einsum(
            "tkgd,tksd->tkgs", q, k_cache_tok, preferred_element_type=jnp.float32
        ) * self.scaling_factor
        sc_p = jnp.einsum(
            "tkgd,tkpd->tkgp", q, k_spec_tok, preferred_element_type=jnp.float32
        ) * self.scaling_factor
        if self.use_alibi:
            slopes = alibi_slopes(self.num_q_heads).reshape(
                self.num_kv_heads, self.q_per_kv
            )[None, :, :, None]
            qpos = base.token_position
            rel_c = (jnp.arange(s)[None, :] - qpos[:, None]).astype(jnp.float32)
            rel_p = (spec_pos[rows] - qpos[:, None]).astype(jnp.float32)
            sc_c = sc_c + slopes * rel_c[:, None, None, :]
            sc_p = sc_p + slopes * rel_p[:, None, None, :]
        sc_c = jnp.where(cmask[:, None, None, :], sc_c, NEG_INF)
        sc_p = jnp.where(amask[:, None, None, :], sc_p, NEG_INF)
        scores = jnp.concatenate([sc_c, sc_p], axis=-1)
        w = jax.nn.softmax(scores, axis=-1)
        v_all = jnp.concatenate([v_cache_tok, v_spec_tok], axis=2).astype(w.dtype)
        out = jnp.einsum(
            "tkgs,tksd->tkgd", w, v_all, preferred_element_type=jnp.float32
        )
        t = q.shape[0]
        out = out.reshape(t, self.num_q_heads, self.head_dim).astype(q.dtype)
        new_state = dict(state)  # k/v already carry any commit from _commit()
        new_state["sk"], new_state["sv"] = sk, sv
        if spec_pos is not None:
            new_state["spec_pos"] = spec_pos
        return out, new_state

    # ---- parallelization ----------------------------------------------
    def parallel_dims(self, in_specs):
        return {"sample": in_specs[0].shape[0], "head": self.num_kv_heads}

    def apply_config(self, config, in_specs, mesh, in_shardings=None):
        x = in_specs[0]
        head = tuple(config.get("head", ()))
        x_sh = TensorSharding.replicated(x.ndim)
        out_sh = TensorSharding.replicated(x.ndim)
        qkv_sh = TensorSharding.from_axes(4, {1: head} if head else {})
        o_sh = TensorSharding.from_axes(2, {0: head} if head else {})
        params = {"qkv": qkv_sh, "o_proj": o_sh}
        if self.use_bias:
            params["qkv_bias"] = TensorSharding.from_axes(
                3, {0: head} if head else {}
            )
        if head:
            out_sh = out_sh.with_partial(head)
        return ShardingSolution(inputs=[x_sh], outputs=[out_sh], params=params)

    # cache depth used for costing; the InferenceManager sets this to its
    # max_seq_len at compile so the simulator sees the deployment's actual
    # attention span instead of a hard-coded constant (VERDICT r2 item 4)
    cost_seq_len: Optional[int] = None

    def flops(self, in_specs):
        t = in_specs[0].shape[0]
        e = self.embed_dim
        qh, d = self.num_q_heads, self.head_dim
        s = self.cost_seq_len or 1024
        proj = 2 * t * e * (qh + 2 * self.num_kv_heads) * d + 2 * t * qh * d * e
        attn = 2 * t * qh * d * s * 2
        return proj + attn


@register_op
class PositionEmbedding(Op):
    """Learned absolute position embedding, positions from the BatchConfig.

    Reference: OPT/StarCoder serve graphs in ``inference/models/opt.cc`` /
    ``starcoder.cc`` feed per-token positions alongside token ids; here the
    positions already ride the step's BatchConfig, so this op needs no graph
    input — it adds ``weight[token_position + offset]`` (OPT uses offset 2).
    """

    type_name = "position_embedding"

    def __init__(self, num_positions: int, out_dim: int, offset: int = 0,
                 dtype=jnp.float32):
        self.num_positions = int(num_positions)
        self.out_dim = int(out_dim)
        self.offset = int(offset)
        self.dtype = jnp.dtype(dtype).name

    def infer_shapes(self, in_specs):
        x = in_specs[0]  # [T, E]: the token embedding to add to
        if x.shape[-1] != self.out_dim:
            raise ValueError(f"expected dim {self.out_dim}, got {x}")
        return [TensorSpec(x.shape, jnp.dtype(self.dtype))]

    def params(self):
        return [
            ParamSpec(
                "weight",
                TensorSpec(
                    (self.num_positions + self.offset, self.out_dim),
                    jnp.dtype(self.dtype),
                ),
            )
        ]

    def lower(self, ctx, inputs, params):
        bc = ctx.extras.get("batch_config")
        if bc is None:
            raise ValueError("position_embedding requires a batch_config")
        base = bc if isinstance(bc, BatchConfig) else bc.base
        pos = jnp.clip(
            base.token_position + self.offset, 0,
            self.num_positions + self.offset - 1,
        )
        return [inputs[0] + params["weight"][pos].astype(inputs[0].dtype)]

    def apply_config(self, config, in_specs, mesh, in_shardings=None):
        sh = TensorSharding.replicated(in_specs[0].ndim)
        return ShardingSolution(
            inputs=[sh], outputs=[sh],
            params={"weight": TensorSharding.replicated(2)},
        )


@register_op
class SpecIncMultiHeadSelfAttention(IncMultiHeadSelfAttention):
    """Parity alias: the draft model's tree-expansion attention.

    Reference: ``src/ops/spec_inc_multihead_self_attention.cu``.  Behavior is
    fully covered by :class:`IncMultiHeadSelfAttention` (mode dispatch on the
    batch-config type); the subclass exists so graphs read like the
    reference's and strategies can target it by type name.
    """

    type_name = "spec_inc_multihead_self_attention"


@register_op
class TreeIncMultiHeadSelfAttention(IncMultiHeadSelfAttention):
    """Parity alias: the verifier's tree-mask attention.

    Reference: ``src/ops/tree_inc_multihead_self_attention.cu``.
    """

    type_name = "tree_inc_multihead_self_attention"
