"""Weight-only int8 quantization for serve graphs (VERDICT r4 #8).

Reference parity: the serve fork's Linear carries quantization hooks
(SURVEY.md §2.2 — "quantization hooks in serve fork"); FlexFlow dequantizes
in its CUDA GEMM prologue.  The TPU analogue: weights are stored int8 with
per-out-channel f32 scales and dequantized on chip — XLA fuses the
``convert * scale`` into the dot's operand pipeline, so HBM traffic for the
quantized weights halves (bf16 -> int8).  Decode is weight-bandwidth-bound,
making this a direct TPOT lever.

Applies AFTER ``init_operators_inference`` / HF weight load: arrays are
replaced in-place in ``im.params`` (sharded like the originals), and the
attention op's fused QKV / output projections ride the same scheme via a
dtype check in ``serve/ops.py``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linear import Linear


def _quantize_array(w):
    """int8-quantize ``w`` with per-out-channel scales.

    Every weight here contracts over its FIRST dim (Linear ``[in, out]``,
    fused QKV ``[E, KV, G, D]``, o_proj ``[QH*D, E]``), so the scale spans
    ``w.shape[1:]`` — one scale per output channel.  Returns ``(q int8,
    scale f32)`` with ``q * scale ~= w`` and per-element error bounded by
    ``scale / 2``.
    """
    wf = np.asarray(w, np.float32)
    scale = np.abs(wf).max(axis=0) / 127.0
    scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
    q = np.clip(np.round(wf / scale), -127, 127).astype(np.int8)
    return q, scale


def _like_sharded(arr, ref):
    """Device-put ``arr`` with ``ref``'s sharding when it has one."""
    sh = getattr(ref, "sharding", None)
    if sh is not None and getattr(sh, "mesh", None) is not None:
        try:
            return jax.device_put(arr, sh)
        except (ValueError, TypeError):
            pass
    return jnp.asarray(arr)


def _scale_sharding(kernel_ref, mesh=None):
    """NamedSharding for a per-out-channel scale: the kernel sharding's
    spec with the contracted (first) dim dropped.  The mesh comes from the
    kernel's OWN sharding — under pipeline-parallel serving each stage's
    kernels live on that stage's sub-mesh, not the model's full mesh."""
    sh = getattr(kernel_ref, "sharding", None)
    if sh is None or getattr(sh, "spec", None) is None:
        return None
    mesh = getattr(sh, "mesh", None) or mesh
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(*sh.spec[1:]))


def quantize_int8(im, include: Optional[Sequence[str]] = None,
                  attention: bool = True) -> int:
    """Quantize the serve model's weight matrices to int8 in place.

    ``include``: optional name substrings restricting which nodes quantize
    (default: every Linear with a 2-D kernel + every attention op's fused
    projections).  The filter applies to BOTH branches — ``attention=True``
    only opts the attention ops in, it does not override ``include``
    (ADVICE r5 low).  ``attention``: also quantize the attention op's fused
    ``qkv`` and ``o_proj``.  Returns the number of quantized weight arrays.
    Call after ``init_operators_inference`` (and any HF weight load);
    re-quantizing is a no-op (int8 arrays are skipped).
    """
    assert im.params is not None, "call init_operators_inference() first"
    mesh = im.model.mesh
    n = 0
    for node in im.model.graph.nodes:
        op = node.op
        g = im.params.get(node.name)
        if g is None:
            continue
        if include and not any(s in node.name for s in include):
            continue
        if isinstance(op, Linear):
            k = g.get("kernel")
            if k is None or k.dtype == jnp.int8:
                continue
            q, scale = _quantize_array(k)
            g["kernel"] = _like_sharded(q, k)
            ssh = _scale_sharding(k, mesh)
            g["kernel_scale"] = (jax.device_put(jnp.asarray(scale), ssh)
                                 if ssh is not None else jnp.asarray(scale))
            op.quantization = "int8"
            n += 1
        elif attention and hasattr(op, "num_kv_heads"):
            for pname in ("qkv", "o_proj"):
                w = g.get(pname)
                if w is None or w.dtype == jnp.int8:
                    continue
                q, scale = _quantize_array(w)
                g[pname] = _like_sharded(q, w)
                ssh = _scale_sharding(w, mesh)
                g[f"{pname}_scale"] = (
                    jax.device_put(jnp.asarray(scale), ssh)
                    if ssh is not None else jnp.asarray(scale))
                op.quantization = "int8"  # capacity planning (see below)
                n += 1
    return n


def annotate_int8(graph, include: Optional[Sequence[str]] = None,
                  attention: bool = True) -> int:
    """Mark a serve graph's weight matrices as int8 FOR CAPACITY PLANNING,
    without touching any arrays.

    ``plan_memory_bytes`` (search/simulator.py) counts params marked
    ``op.quantization == "int8"`` at 1 byte/element + per-out-channel f32
    scales — the planning-time counterpart of :func:`quantize_int8`, usable
    on a purely symbolic graph (no ``init_operators_inference`` needed).
    This is how the full-depth 32-layer 7B-shape config is budgeted BEFORE
    allocating anything: build the graph, ``annotate_int8`` it, register
    the serve capacities (+ ``kv_dtype="int8"``), and check
    ``plan_memory_bytes(plan, training=False)`` against the chip's HBM.
    Same ``include``/``attention`` selection rules as :func:`quantize_int8`.
    Returns the number of ops marked.
    """
    n = 0
    for node in graph.nodes:
        op = node.op
        if include and not any(s in node.name for s in include):
            continue
        if isinstance(op, Linear):
            if any(p.name == "kernel" and len(p.spec.shape) == 2
                   for p in op.params()):
                op.quantization = "int8"
                n += 1
        elif attention and hasattr(op, "num_kv_heads"):
            op.quantization = "int8"
            n += 1
    return n


def dequant(w, scale, dtype):
    """On-chip dequantize: fused by XLA into the consuming dot."""
    if w.dtype != jnp.int8:
        return w
    return (w.astype(jnp.float32) * scale).astype(dtype)
