"""SpecInfer: tree-based speculative decoding (SSM draft + LLM verify).

Reference: ``RequestManager::serve_spec_infer`` / ``prepare_next_batch_beam`` /
``prepare_next_batch_verify`` in ``src/runtime/request_manager.cc`` and the
SpecInfer ASPLOS'24 design: a small draft model (SSM) expands a token TREE per
request; the LLM verifies the whole tree in ONE batched step using
tree-topology causal attention; the longest root-path whose tokens match the
LLM's own greedy choices is committed, plus one "bonus" token from the LLM —
so each LLM pass can commit up to depth+1 tokens.

Per macro-step, per request (host bookkeeping; device work is 4 jitted
programs total — SSM inc/tree-search, LLM inc/tree-verify):

1. *catch-up*   — feed tokens accepted last round into the SSM's committed
   cache (plain ``BatchConfig``; the LLM's copies are committed via the
   verify step's commit descriptor instead, reusing KV computed during
   verification).
2. *draft*      — root = latest token; ``depth`` beam-expansion steps of
   width ``width`` through the SSM (``TreeSearchBatchConfig``), keeping
   per-node cumulative logprobs; nodes live in the spec KV buffer.
3. *verify*     — flatten the tree into one ``TreeVerifyBatchConfig`` step of
   the LLM (commit descriptor carries last round's accepted nodes); walk the
   result greedily root-down to find the accepted path + bonus token.

Greedy invariant (tested): output sequences are EXACTLY those of plain
incremental decoding with the LLM, for any draft model.

**Mixed spec/non-spec batches (first-class production mode).**  Speculation
is a PER-REQUEST scheduling decision: ``register_new_request(spec=...)``
sets the mode at admission (default True under this manager) and
``set_spec_mode`` flips it at runtime.  Non-spec rows join the same verify
macro-step as degenerate root-only trees — their single node is the decode
token, the accept walk trivially emits one target-sampled token — so a
heterogeneous mix runs in ONE batched LLM step: spec rows verify
multi-token, plain rows decode one token.  While NO live request is in
spec mode, the manager's tick degrades to the incremental fast path
(decode stretches/scans included) after flushing any pending spec commits
into the committed cache, so an all-plain population never pays the
macro-step overhead.

**Seeded-sampling bit-identity.**  Every sampled dispatch in the spec
phases keys on the r9 ``(rid, token_index)`` fold (a verify row at tree
depth ``d`` samples generated-token index ``len(generated) + d``), so
sampled speculative serving is BIT-IDENTICAL to sampled incremental
decoding — which is what makes mixed batches, recompute recovery, and
mode flips composable: a token's value depends only on (seed, rid, index)
and the committed prefix, never on which serving path produced it.

**Recompute recovery.**  ``supports_recompute`` is True: a dispatch fault
past the retry budget (or slot/page pressure) preempts the affected
requests through the r9 path — spec bookkeeping (tree, pending commits,
committed depths) resets, the readmission re-prefills prompt+generated
into BOTH models' caches, and the recomputed tokens are bit-identical.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .batch_config import (
    BatchConfig,
    TreeSearchBatchConfig,
    TreeVerifyBatchConfig,
)
from .inference_manager import InferenceManager
from .request_manager import (
    GenerationConfig,
    Request,
    RequestManager,
    RequestStatus,
)


@dataclasses.dataclass
class TokenTreeNode:
    token: int
    parent: int          # index into the tree's node list (-1 for root)
    depth: int
    logprob: float = 0.0  # cumulative draft logprob along the root path


@dataclasses.dataclass
class SpecRequest(Request):
    """Request + speculation bookkeeping."""

    # accepted-but-not-yet-committed (spec_index, position, token) triples;
    # committed into the LLM cache by the NEXT verify step's commit descriptor
    pending_commit: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    llm_committed: int = 0   # LLM cache depth
    ssm_committed: int = 0   # SSM cache depth
    ssm_backlog: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    tree: List[TokenTreeNode] = dataclasses.field(default_factory=list)


class SpecInferManager(RequestManager):
    """Drives speculative serving over two InferenceManagers (SSM + LLM).

    Queue/admission/stopping logic is inherited from :class:`RequestManager`
    (so incremental and speculative serving can never diverge on lifecycle
    semantics); this class replaces the per-step loop with the three-phase
    macro step.  ``width``/``depth`` bound each request's tree to
    ``1 + width*depth`` nodes; all capacities are validated up front.
    """

    request_cls = SpecRequest
    # inherited speculation semantics: requests default to spec mode (the
    # historical all-spec behavior); callers opt rows out per request
    default_spec_mode = True
    # dispatch failures recover through the r9 preemption-and-recompute
    # path: preempt() resets the spec bookkeeping and readmission
    # re-prefills prompt+generated into both models' caches (bit-identical
    # for greedy AND seeded sampling — the (rid, token_index) fold)
    supports_recompute = True

    def __init__(
        self,
        llm: InferenceManager,
        ssm: InferenceManager,
        gen_config: Optional[GenerationConfig] = None,
        width: int = 2,
        depth: int = 3,
        telemetry=None,
        resilience=None,
        fault_injector=None,
        clock=None,
        plan_health=None,
        profiler=None,
        slo=None,
        brownout=None,
    ):
        super().__init__(llm, gen_config, telemetry=telemetry,
                         resilience=resilience,
                         fault_injector=fault_injector, clock=clock,
                         plan_health=plan_health, profiler=profiler,
                         slo=slo, brownout=brownout)
        self.llm = llm
        self.ssm = ssm
        self.width = width
        self.depth = depth
        self.max_tree = 1 + width * depth
        if llm.max_spec_tokens < self.max_tree or ssm.max_spec_tokens < self.max_tree:
            raise ValueError(
                f"spec buffers too small: need {self.max_tree} slots, have "
                f"llm={llm.max_spec_tokens} ssm={ssm.max_spec_tokens}"
            )
        if llm.max_requests != ssm.max_requests:
            raise ValueError("LLM and SSM must agree on max_requests")
        if llm.max_tokens < llm.max_requests * self.max_tree:
            raise ValueError(
                "LLM max_tokens_per_batch must fit max_requests full trees "
                f"({llm.max_requests}x{self.max_tree})"
            )
        if ssm.max_tokens < ssm.max_requests * width:
            raise ValueError(
                "SSM max_tokens_per_batch must fit one frontier per request "
                f"({ssm.max_requests}x{width})"
            )
        if ssm.topk < width:
            raise ValueError(f"SSM InferenceManager needs topk >= width ({width})")
        self.macro_steps = 0
        self.llm_steps = 0
        self._kv_hwm_tokens = 0    # combined (target + draft) watermark
        self._kv_hwm_bytes = 0.0
        # the draft model is a co-resident deployment: its params + KV
        # buffers are REAL HBM, so its allocator joins the attribution
        # protocol (reset like the target's in RequestManager.__init__)
        # and its predicted-vs-allocated record lands in the memory
        # ledger under its own "_draft" plan key — same tp/pp shape as
        # the target must not collide with the target's record
        # the draft model shares the ONE profiler handle (like telemetry):
        # its dispatches/jit caches join the dispatch + recompile
        # accounting, and its work is priced with its OWN cost card
        ssm.profiler = self.profiler
        if self.profiler.enabled:
            self.profiler.install(ssm)
        kv_s = getattr(ssm, "kv", None)
        if kv_s is not None:
            kv_s.reset_attribution()
            # the base __init__ auto-wired the plan-health monitor to the
            # TARGET allocator; widen the auto-wiring to both caches so
            # the OOM projection covers the draft's growth too (an
            # explicitly-provided allocator is the caller's choice)
            kv_l = getattr(llm, "kv", None)
            if (self.plan_health is not None and kv_l is not None
                    and self.plan_health.kv_allocator is kv_l):
                self.plan_health.kv_allocator = [kv_l, kv_s]
        if self.telemetry.enabled and hasattr(ssm, "publish_memory"):
            ssm.publish_memory(self.telemetry,
                               key=ssm.plan_key + "_draft")

    def trace_run_meta(self):
        """Trace provenance (obs/replay.py): the base manager's header
        plus the draft-tree shape and the draft deployment's plan — a
        fidelity replay must rebuild the SAME speculation config, and a
        what-if replay prices spec candidates off these fields."""
        meta = super().trace_run_meta()
        from ..obs.replay import engine_shape_of

        meta["spec"] = {"width": self.width, "depth": self.depth,
                        "draft_plan": engine_shape_of(self.ssm)}
        return meta

    # ------------------------------------------------------------------
    # memory observability over TWO deployments (target + draft)
    # ------------------------------------------------------------------
    def _kv_bind(self, rid: int) -> None:
        # the target allocator gets the full prefix-reuse bind (the LLM
        # prompt prefill consumes the cached offset); the draft cache
        # binds attribution + slot only — its pages map on demand through
        # the ssm-side prepare spans, no prefix chain (the catch-up feed
        # is committed-depth-driven, not offset-driven)
        super()._kv_bind(rid)
        kv_s = getattr(self.ssm, "kv", None)
        if kv_s is not None:
            kv_s.bind(rid, slot=self.requests[rid].slot)

    def _release_slot(self, req: Request) -> None:
        if req.slot < 0:
            return
        # both deployments release on every slot-leaving path (terminal
        # outcomes AND preemption — spec requests recompute now, so a
        # request can bind more than once): the combined target+draft
        # bytes of THIS binding epoch max-combine with previous epochs'
        # stamp, so the recorded peak is what the request really held
        kv_s = getattr(self.ssm, "kv", None)
        draft = (kv_s.release(req.rid, tokens=req.ssm_committed)
                 if kv_s is not None else 0.0)
        kv_l = getattr(self.llm, "kv", None)
        target = (kv_l.release(req.rid, tokens=req.seq_len)
                  if kv_l is not None else 0.0)
        self.slots[req.slot] = None
        req.slot = -1
        req.kv_bytes = max(req.kv_bytes, target + draft)

    def preempt(self, rid: int) -> None:
        """Recompute-based spec preemption (lifts the r9 restriction):
        the slot + BOTH caches release, the tree/commit/committed-depth
        bookkeeping resets, and readmission re-prefills prompt+generated
        into the LLM AND the SSM (``_prefill_phase`` feeds
        ``prefill_tokens``), after which served tokens are bit-identical
        to an unpreempted run for greedy and seeded sampling — the spec
        phases key every sample on the same (rid, token_index) fold the
        incremental paths use."""
        super().preempt(rid)
        req = self.requests[rid]
        req.pending_commit = []
        req.tree = []
        req.llm_committed = 0
        req.ssm_committed = 0
        req.ssm_backlog = []

    def _on_spec_flip(self, req: Request) -> None:
        """Runtime mode flip.  Enabling speculation mid-decode rebuilds
        the draft model's catch-up feed when it lags (``_ssm_sync``) —
        the SSM committed cache must hold every position before the next
        draft root, and a request that served non-spec rounds left it
        behind.  Disabling needs nothing — the row stops drafting at the
        next macro step and any pending commit flows through the next
        verify batch (or the incremental-path flush)."""
        if req.spec:
            self._ssm_sync(req)

    def _token_at(self, req: Request, p: int) -> int:
        """The logical token at sequence position ``p`` (prompt, then
        generated — the one layout every cache position maps to)."""
        return (req.prompt[p] if p < len(req.prompt)
                else req.generated[p - len(req.prompt)])

    def _combine_snaps(self, snap: Dict, snap_s: Dict, kv_l, kv_s) -> Dict:
        """Fold the draft allocator's snapshot into the target's: summed
        tokens/bytes/capacity, recomputed fracs, and the manager-held
        combined watermark — the peak of the SUMMED live stream (adding
        the two allocators' independent all-time peaks could overstate:
        they may peak at different ticks — and diverge from the ledger's
        own observe_live watermark over the same summed stream).  Any
        true observation may raise the watermark, so pure-read callers
        (``kv_snapshot``) share this safely."""
        for k in ("live_tokens", "live_bytes", "capacity_tokens",
                  "capacity_bytes", "headroom_bytes"):
            snap[k] += snap_s[k]
        self._kv_hwm_tokens = max(self._kv_hwm_tokens, snap["live_tokens"])
        self._kv_hwm_bytes = max(self._kv_hwm_bytes, snap["live_bytes"])
        snap["hwm_tokens"] = self._kv_hwm_tokens
        snap["hwm_bytes"] = self._kv_hwm_bytes
        snap["occupancy_frac"] = (
            snap["live_tokens"] / snap["capacity_tokens"]
            if snap["capacity_tokens"] else 0.0)
        reserved = (kv_l.live_requests() * kv_l.max_seq_len
                    + kv_s.live_requests() * kv_s.max_seq_len)
        snap["fragmentation_frac"] = (
            1.0 - snap["live_tokens"] / reserved if reserved else 0.0)
        return snap

    def kv_snapshot(self):
        kv_l = getattr(self.llm, "kv", None)
        kv_s = getattr(self.ssm, "kv", None)
        if kv_l is None or kv_s is None:
            return super().kv_snapshot()
        return self._combine_snaps(kv_l.snapshot(), kv_s.snapshot(),
                                   kv_l, kv_s)

    def _sync_kv(self) -> None:
        """Observe BOTH allocators (per-deployment peaks + watermarks)
        and publish ONE combined live view — summed tokens/bytes/
        capacity — so the occupancy/headroom gauges and the ledger
        watermark account the draft model's KV instead of under-reporting
        live HBM by its whole share."""
        kv_l = getattr(self.llm, "kv", None)
        kv_s = getattr(self.ssm, "kv", None)
        if kv_l is None or kv_s is None:
            return super()._sync_kv()
        live = [r for r in self._active()
                if r.status in (RequestStatus.PREFILLING,
                                RequestStatus.DECODING)]
        snap = self._combine_snaps(
            kv_l.observe({r.rid: r.seq_len for r in live}, None),
            kv_s.observe({r.rid: r.ssm_committed for r in live}, None),
            kv_l, kv_s)
        if self.telemetry.enabled:
            self.telemetry.kv_usage(snap)

    def _seq_len_needed(self, req: Request) -> int:
        # verification scores up to `depth` speculative positions past the
        # last committed token, so the cache needs headroom beyond max_new
        return len(req.prompt) + req.max_new_tokens + self.depth + 1

    # ------------------------------------------------------------------
    # phase A: prompt prefill (both models) + SSM catch-up
    # ------------------------------------------------------------------
    def _prefill_phase(self):
        self._admit()
        # LLM prefill for new requests (chunked by the LLM token budget).
        # The feed is ``prefill_tokens`` — the prompt, or prompt+generated
        # while recovering from preemption (recompute), exactly like the
        # incremental prefill paths.
        while True:
            toks, reqi, pos, points, spans = [], [], [], [], []
            budget = self.llm.max_tokens
            for req in self._active():
                if req.status is not RequestStatus.PREFILLING or budget <= 0:
                    continue
                feed = req.prefill_tokens
                take = min(budget, len(feed) - req.prefill_offset)
                st = req.prefill_offset
                toks += feed[st : st + take]
                reqi += [req.slot] * take
                pos += list(range(st, st + take))
                if take:
                    spans.append((req.rid, st, st + take))
                req.prefill_offset += take
                budget -= take
                if req.prefill_offset == len(feed):
                    points.append((len(toks) - 1, req.rid))
            if not toks:
                break
            self._kv_prepare(spans)
            self._prof_account(spans)
            bc = self._plain_bc(self.llm, toks, reqi, pos)
            # per-request (rid, token_index) sample folds so the first
            # generated token (read off the last fed position's logits) is
            # bit-identical to the incremental loop's — for fresh prompts
            # AND recompute re-prefills.  All phase dispatches run under
            # the retry guard; the fold schedule is deterministic, so a
            # retried dispatch replays the identical step.
            smp = self._sample_for(points, self.llm.max_tokens)
            result = self._guarded(
                "spec_prefill",
                lambda b=bc, s=smp: self.llm.step(b, sample=s))
            if result is None:
                return
            self.llm_steps += 1
            with self.profiler.phase("readback"):
                ids = np.asarray(result.token_ids)
            self.profiler.host_sync()
            for flat, rid in points:
                req = self.requests[rid]
                if req.status is not RequestStatus.PREFILLING:
                    continue  # left the slot between build and readback
                req.status = RequestStatus.DECODING
                req.llm_committed = len(req.prefill_tokens)
                self._append_token(req, int(ids[flat]))
                self._maybe_finish(req)

        # SSM prefill (prompt / recompute feed) + catch-up (tokens accepted
        # by previous rounds).  Non-spec rows skip the draft model entirely
        # — their SSM cache rebuilds from scratch on a later flip-on or
        # activation (``_ssm_sync``).
        for req in self._active():
            if req.spec:
                # a row may reach the macro path with a lagging SSM side
                # (flip-on, or incremental-path ticks before activation)
                self._ssm_sync(req)
        while True:
            toks, reqi, pos, spans = [], [], [], []
            budget = self.ssm.max_tokens
            for req in self._active():
                if budget <= 0:
                    break
                if not req.spec:
                    continue
                lo = len(pos)
                feed = req.prefill_tokens
                if req.ssm_committed < len(feed):
                    take = min(budget, len(feed) - req.ssm_committed)
                    st = req.ssm_committed
                    toks += feed[st : st + take]
                    reqi += [req.slot] * take
                    pos += list(range(st, st + take))
                    req.ssm_committed += take
                    budget -= take
                if req.ssm_backlog and budget > 0:
                    take = min(budget, len(req.ssm_backlog))
                    for t, p in req.ssm_backlog[:take]:
                        toks.append(t)
                        reqi.append(req.slot)
                        pos.append(p)
                    req.ssm_backlog = req.ssm_backlog[take:]
                    req.ssm_committed += take
                    budget -= take
                if len(pos) > lo:
                    spans.append((req.rid, min(pos[lo:]),
                                  max(pos[lo:]) + 1))
            if not toks:
                break
            self._kv_prepare(spans, kv=getattr(self.ssm, "kv", None))
            self._prof_account(spans, im=self.ssm)
            bc = self._plain_bc(self.ssm, toks, reqi, pos)
            if self._guarded("spec_ssm_prefill",
                             lambda b=bc: self.ssm.step(b)) is None:
                return

    def _plain_bc(self, im, toks, reqi, pos):
        seq_lens = np.zeros(im.max_requests, np.int32)
        for req in self._active():
            seq_lens[req.slot] = req.seq_len
        return BatchConfig.build(
            toks, reqi, pos, seq_lens,
            max_tokens=im.max_tokens, max_requests=im.max_requests,
        )

    # ------------------------------------------------------------------
    # phase B: draft-tree expansion through the SSM
    # ------------------------------------------------------------------
    def _draft_phase(self) -> List[SpecRequest]:
        """Build every DECODING request's speculation tree for this round.

        Spec-mode rows expand ``depth`` beam levels through the SSM;
        non-spec rows get a degenerate ROOT-ONLY tree (their decode token)
        — the mixed-batch lever: both populations then verify in ONE
        LLM step (:meth:`_verify_phase`), spec rows multi-token, plain
        rows one token.  Returns the full verifying list."""
        decoding = [r for r in self._active()
                    if r.status is RequestStatus.DECODING]
        if not decoding:
            return []
        P = self.ssm.max_spec_tokens
        R = self.ssm.max_requests
        masks = np.zeros((R, P, P), bool)
        for req in decoding:
            # macro-boundary invariant: the LLM's committed depth is the
            # cache prefix before the root (= seq_len - 1).  A row that
            # served incremental ticks (all-plain phases) advanced its
            # cache without this bookkeeping — resync is a no-op for rows
            # in continuous speculative service.
            req.llm_committed = req.seq_len - 1
            req.tree = [TokenTreeNode(req.generated[-1], -1, 0, 0.0)]
            masks[req.slot, 0, 0] = True

        drafting = [r for r in decoding if r.spec]
        if not drafting:
            return decoding
        frontier = {req.rid: [0] for req in drafting}  # node indices at depth d
        # feeding depth-d nodes yields depth-(d+1) children; final-depth nodes
        # are never fed (their KV is only needed by the LLM's verify pass)
        for d in range(self.depth):
            toks, reqi, pos, spec, points = [], [], [], [], []
            for req in drafting:
                for ni in frontier.get(req.rid, []):
                    node = req.tree[ni]
                    toks.append(node.token)
                    reqi.append(req.slot)
                    pos.append(req.llm_committed + node.depth)
                    spec.append(ni)
                    points.append((len(toks) - 1, req.rid, ni))
            if not toks:
                break
            bc = self._tree_bc(
                TreeSearchBatchConfig, self.ssm, toks, reqi, pos, spec, masks,
                committed_attr="ssm_committed",
            )
            prof = self.profiler
            if prof.enabled and toks:
                per: Dict[int, int] = {}
                for _, rid, _ni in points:
                    per[rid] = per.get(rid, 0) + 1
                prof.account(
                    prof.card_for(self.ssm),
                    [(rid, c, self.requests[rid].seq_len)
                     for rid, c in per.items()])
            result = self._guarded("spec_draft",
                                   lambda b=bc: self.ssm.step(b))
            if result is None:
                return []
            with prof.phase("readback"):
                topk_ids = np.asarray(result.topk_ids)
                topk_lp = np.asarray(result.topk_logprobs)
            prof.host_sync()
            # beam-select the next frontier per request
            for req in drafting:
                cands = []
                for flat, rid, ni in points:
                    if rid != req.rid:
                        continue
                    base_lp = req.tree[ni].logprob
                    for j in range(self.width):
                        cands.append(
                            (base_lp + float(topk_lp[flat, j]),
                             int(topk_ids[flat, j]), ni)
                        )
                cands.sort(reverse=True)
                nxt = []
                for lp, tok, parent in cands[: self.width]:
                    if len(req.tree) >= self.max_tree:
                        break
                    idx = len(req.tree)
                    req.tree.append(
                        TokenTreeNode(tok, parent, req.tree[parent].depth + 1, lp)
                    )
                    # ancestor mask row = parent's row + self
                    masks[req.slot, idx] = masks[req.slot, parent]
                    masks[req.slot, idx, idx] = True
                    nxt.append(idx)
                frontier[req.rid] = nxt
        return decoding

    def _tree_bc(self, cls, im, toks, reqi, pos, spec, masks, committed_attr,
                 commit=None):
        seq_lens = np.zeros(im.max_requests, np.int32)
        committed = np.zeros(im.max_requests, np.int32)
        for req in self._active():
            seq_lens[req.slot] = req.seq_len
            committed[req.slot] = getattr(req, committed_attr)
        base = BatchConfig.build(
            toks, reqi, pos, seq_lens,
            max_tokens=im.max_tokens, max_requests=im.max_requests,
        )
        import jax.numpy as jnp

        P = im.max_spec_tokens
        si = np.zeros(im.max_tokens, np.int32)
        si[: len(spec)] = spec
        kw = dict(
            base=base,
            spec_index=jnp.asarray(si),
            ancestor_mask=jnp.asarray(masks[:, :P, :P]),
            committed_lens=jnp.asarray(committed),
        )
        if cls is TreeVerifyBatchConfig:
            n = im.max_tokens
            cri = np.full(n, -1, np.int32)
            csi = np.zeros(n, np.int32)
            cdp = np.zeros(n, np.int32)
            commit = commit or []
            for i, (slot, src, dst) in enumerate(commit):
                cri[i], csi[i], cdp[i] = slot, src, dst
            kw.update(
                commit_request_index=jnp.asarray(cri),
                commit_src_spec_index=jnp.asarray(csi),
                commit_dst_position=jnp.asarray(cdp),
            )
        return cls(**kw)

    # ------------------------------------------------------------------
    # phase C: LLM tree verification + accept walk
    # ------------------------------------------------------------------
    def _verify_phase(self, verifying: List[SpecRequest]):
        """ONE batched LLM step over every decoding row's tree — the
        mixed macro-step: spec rows ship their whole draft tree (verify
        multi-token), plain rows ship a root-only tree (decode one
        token).  The accept walk + commit bookkeeping are identical for
        both; a root-only tree trivially accepts zero children and emits
        the bonus token."""
        if not verifying:
            return
        tel = self.telemetry
        R = self.llm.max_requests
        P = self.llm.max_spec_tokens
        masks = np.zeros((R, P, P), bool)
        toks, reqi, pos, spec, index_of = [], [], [], [], {}
        commit, spans = [], []
        for req in verifying:
            for ni, node in enumerate(req.tree):
                masks[req.slot, ni, ni] = True
                if node.parent >= 0:
                    masks[req.slot, ni] |= masks[req.slot, node.parent]
                    masks[req.slot, ni, ni] = True
                index_of[(req.rid, ni)] = len(toks)
                toks.append(node.token)
                reqi.append(req.slot)
                pos.append(req.llm_committed + node.depth)
                spec.append(ni)
            for src, dst in req.pending_commit:
                commit.append((req.slot, src, dst))
            if req.pending_commit:
                # the commit descriptor writes accepted KV into the
                # committed cache at these positions (the spec-tree buffer
                # itself is never paged)
                dsts = [d for _, d in req.pending_commit]
                spans.append((req.rid, min(dsts), max(dsts) + 1))
            req.pending_commit = []
        self._kv_prepare(spans)
        bc = self._tree_bc(
            TreeVerifyBatchConfig, self.llm, toks, reqi, pos, spec, masks,
            committed_attr="llm_committed", commit=commit,
        )
        # stochastic verification: with temperature > 0 the verify step
        # SAMPLES y ~ p(target | node prefix) per tree node (seeded,
        # top-p) and the walk accepts a child iff its token equals y.
        # Each row's key folds (rid, generated-token index): a node at
        # tree depth d samples index len(generated)+d — the SAME key the
        # incremental loop would use for that token, so sampled spec
        # output is BIT-IDENTICAL to sampled incremental decoding (not
        # merely distribution-equal), which is what the mixed-batch and
        # recompute bit-identity contracts rest on.  T<=0 keeps the
        # exact-greedy walk.
        smp = self._verify_sample(verifying, index_of)
        prof = self.profiler
        if prof.enabled:
            # one verify macro-step: each row ships its whole tree (a
            # root-only tree for plain rows) and reads its live prefix
            prof.account(
                prof.card_for(self.llm),
                [(r.rid, len(r.tree), r.seq_len) for r in verifying])
        n_spec = sum(1 for r in verifying if len(r.tree) > 1)
        n_plain = len(verifying) - n_spec
        if tel.enabled:
            tel.spec_batch_mix(n_spec, n_plain)
        with tel.span("spec_verify_round", cat="spec", track="spec",
                      n_spec=n_spec, n_plain=n_plain,
                      tree_tokens=len(toks)):
            result = self._guarded(
                "spec_verify", lambda: self.llm.step(bc, sample=smp))
        if result is None:
            return
        self.llm_steps += 1
        with prof.phase("readback"):
            ids = np.asarray(result.token_ids)
        prof.host_sync()

        for req in verifying:
            if req.status is not RequestStatus.DECODING:
                # the request left its slot between list build and
                # readback (page-pressure preemption inside _kv_prepare
                # resets its tree; a lifecycle reap can't land here, but
                # the guard is status-based like _prefill_phase's): its
                # verify rows are dead — the readmission recomputes, and
                # walking the reset tree would index an empty list
                continue
            # accept walk from the root (greedy or vs the sampled tokens)
            ni = 0
            accepted_nodes = [0]
            while True:
                want = int(ids[index_of[(req.rid, ni)]])
                child = next(
                    (
                        j
                        for j, n in enumerate(req.tree)
                        if n.parent == ni and n.token == want
                    ),
                    None,
                )
                if child is None:
                    bonus = want
                    break
                accepted_nodes.append(child)
                ni = child
            # commit root + accepted draft nodes next round; emit their tokens
            new_tokens = []
            for k, node_idx in enumerate(accepted_nodes):
                node = req.tree[node_idx]
                posn = req.llm_committed + node.depth
                req.pending_commit.append((node_idx, posn))
                if k > 0:  # root token was already in req.generated
                    new_tokens.append(node.token)
            new_tokens.append(bonus)
            req.llm_committed += len(accepted_nodes)
            # acceptance telemetry: draft tokens that survived the walk
            # this round (the root is committed context, not a draft) —
            # feeds the workload profile's spec_acceptance histogram so
            # acceptance-rate drift is visible to the planner
            if self.telemetry.enabled and len(req.tree) > 1:
                self.telemetry.spec_acceptance(
                    len(accepted_nodes) - 1, len(req.tree) - 1)
            # SSM needs the same accepted tokens in its committed cache;
            # the root (generated[-1] pre-walk) is part of them.  Plain
            # rows skip the draft model entirely — a later flip-on
            # rebuilds the feed from scratch (``_on_spec_flip``), so
            # their backlog must not accumulate unconsumed entries.
            if req.spec:
                base_pos = req.ssm_committed + len(req.ssm_backlog)
                acc_toks = [req.tree[i].token for i in accepted_nodes]
                req.ssm_backlog += [
                    (t, base_pos + k) for k, t in enumerate(acc_toks)
                ]
            for t in new_tokens:
                self._append_token(req, t)
                self._maybe_finish(req)
                if req.status is RequestStatus.COMPLETED:
                    break

    def _verify_sample(self, verifying: List[SpecRequest], index_of):
        """Per-row sampling arg for the verify step: row ``index_of[(rid,
        ni)]`` folds ``(rid, len(generated) + depth(ni))`` — the exact key
        the incremental loop uses for that generated-token index, so
        sampled speculative output is bit-identical to sampled incremental
        decoding (rows of non-verifying slots draw from the (0, 0) fold
        and are discarded).  Assembled by the ONE ``_sample_for`` path
        (the tree depth rides the per-point index offset).  None for
        greedy — checked HERE too so the point list (which indexes each
        row's tree) is never built eagerly; rows whose request left
        DECODING between list build and this call (page-pressure
        preemption in ``_kv_prepare`` resets the tree) are skipped like
        the accept walk skips them."""
        if self.gen.temperature <= 0.0:
            return None
        return self._sample_for(
            [(row, rid, self.requests[rid].tree[ni].depth)
             for (rid, ni), row in index_of.items()
             if self.requests[rid].status is RequestStatus.DECODING],
            self.llm.max_tokens)

    # ------------------------------------------------------------------
    # the spec-aware tick: mixed macro-step, or the incremental fast path
    # ------------------------------------------------------------------
    def _spec_live(self) -> bool:
        """Any ACTIVE (slotted) request in spec mode — the per-tick
        dispatch decision.  Deliberately ignores the pending queue: a
        spec arrival stuck behind a full house of plain decoders must not
        force everyone onto the macro-step path (1 token/row/dispatch)
        while it waits — the incremental fast path keeps serving, the
        arrival admits through it, and the NEXT tick's check sees the
        active spec row (its SSM cache lazily resyncs via
        :meth:`_ssm_sync`, so incremental prefill/decode ticks before
        activation are fine)."""
        return any(r.spec for r in self._active())

    def _ssm_sync(self, req: SpecRequest) -> None:
        """Ensure the draft model's catch-up feed covers every position
        before the next draft root (``seq_len - 1``).  A spec-mode row
        can reach the macro path with a LAGGING SSM side — runtime
        flip-on, or LLM prefill/decode ticks served by the incremental
        fast path while the row waited to activate — in which case the
        feed rebuilds from scratch (value-deterministic overwrite).
        Steady-state rows (committed + backlog already reach the root)
        are untouched."""
        if req.status is not RequestStatus.DECODING:
            return
        want = req.seq_len - 1
        if req.ssm_committed + len(req.ssm_backlog) >= want:
            return
        req.ssm_committed = 0
        req.ssm_backlog = [
            (self._token_at(req, p), p)
            for p in range(len(req.prefill_tokens), want)
        ]

    def _flush_commits(self) -> bool:
        """Exit-speculation commit flush: accepted-but-uncommitted tokens
        (``pending_commit``) normally reach the committed cache through
        the NEXT verify step's commit descriptor — when the tick degrades
        to the incremental path (no live spec request) there is no next
        verify step, so the pending positions are re-fed as one plain
        batch instead (KV writes are value-deterministic, so recomputing
        them equals the descriptor's spec-buffer copy bit-for-bit).  The
        incremental step that follows then sees the complete cache
        prefix.  Runs only at speculative→incremental transitions.

        Returns whether the flush COMPLETED: a dispatch fault past the
        retry budget requeues/fails only the rows in the failed batch,
        but rows budget-deferred to a later inner batch still hold
        un-flushed commits — the caller must not run an incremental step
        over their incomplete cache prefix (the next tick retries)."""
        flush = [r for r in self._active()
                 if r.status is RequestStatus.DECODING and r.pending_commit]
        if not flush:
            return True
        while True:
            toks, reqi, pos, spans = [], [], [], []
            budget = self.llm.max_tokens
            for req in flush:
                if not req.pending_commit or budget <= 0:
                    continue
                take = min(budget, len(req.pending_commit))
                part = req.pending_commit[:take]
                req.pending_commit = req.pending_commit[take:]
                for _, dst in part:
                    toks.append(self._token_at(req, dst))
                    reqi.append(req.slot)
                    pos.append(dst)
                dsts = [d for _, d in part]
                spans.append((req.rid, min(dsts), max(dsts) + 1))
                budget -= take
            if not toks:
                break
            self._kv_prepare(spans)
            self._prof_account(spans)
            bc = self._plain_bc(self.llm, toks, reqi, pos)
            # a flush fault past the retry budget affects only the rows
            # actually IN the failed batch (a budget-limited flush may
            # have deferred other rows to a later inner batch)
            if self._guarded("spec_commit_flush",
                             lambda b=bc: self.llm.step(b),
                             affected_fn=lambda b=bc:
                             self._rids_in_batch(b)) is None:
                return False
            self.llm_steps += 1
        return True

    def flush_pending_commits(self) -> bool:
        """Public drain hook (serve/migration.py): commit every
        accepted-but-uncommitted token into the LLM cache NOW, so a
        migration drain's grace window runs over a complete cache prefix
        (the requests it then preempts recompute from scratch anyway —
        their pending commits reset in :meth:`preempt` — but rows that
        COMPLETE during the grace window must not finish on a cache
        missing their accepted tail).  Same semantics as the
        speculative→incremental transition flush."""
        return self._flush_commits()

    def _tick(self) -> None:
        """One serving tick: a mixed speculative macro-step while any
        live request is in spec mode (plain rows ride the same verify
        batch as root-only trees), otherwise — after flushing any
        pending spec commits — the inherited incremental fast path
        (decode stretches/scans included), so an all-plain population
        never pays the macro-step overhead.  Lifecycle reaping, KV sync,
        and plan-health polling stay in the shared serve loops
        (``serve_incr_decoding`` / ``serve_with_arrivals``), so
        deadlines/TTL/cancel land at spec macro-step boundaries exactly
        like the incremental loop's step boundaries."""
        if self._spec_live():
            with self.telemetry.span("spec_macro_step", cat="spec",
                                     track="spec"):
                self._prefill_phase()
                verifying = self._draft_phase()
                self._verify_phase(verifying)
            self.macro_steps += 1
        else:
            if self._flush_commits():
                self._serve_tick()

    # ------------------------------------------------------------------
    def serve_spec_infer(self) -> Dict[int, List[int]]:
        """Reference: ``RequestManager::serve_spec_infer``.

        Now literally the inherited serve loop: the spec-aware
        :meth:`_tick` is the only specialization, so cancellations,
        deadline expiries, admission control, and plan-health polling are
        ONE implementation across incremental and speculative serving —
        reaped at macro-step boundaries (the speculative analogue of the
        incremental loop's step-boundary checks)."""
        return self.serve_incr_decoding()

    _serve = serve_spec_infer
