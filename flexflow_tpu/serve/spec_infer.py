"""SpecInfer: tree-based speculative decoding (SSM draft + LLM verify).

Reference: ``RequestManager::serve_spec_infer`` / ``prepare_next_batch_beam`` /
``prepare_next_batch_verify`` in ``src/runtime/request_manager.cc`` and the
SpecInfer ASPLOS'24 design: a small draft model (SSM) expands a token TREE per
request; the LLM verifies the whole tree in ONE batched step using
tree-topology causal attention; the longest root-path whose tokens match the
LLM's own greedy choices is committed, plus one "bonus" token from the LLM —
so each LLM pass can commit up to depth+1 tokens.

Per macro-step, per request (host bookkeeping; device work is 4 jitted
programs total — SSM inc/tree-search, LLM inc/tree-verify):

1. *catch-up*   — feed tokens accepted last round into the SSM's committed
   cache (plain ``BatchConfig``; the LLM's copies are committed via the
   verify step's commit descriptor instead, reusing KV computed during
   verification).
2. *draft*      — root = latest token; ``depth`` beam-expansion steps of
   width ``width`` through the SSM (``TreeSearchBatchConfig``), keeping
   per-node cumulative logprobs; nodes live in the spec KV buffer.
3. *verify*     — flatten the tree into one ``TreeVerifyBatchConfig`` step of
   the LLM (commit descriptor carries last round's accepted nodes); walk the
   result greedily root-down to find the accepted path + bonus token.

Greedy invariant (tested): output sequences are EXACTLY those of plain
incremental decoding with the LLM, for any draft model.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .batch_config import (
    BatchConfig,
    TreeSearchBatchConfig,
    TreeVerifyBatchConfig,
)
from .inference_manager import InferenceManager
from .request_manager import (
    GenerationConfig,
    Request,
    RequestManager,
    RequestStatus,
)


@dataclasses.dataclass
class TokenTreeNode:
    token: int
    parent: int          # index into the tree's node list (-1 for root)
    depth: int
    logprob: float = 0.0  # cumulative draft logprob along the root path


@dataclasses.dataclass
class SpecRequest(Request):
    """Request + speculation bookkeeping."""

    # accepted-but-not-yet-committed (spec_index, position, token) triples;
    # committed into the LLM cache by the NEXT verify step's commit descriptor
    pending_commit: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    llm_committed: int = 0   # LLM cache depth
    ssm_committed: int = 0   # SSM cache depth
    ssm_backlog: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    tree: List[TokenTreeNode] = dataclasses.field(default_factory=list)


class SpecInferManager(RequestManager):
    """Drives speculative serving over two InferenceManagers (SSM + LLM).

    Queue/admission/stopping logic is inherited from :class:`RequestManager`
    (so incremental and speculative serving can never diverge on lifecycle
    semantics); this class replaces the per-step loop with the three-phase
    macro step.  ``width``/``depth`` bound each request's tree to
    ``1 + width*depth`` nodes; all capacities are validated up front.
    """

    request_cls = SpecRequest
    # dispatch failures past the retry budget go terminal: the three-phase
    # macro step's committed-depth bookkeeping has no recompute path
    supports_recompute = False

    def __init__(
        self,
        llm: InferenceManager,
        ssm: InferenceManager,
        gen_config: Optional[GenerationConfig] = None,
        width: int = 2,
        depth: int = 3,
        telemetry=None,
        resilience=None,
        fault_injector=None,
        clock=None,
        plan_health=None,
    ):
        super().__init__(llm, gen_config, telemetry=telemetry,
                         resilience=resilience,
                         fault_injector=fault_injector, clock=clock,
                         plan_health=plan_health)
        if self.res.preemption:
            # recompute-based preemption needs the incremental prefill
            # paths (prefill_src); the spec macro-step's three-phase cache
            # bookkeeping (llm/ssm committed depths) has no recompute story
            raise ValueError(
                "ResilienceConfig.preemption is not supported by "
                "SpecInferManager (recovery is recompute-based and only "
                "the incremental serving paths recompute)")
        self.llm = llm
        self.ssm = ssm
        self.width = width
        self.depth = depth
        self.max_tree = 1 + width * depth
        if llm.max_spec_tokens < self.max_tree or ssm.max_spec_tokens < self.max_tree:
            raise ValueError(
                f"spec buffers too small: need {self.max_tree} slots, have "
                f"llm={llm.max_spec_tokens} ssm={ssm.max_spec_tokens}"
            )
        if llm.max_requests != ssm.max_requests:
            raise ValueError("LLM and SSM must agree on max_requests")
        if llm.max_tokens < llm.max_requests * self.max_tree:
            raise ValueError(
                "LLM max_tokens_per_batch must fit max_requests full trees "
                f"({llm.max_requests}x{self.max_tree})"
            )
        if ssm.max_tokens < ssm.max_requests * width:
            raise ValueError(
                "SSM max_tokens_per_batch must fit one frontier per request "
                f"({ssm.max_requests}x{width})"
            )
        if ssm.topk < width:
            raise ValueError(f"SSM InferenceManager needs topk >= width ({width})")
        self.macro_steps = 0
        self.llm_steps = 0
        self._kv_hwm_tokens = 0    # combined (target + draft) watermark
        self._kv_hwm_bytes = 0.0
        # the draft model is a co-resident deployment: its params + KV
        # buffers are REAL HBM, so its allocator joins the attribution
        # protocol (reset like the target's in RequestManager.__init__)
        # and its predicted-vs-allocated record lands in the memory
        # ledger under its own "_draft" plan key — same tp/pp shape as
        # the target must not collide with the target's record
        kv_s = getattr(ssm, "kv", None)
        if kv_s is not None:
            kv_s.reset_attribution()
            # the base __init__ auto-wired the plan-health monitor to the
            # TARGET allocator; widen the auto-wiring to both caches so
            # the OOM projection covers the draft's growth too (an
            # explicitly-provided allocator is the caller's choice)
            kv_l = getattr(llm, "kv", None)
            if (self.plan_health is not None and kv_l is not None
                    and self.plan_health.kv_allocator is kv_l):
                self.plan_health.kv_allocator = [kv_l, kv_s]
        if self.telemetry.enabled and hasattr(ssm, "publish_memory"):
            ssm.publish_memory(self.telemetry,
                               key=ssm.plan_key + "_draft")

    # ------------------------------------------------------------------
    # memory observability over TWO deployments (target + draft)
    # ------------------------------------------------------------------
    def _kv_bind(self, rid: int) -> None:
        # the target allocator gets the full prefix-reuse bind (the LLM
        # prompt prefill consumes the cached offset); the draft cache
        # binds attribution + slot only — its pages map on demand through
        # the ssm-side prepare spans, no prefix chain (the catch-up feed
        # is committed-depth-driven, not offset-driven)
        super()._kv_bind(rid)
        kv_s = getattr(self.ssm, "kv", None)
        if kv_s is not None:
            kv_s.bind(rid, slot=self.requests[rid].slot)

    def _release_slot(self, req: Request) -> None:
        if req.slot < 0:
            return
        # draft share first (super() clears req.slot); spec serving has
        # no preemption, so a request binds exactly once and the target
        # (max-stamped by super) + draft shares sum exactly
        kv_s = getattr(self.ssm, "kv", None)
        draft = (kv_s.release(req.rid, tokens=req.ssm_committed)
                 if kv_s is not None else 0.0)
        super()._release_slot(req)
        req.kv_bytes += draft

    def _combine_snaps(self, snap: Dict, snap_s: Dict, kv_l, kv_s) -> Dict:
        """Fold the draft allocator's snapshot into the target's: summed
        tokens/bytes/capacity, recomputed fracs, and the manager-held
        combined watermark — the peak of the SUMMED live stream (adding
        the two allocators' independent all-time peaks could overstate:
        they may peak at different ticks — and diverge from the ledger's
        own observe_live watermark over the same summed stream).  Any
        true observation may raise the watermark, so pure-read callers
        (``kv_snapshot``) share this safely."""
        for k in ("live_tokens", "live_bytes", "capacity_tokens",
                  "capacity_bytes", "headroom_bytes"):
            snap[k] += snap_s[k]
        self._kv_hwm_tokens = max(self._kv_hwm_tokens, snap["live_tokens"])
        self._kv_hwm_bytes = max(self._kv_hwm_bytes, snap["live_bytes"])
        snap["hwm_tokens"] = self._kv_hwm_tokens
        snap["hwm_bytes"] = self._kv_hwm_bytes
        snap["occupancy_frac"] = (
            snap["live_tokens"] / snap["capacity_tokens"]
            if snap["capacity_tokens"] else 0.0)
        reserved = (kv_l.live_requests() * kv_l.max_seq_len
                    + kv_s.live_requests() * kv_s.max_seq_len)
        snap["fragmentation_frac"] = (
            1.0 - snap["live_tokens"] / reserved if reserved else 0.0)
        return snap

    def kv_snapshot(self):
        kv_l = getattr(self.llm, "kv", None)
        kv_s = getattr(self.ssm, "kv", None)
        if kv_l is None or kv_s is None:
            return super().kv_snapshot()
        return self._combine_snaps(kv_l.snapshot(), kv_s.snapshot(),
                                   kv_l, kv_s)

    def _sync_kv(self) -> None:
        """Observe BOTH allocators (per-deployment peaks + watermarks)
        and publish ONE combined live view — summed tokens/bytes/
        capacity — so the occupancy/headroom gauges and the ledger
        watermark account the draft model's KV instead of under-reporting
        live HBM by its whole share."""
        kv_l = getattr(self.llm, "kv", None)
        kv_s = getattr(self.ssm, "kv", None)
        if kv_l is None or kv_s is None:
            return super()._sync_kv()
        live = [r for r in self._active()
                if r.status in (RequestStatus.PREFILLING,
                                RequestStatus.DECODING)]
        snap = self._combine_snaps(
            kv_l.observe({r.rid: r.seq_len for r in live}, None),
            kv_s.observe({r.rid: r.ssm_committed for r in live}, None),
            kv_l, kv_s)
        if self.telemetry.enabled:
            self.telemetry.kv_usage(snap)

    def _seq_len_needed(self, req: Request) -> int:
        # verification scores up to `depth` speculative positions past the
        # last committed token, so the cache needs headroom beyond max_new
        return len(req.prompt) + req.max_new_tokens + self.depth + 1

    # ------------------------------------------------------------------
    # phase A: prompt prefill (both models) + SSM catch-up
    # ------------------------------------------------------------------
    def _prefill_phase(self):
        self._admit()
        # LLM prefill for new requests (chunked by the LLM token budget)
        while True:
            toks, reqi, pos, points, spans = [], [], [], [], []
            budget = self.llm.max_tokens
            for req in self._active():
                if req.status is not RequestStatus.PREFILLING or budget <= 0:
                    continue
                take = min(budget, len(req.prompt) - req.prefill_offset)
                st = req.prefill_offset
                toks += req.prompt[st : st + take]
                reqi += [req.slot] * take
                pos += list(range(st, st + take))
                if take:
                    spans.append((req.rid, st, st + take))
                req.prefill_offset += take
                budget -= take
                if req.prefill_offset == len(req.prompt):
                    points.append((len(toks) - 1, req.rid))
            if not toks:
                break
            self._kv_prepare(spans)
            bc = self._plain_bc(self.llm, toks, reqi, pos)
            # sample arg so the first generated token (read off the last
            # prompt position's logits) honors temperature/top_p.  All
            # phase dispatches run under the retry guard: a fault past the
            # budget fails only the in-flight requests (no recompute here).
            # The sample key is drawn ONCE outside the guard so a retried
            # dispatch replays the identical step.
            smp = self._sample_arg()
            result = self._guarded(
                "spec_prefill",
                lambda b=bc, s=smp: self.llm.step(b, sample=s))
            if result is None:
                return
            self.llm_steps += 1
            ids = np.asarray(result.token_ids)
            for flat, rid in points:
                req = self.requests[rid]
                req.status = RequestStatus.DECODING
                req.llm_committed = len(req.prompt)
                self._append_token(req, int(ids[flat]))
                self._maybe_finish(req)

        # SSM prefill (prompt) + catch-up (tokens accepted by previous rounds)
        while True:
            toks, reqi, pos, spans = [], [], [], []
            budget = self.ssm.max_tokens
            for req in self._active():
                if budget <= 0:
                    break
                lo = len(pos)
                if req.ssm_committed < len(req.prompt):
                    take = min(budget, len(req.prompt) - req.ssm_committed)
                    st = req.ssm_committed
                    toks += req.prompt[st : st + take]
                    reqi += [req.slot] * take
                    pos += list(range(st, st + take))
                    req.ssm_committed += take
                    budget -= take
                if req.ssm_backlog and budget > 0:
                    take = min(budget, len(req.ssm_backlog))
                    for t, p in req.ssm_backlog[:take]:
                        toks.append(t)
                        reqi.append(req.slot)
                        pos.append(p)
                    req.ssm_backlog = req.ssm_backlog[take:]
                    req.ssm_committed += take
                    budget -= take
                if len(pos) > lo:
                    spans.append((req.rid, min(pos[lo:]),
                                  max(pos[lo:]) + 1))
            if not toks:
                break
            self._kv_prepare(spans, kv=getattr(self.ssm, "kv", None))
            bc = self._plain_bc(self.ssm, toks, reqi, pos)
            if self._guarded("spec_ssm_prefill",
                             lambda b=bc: self.ssm.step(b)) is None:
                return

    def _plain_bc(self, im, toks, reqi, pos):
        seq_lens = np.zeros(im.max_requests, np.int32)
        for req in self._active():
            seq_lens[req.slot] = req.seq_len
        return BatchConfig.build(
            toks, reqi, pos, seq_lens,
            max_tokens=im.max_tokens, max_requests=im.max_requests,
        )

    # ------------------------------------------------------------------
    # phase B: draft-tree expansion through the SSM
    # ------------------------------------------------------------------
    def _draft_phase(self) -> List[SpecRequest]:
        drafting = [r for r in self._active() if r.status is RequestStatus.DECODING]
        if not drafting:
            return []
        P = self.ssm.max_spec_tokens
        R = self.ssm.max_requests
        masks = np.zeros((R, P, P), bool)
        for req in drafting:
            req.tree = [TokenTreeNode(req.generated[-1], -1, 0, 0.0)]
            masks[req.slot, 0, 0] = True

        frontier = {req.rid: [0] for req in drafting}  # node indices at depth d
        # feeding depth-d nodes yields depth-(d+1) children; final-depth nodes
        # are never fed (their KV is only needed by the LLM's verify pass)
        for d in range(self.depth):
            toks, reqi, pos, spec, points = [], [], [], [], []
            for req in drafting:
                for ni in frontier.get(req.rid, []):
                    node = req.tree[ni]
                    toks.append(node.token)
                    reqi.append(req.slot)
                    pos.append(req.llm_committed + node.depth)
                    spec.append(ni)
                    points.append((len(toks) - 1, req.rid, ni))
            if not toks:
                break
            bc = self._tree_bc(
                TreeSearchBatchConfig, self.ssm, toks, reqi, pos, spec, masks,
                committed_attr="ssm_committed",
            )
            result = self._guarded("spec_draft",
                                   lambda b=bc: self.ssm.step(b))
            if result is None:
                return []
            topk_ids = np.asarray(result.topk_ids)
            topk_lp = np.asarray(result.topk_logprobs)
            # beam-select the next frontier per request
            for req in drafting:
                cands = []
                for flat, rid, ni in points:
                    if rid != req.rid:
                        continue
                    base_lp = req.tree[ni].logprob
                    for j in range(self.width):
                        cands.append(
                            (base_lp + float(topk_lp[flat, j]),
                             int(topk_ids[flat, j]), ni)
                        )
                cands.sort(reverse=True)
                nxt = []
                for lp, tok, parent in cands[: self.width]:
                    if len(req.tree) >= self.max_tree:
                        break
                    idx = len(req.tree)
                    req.tree.append(
                        TokenTreeNode(tok, parent, req.tree[parent].depth + 1, lp)
                    )
                    # ancestor mask row = parent's row + self
                    masks[req.slot, idx] = masks[req.slot, parent]
                    masks[req.slot, idx, idx] = True
                    nxt.append(idx)
                frontier[req.rid] = nxt
        return drafting

    def _tree_bc(self, cls, im, toks, reqi, pos, spec, masks, committed_attr,
                 commit=None):
        seq_lens = np.zeros(im.max_requests, np.int32)
        committed = np.zeros(im.max_requests, np.int32)
        for req in self._active():
            seq_lens[req.slot] = req.seq_len
            committed[req.slot] = getattr(req, committed_attr)
        base = BatchConfig.build(
            toks, reqi, pos, seq_lens,
            max_tokens=im.max_tokens, max_requests=im.max_requests,
        )
        import jax.numpy as jnp

        P = im.max_spec_tokens
        si = np.zeros(im.max_tokens, np.int32)
        si[: len(spec)] = spec
        kw = dict(
            base=base,
            spec_index=jnp.asarray(si),
            ancestor_mask=jnp.asarray(masks[:, :P, :P]),
            committed_lens=jnp.asarray(committed),
        )
        if cls is TreeVerifyBatchConfig:
            n = im.max_tokens
            cri = np.full(n, -1, np.int32)
            csi = np.zeros(n, np.int32)
            cdp = np.zeros(n, np.int32)
            commit = commit or []
            for i, (slot, src, dst) in enumerate(commit):
                cri[i], csi[i], cdp[i] = slot, src, dst
            kw.update(
                commit_request_index=jnp.asarray(cri),
                commit_src_spec_index=jnp.asarray(csi),
                commit_dst_position=jnp.asarray(cdp),
            )
        return cls(**kw)

    # ------------------------------------------------------------------
    # phase C: LLM tree verification + accept walk
    # ------------------------------------------------------------------
    def _verify_phase(self, drafting: List[SpecRequest]):
        if not drafting:
            return
        R = self.llm.max_requests
        P = self.llm.max_spec_tokens
        masks = np.zeros((R, P, P), bool)
        toks, reqi, pos, spec, index_of = [], [], [], [], {}
        commit, spans = [], []
        for req in drafting:
            for ni, node in enumerate(req.tree):
                masks[req.slot, ni, ni] = True
                if node.parent >= 0:
                    masks[req.slot, ni] |= masks[req.slot, node.parent]
                    masks[req.slot, ni, ni] = True
                index_of[(req.rid, ni)] = len(toks)
                toks.append(node.token)
                reqi.append(req.slot)
                pos.append(req.llm_committed + node.depth)
                spec.append(ni)
            for src, dst in req.pending_commit:
                commit.append((req.slot, src, dst))
            if req.pending_commit:
                # the commit descriptor writes accepted KV into the
                # committed cache at these positions (the spec-tree buffer
                # itself is never paged)
                dsts = [d for _, d in req.pending_commit]
                spans.append((req.rid, min(dsts), max(dsts) + 1))
            req.pending_commit = []
        self._kv_prepare(spans)
        bc = self._tree_bc(
            TreeVerifyBatchConfig, self.llm, toks, reqi, pos, spec, masks,
            committed_attr="llm_committed", commit=commit,
        )
        # stochastic verification: with temperature > 0 the verify step
        # SAMPLES y ~ p(target | node prefix) per tree node (seeded, top-p)
        # and the walk accepts a child iff its token equals y — every
        # emitted token is a fresh target-conditional draw, so the output
        # distribution equals plain sampled incremental decoding's (see
        # spec_scan._macro_body for the acceptance-rate tradeoff vs the
        # p/q-ratio rule).  T<=0 keeps the exact-greedy walk.
        smp = self._sample_arg()
        result = self._guarded(
            "spec_verify", lambda: self.llm.step(bc, sample=smp))
        if result is None:
            return
        self.llm_steps += 1
        ids = np.asarray(result.token_ids)

        for req in drafting:
            # accept walk from the root (greedy or vs the sampled tokens)
            ni = 0
            accepted_nodes = [0]
            while True:
                want = int(ids[index_of[(req.rid, ni)]])
                child = next(
                    (
                        j
                        for j, n in enumerate(req.tree)
                        if n.parent == ni and n.token == want
                    ),
                    None,
                )
                if child is None:
                    bonus = want
                    break
                accepted_nodes.append(child)
                ni = child
            # commit root + accepted draft nodes next round; emit their tokens
            new_tokens = []
            for k, node_idx in enumerate(accepted_nodes):
                node = req.tree[node_idx]
                posn = req.llm_committed + node.depth
                req.pending_commit.append((node_idx, posn))
                if k > 0:  # root token was already in req.generated
                    new_tokens.append(node.token)
            new_tokens.append(bonus)
            req.llm_committed += len(accepted_nodes)
            # acceptance telemetry: draft tokens that survived the walk
            # this round (the root is committed context, not a draft) —
            # feeds the workload profile's spec_acceptance histogram so
            # acceptance-rate drift is visible to the planner
            if self.telemetry.enabled and len(req.tree) > 1:
                self.telemetry.spec_acceptance(
                    len(accepted_nodes) - 1, len(req.tree) - 1)
            # SSM needs the same accepted tokens in its committed cache; the
            # root (generated[-1] pre-walk) is part of them
            base_pos = req.ssm_committed
            acc_toks = [req.tree[i].token for i in accepted_nodes]
            req.ssm_backlog += [
                (t, base_pos + k) for k, t in enumerate(acc_toks)
            ]
            for t in new_tokens:
                self._append_token(req, t)
                self._maybe_finish(req)
                if req.status is RequestStatus.COMPLETED:
                    break

    # ------------------------------------------------------------------
    def serve_spec_infer(self) -> Dict[int, List[int]]:
        """Reference: ``RequestManager::serve_spec_infer``.

        Cancellations and deadline expiries are reaped at macro-step
        boundaries (the speculative analogue of the incremental loop's
        step-boundary checks)."""
        while True:
            self._check_lifecycle()
            if not self.has_work():
                break
            self._prefill_phase()
            drafting = self._draft_phase()
            self._verify_phase(drafting)
            self._sync_kv()  # live KV occupancy, once per macro step
            self.macro_steps += 1
        return {rid: r.generated for rid, r in self.requests.items()}

    _serve = serve_spec_infer
