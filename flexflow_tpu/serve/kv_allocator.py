"""KVAllocator: the single owner of the serving KV-cache buffers.

Through r9 the cache buffers were InferenceManager attributes and every
consumer re-derived its own view of them: admission control walked the raw
buffer shapes (``resilience.kv_bytes_per_token``), preemption released
slots it never priced, and ``plan_memory_bytes`` predicted a capacity
nothing ever reconciled against what HBM actually held.  vLLM (Kwon et
al., SOSP'23) showed that KV accounting at sub-request granularity is what
turns memory from a cliff into a managed resource — this module is that
accounting layer for the slot-contiguous cache (and the interface the
ROADMAP's paged/prefix-shared KV item will re-implement with a block
table behind the same API):

* :class:`StageKV` — buffers of ONE compiled plan (the single-plan
  :class:`~flexflow_tpu.serve.inference_manager.InferenceManager`, or one
  pipeline stage of the
  :class:`~flexflow_tpu.serve.pp.PipelinedInferenceManager`): allocation
  via :func:`allocate_attention_state` (the one cache-layout function),
  plus the byte arithmetic read off the REAL allocated arrays.
* :class:`KVAllocator` — the deployment-level front: composes the
  per-stage instances, owns the per-request slot→bytes attribution
  (``bind`` at slot assignment, ``observe`` with live token counts per
  serve tick, ``release`` on EVERY terminal outcome and preemption), and
  emits the live-side memory telemetry (``kv_occupancy_frac``,
  ``kv_headroom_bytes``, high-watermark, slot fragmentation) through the
  shared :class:`~flexflow_tpu.obs.telemetry.Telemetry` handle.

Everything here is host-side bookkeeping over buffer metadata — the
buffers themselves are the same arrays the jitted step donates, so owning
them here cannot change compiled executables or their outputs
(bit-identity with the memory layer on or off is pinned by
tests/test_kv_allocator.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

# the committed-KV buffer names (k/v planes and, under int8 KV, their f32
# scale planes) — THE byte-accounting vocabulary every consumer shares
# (admission headroom, the serve search's KV-stream pricing, the ledger)
KV_BUFFER_NAMES = frozenset({"k", "v", "k_scale", "v_scale"})


def per_device_nbytes(arr) -> float:
    """Bytes ONE device holds of a (possibly sharded) array — the worst
    device's share, so replicated arrays count full size and sharded ones
    their largest shard sum.  The per-device basis is what reconciles the
    real allocation against ``plan_memory_bytes``'s per-device contract."""
    try:
        shards = arr.addressable_shards
    except AttributeError:
        return float(getattr(arr, "nbytes", 0))
    if not shards:
        return float(arr.nbytes)
    by_dev: Dict[Any, float] = {}
    for s in shards:
        by_dev[s.device] = by_dev.get(s.device, 0.0) + s.data.nbytes
    return max(by_dev.values())


def params_nbytes(params) -> float:
    """Per-device bytes of a serve param tree (the allocated-weights side
    of the memory ledger; int8 values + f32 scales count as stored)."""
    total = 0.0
    for group in (params or {}).values():
        for arr in group.values():
            total += per_device_nbytes(arr)
    return total


def allocate_attention_state(nodes, strategy, mesh, max_requests,
                             max_seq_len, max_spec_tokens=0,
                             always_place=False):
    """Allocate the KV/spec cache buffers for the attention ops in
    ``nodes`` — the single source of the cache layout shared by the
    single-plan manager and the per-stage allocator of pipeline-parallel
    serving (so the seq-pad rule and buffer name set cannot diverge from
    the bit-identity contract the pp tests pin).

    The k/v (+ int8 scale) seq dim is rounded up to a lane-width (128)
    multiple so the Pallas kernels always get a dividing power-of-two
    block; extra slots sit beyond every mask, and the int8 scale buffers
    share the caches' seq dim so they pad identically.

    ``always_place``: commit buffers to ``mesh`` even when it is a single
    device — per-stage KV residency is the capacity contract of PP serving
    (the default only places on multi-device meshes, matching the
    single-plan manager's historical behavior).
    """
    from .ops import IncMultiHeadSelfAttention

    state: Dict[str, Any] = {}
    for node in nodes:
        op = node.op
        if not isinstance(op, IncMultiHeadSelfAttention):
            continue
        head_axes = tuple(strategy.get(node.name, {}).get("head", ()))
        specs = op.state_specs(max_requests, max_seq_len, max_spec_tokens,
                               head_axes)
        bufs = {}
        for name, (shape, dt, sh) in specs.items():
            if name in KV_BUFFER_NAMES:
                s_pad = -(-shape[2] // 128) * 128
                shape = shape[:2] + (s_pad,) + shape[3:]
            arr = jnp.zeros(shape, jnp.dtype(dt))
            if always_place or (mesh is not None and mesh.size > 1):
                arr = jax.device_put(arr, sh.named_sharding(mesh))
            bufs[name] = arr
        state[node.name] = bufs
    return state


class StageKV:
    """Buffers of one compiled plan (a whole single-plan deployment, or
    one pipeline stage).  Holds the live state dict the jitted step
    donates and re-binds, plus the byte arithmetic over it."""

    def __init__(self, nodes, strategy, mesh, max_requests: int,
                 max_seq_len: int, max_spec_tokens: int = 0,
                 always_place: bool = False, label: str = "plan"):
        self.nodes = list(nodes)
        self.strategy = strategy or {}
        self.mesh = mesh
        self.max_requests = max_requests
        self.max_seq_len = max_seq_len
        self.max_spec_tokens = max_spec_tokens
        self.always_place = always_place
        self.label = label
        self.state: Optional[Dict[str, Dict]] = None

    def allocate(self) -> Dict[str, Dict]:
        """(Re)allocate zeroed cache buffers; returns the state dict."""
        self.state = allocate_attention_state(
            self.nodes, self.strategy, self.mesh, self.max_requests,
            self.max_seq_len, self.max_spec_tokens,
            always_place=self.always_place,
        )
        return self.state

    # ---- byte accounting over the ALLOCATED arrays --------------------
    def allocated_bytes(self, kv_only: bool = True,
                        per_device: bool = False) -> float:
        """Bytes of the allocated serve-state buffers (``kv_only``
        restricts to the committed k/v (+scale) planes; False adds the
        spec-tree buffers too).  ``per_device`` counts one device's share
        (the ledger's reconciliation basis against per-device
        ``plan_memory_bytes``); the default is global bytes, matching the
        admission gate's historical accounting.  0.0 before
        :meth:`allocate`."""
        if not self.state:
            return 0.0
        total = 0.0
        for bufs in self.state.values():
            for name, arr in bufs.items():
                if kv_only and name not in KV_BUFFER_NAMES:
                    continue
                total += per_device_nbytes(arr) if per_device else arr.nbytes
        return total

    def bytes_per_token(self) -> Optional[float]:
        """Committed-KV bytes one request's cache position costs across
        this plan's attention ops — THE shape walk admission control,
        preemption pricing, and the memory ledger all share.

        Buffers are ``[max_requests+1, heads, seq, dim]``, so the
        per-request-token price divides by the REAL request rows as well
        as the seq axis; the pad-scratch row's bytes amortize over the
        real rows, so ``per_tok * max_requests * max_seq_len``
        approximates the full cache allocation (scratch row priced in,
        lane padding beyond ``max_seq_len`` not).  None before
        :meth:`allocate`."""
        if not self.state:
            return None
        total = 0.0
        for bufs in self.state.values():
            for name, arr in bufs.items():
                if name in KV_BUFFER_NAMES:
                    rows = max(arr.shape[0] - 1, 1)  # minus the scratch row
                    total += arr.nbytes / (rows * arr.shape[2])
        return total or None


class KVAllocator:
    """Deployment-level KV ownership: per-stage buffers + per-request
    attribution + live-side memory telemetry.

    ``stages``: one :class:`StageKV` per compiled plan — a single-plan
    manager passes one; ``PipelinedInferenceManager`` one per pipeline
    stage (per-stage KV residency is its capacity contract).

    Attribution protocol (driven by the RequestManager):

    * :meth:`bind` when a request takes a slot;
    * :meth:`observe` once per serve tick with every live slotted
      request's cache depth — updates per-request peaks, the live
      high-watermark, and (telemetry enabled) the occupancy/headroom/
      fragmentation gauges;
    * :meth:`release` on EVERY path a request leaves its slot —
      completion, cancel, timeout, failure, preemption — returning the
      bytes attributed to the binding (peak positions held × bytes per
      token), so no terminal outcome can leak attribution
      (tests/test_kv_allocator.py pins all of r9's outcomes).
    """

    # the slot-contiguous allocator: one reserved max_seq_len span per slot.
    # The paged subclass (serve/kv_paged.py) flips this and overrides the
    # page-granular hooks below behind the SAME bind/observe/release/
    # bytes_per_token/capacity_bytes interface.
    paged = False
    # host-DRAM spill tier (serve/kv_paged.py HostPageTier); the
    # slot-contiguous allocator never tiers — None keeps every caller's
    # ``kv.host_tier is not None`` gate uniform across allocator kinds.
    host_tier = None

    def __init__(self, stages: Sequence[StageKV], max_requests: int,
                 max_seq_len: int):
        self.stages = list(stages)
        self.max_requests = max_requests
        self.max_seq_len = max_seq_len
        self._live: Dict[int, int] = {}   # rid -> last observed cache depth
        self._peak: Dict[int, int] = {}   # rid -> peak depth this binding
        self.hwm_tokens = 0
        self.hwm_bytes = 0.0

    # ---- buffer ownership ---------------------------------------------
    def allocate(self):
        """(Re)allocate every stage's buffers (zeroed).  Returns the
        single-plan state dict, or the per-stage list for pp."""
        states = [s.allocate() for s in self.stages]
        return states[0] if len(states) == 1 else states

    @property
    def state(self):
        """Single-plan convenience view (the one stage's state dict); pp
        callers address ``stages[i].state`` directly."""
        return self.stages[0].state

    @state.setter
    def state(self, value):
        self.stages[0].state = value

    def reset_attribution(self) -> None:
        """Forget all request attribution + watermarks (new serving
        session over the same buffers; rids restart from 0)."""
        self._live.clear()
        self._peak.clear()
        self.hwm_tokens = 0
        self.hwm_bytes = 0.0

    # ---- the ONE headroom arithmetic ----------------------------------
    def bytes_per_token(self) -> Optional[float]:
        """Committed-KV bytes one request-token costs across ALL stages —
        None until every stage's caches are allocated, and None again if a
        caller drops them (``im.state = None`` frees HBM between bench
        runs); always read off the LIVE buffers, never cached, so the
        price can't outlive the allocation it describes."""
        parts = [s.bytes_per_token() for s in self.stages]
        if any(p is None for p in parts):
            return None
        return sum(parts) or None

    @property
    def capacity_tokens(self) -> int:
        """Position capacity of the slot-contiguous cache."""
        return self.max_requests * self.max_seq_len

    def capacity_bytes(self) -> float:
        """Byte capacity priced at :meth:`bytes_per_token` (falls back to
        token-slot units — 1.0/token — before caches are allocated, the
        same degradation the admission gate historically had)."""
        return self.capacity_tokens * (self.bytes_per_token() or 1.0)

    def allocated_bytes(self, kv_only: bool = True,
                        per_device: bool = False) -> float:
        """Bytes actually held by the allocated cache buffers (lane
        padding and scratch rows included) across all stages; see
        :meth:`StageKV.allocated_bytes` for the ``per_device`` basis."""
        return sum(s.allocated_bytes(kv_only=kv_only, per_device=per_device)
                   for s in self.stages)

    # ---- per-request attribution --------------------------------------
    def bind(self, rid: int, **_) -> Optional[Dict]:
        """A request took a slot (admission or preemption-readmission).

        The slot-contiguous allocator only starts attribution; the extra
        keyword context the RequestManager supplies (``slot``, ``tokens``,
        ``need``, ``align``) is consumed by the paged subclass, which maps
        pages and returns a ``{"cached_tokens", "hit_pages"}`` prefix-reuse
        dict (None here: nothing is ever pre-cached in a dedicated span).
        """
        self._live.setdefault(int(rid), 0)
        self._peak.setdefault(int(rid), 0)
        return None

    def prepare_write(self, rid: int, lo: int, hi: int) -> None:
        """Authorize cache writes at positions ``[lo, hi)`` for ``rid``
        BEFORE the dispatch that performs them.  A no-op here — every
        slot's span is pre-reserved — but the paged subclass allocates
        missing pages and copy-on-writes shared ones, so serve loops call
        this unconditionally through RequestManager._kv_prepare."""
        return None

    def round_need(self, tokens: int) -> int:
        """Admission-gate granularity of a worst-case cache need: the
        slot-contiguous gate prices exact positions; the paged subclass
        rounds up to whole pages (a request can only hold page multiples)."""
        return int(tokens)

    def page_view(self):
        """The device-side block-table pytree for the jitted step (None =
        slot-contiguous addressing; see kv_paged.PageTable)."""
        return None

    # ---- host-tier hooks (no-ops: only the paged subclass tiers) ------
    def attach_host_tier(self, capacity_bytes: int):
        """Attach a bounded host-DRAM spill tier.  The slot-contiguous
        allocator has no page granularity to spill at — recovery stays
        recompute-based — so this is a no-op returning None; callers
        (RequestManager, migration, fleet) gate every swap path on
        ``host_tier is not None`` and need no isinstance checks."""
        return None

    def spill(self, rid: int, tokens) -> Optional[Dict]:
        return None

    def restore(self, rid: int, align: int = 1) -> Optional[Dict]:
        return None

    def has_spill(self, rid: int) -> bool:
        return False

    def drop_spill(self, rid: int) -> None:
        return None

    def adopt_spills(self, other, rids) -> int:
        return 0

    def observe(self, usage: Dict[int, int], telemetry=None) -> Dict:
        """One serve tick's live cache depths (``rid -> tokens`` for every
        slotted PREFILLING/DECODING request).  Updates peaks + watermarks
        and, when a live telemetry handle is given, publishes the gauge
        set; returns the computed snapshot either way."""
        self._live = {int(r): int(t) for r, t in usage.items()}
        for rid, t in self._live.items():
            if t > self._peak.get(rid, 0):
                self._peak[rid] = t
        per_tok = self.bytes_per_token()  # ONE buffer walk per tick
        live = sum(self._live.values())
        live_bytes = live * per_tok if per_tok else 0.0
        if live > self.hwm_tokens:
            self.hwm_tokens = live
        if live_bytes > self.hwm_bytes:
            self.hwm_bytes = live_bytes
        snap = self.snapshot(_per_tok=per_tok, _live=live)
        if telemetry is not None and getattr(telemetry, "enabled", False):
            telemetry.kv_usage(snap)
        return snap

    def snapshot(self, _per_tok: Optional[float] = None,
                 _live: Optional[int] = None) -> Dict:
        """The current occupancy/headroom/fragmentation view over the
        last-observed depths — pure read (no peak/watermark updates, no
        telemetry); :meth:`observe` is the mutating per-tick entry and
        passes its already-computed walk/sum in so the hot path prices
        the buffers exactly once per tick."""
        per_tok = self.bytes_per_token() if _per_tok is None else _per_tok
        live = sum(self._live.values()) if _live is None else _live
        live_bytes = live * per_tok if per_tok else 0.0
        cap_b = self.capacity_tokens * (per_tok or 1.0)
        bound = len(self._live)
        return {
            "live_tokens": live,
            "live_bytes": live_bytes,
            "capacity_tokens": self.capacity_tokens,
            "capacity_bytes": cap_b,
            "headroom_bytes": cap_b - (live_bytes if per_tok else live),
            "occupancy_frac": (live / self.capacity_tokens
                               if self.capacity_tokens else 0.0),
            # slot fragmentation: each bound slot reserves max_seq_len
            # contiguous positions of which only the live prefix is
            # occupied — the allocated-but-idle share the paged-KV item
            # exists to reclaim
            "fragmentation_frac": (
                1.0 - live / (bound * self.max_seq_len)
                if bound and self.max_seq_len else 0.0),
            "bound_slots": bound,
            "hwm_tokens": self.hwm_tokens,
            "hwm_bytes": self.hwm_bytes,
        }

    def live_tokens(self) -> int:
        return sum(self._live.values())

    def live_requests(self) -> int:
        """Slotted requests currently holding cache (the OOM-risk
        projection multiplies each by the expected remaining output)."""
        return len(self._live)

    def release(self, rid: int, tokens: Optional[int] = None) -> float:
        """The request left its slot (ANY terminal outcome, or a
        preemption eviction).  ``tokens`` is its final cache depth when
        the caller knows it (a request can admit and finish within one
        tick, before any :meth:`observe`); attribution is the PEAK depth
        the binding reached × bytes per token.  Safe (0.0) for rids that
        never bound — a rejected request holds no cache."""
        rid = int(rid)
        peak = self._peak.pop(rid, 0)
        last = self._live.pop(rid, 0)
        if tokens is not None:
            peak = max(peak, int(tokens))
        peak = max(peak, last)
        return peak * (self.bytes_per_token() or 0.0)

    def attributed_rids(self) -> List[int]:
        """Rids currently holding attribution — empty once every request
        reached a terminal outcome (the no-leak contract)."""
        return sorted(set(self._live) | set(self._peak))

    def teardown(self) -> List[int]:
        """Release this deployment's cache ownership entirely: every
        remaining per-request attribution releases, the watermarks reset,
        and the buffers drop (``state = None`` per stage, freeing the
        HBM).  THE incumbent-retirement hook of live plan migration
        (serve/migration.py): after a full drain every request already
        released on its slot-leaving path, so the returned list of rids
        that STILL held attribution is the refcount no-leak check —
        non-empty means some path leaked (pinned by
        tests/test_migration.py / test_kv_paged.py)."""
        leaked = self.attributed_rids()
        for rid in leaked:
            self.release(rid)
        self.reset_attribution()
        for s in self.stages:
            s.state = None
        return leaked
