"""On-device speculative-decoding macro-step scan.

TPU-first redesign of the reference's speculative serving loop (reference:
``RequestManager::serve_spec_infer`` / ``prepare_next_batch_beam`` /
``prepare_next_batch_verify`` in ``src/runtime/request_manager.cc``): the
reference re-plans every phase on the host (CPU builds a BeamSearchBatchConfig
per draft level and a TreeVerifyBatchConfig per verify, syncing results back
each time).  On a tunneled TPU runtime a host sync costs ~100ms while a
decode step costs ~7ms, so a host-driven macro step (depth+2 syncs) would be
latency, not compute.

Here the ENTIRE macro step runs on device inside one ``lax.scan``:

1. *SSM catch-up* — feed the previous macro-step's accepted tokens into the
   draft model's committed cache (plain ``BatchConfig``).
2. *draft* — ``depth`` unrolled beam-expansion levels through the SSM
   (``TreeSearchBatchConfig``); per level, the global top-``width``
   candidates by cumulative logprob become the next frontier.  Because the
   beam always fills exactly ``width`` nodes per level, node indices are
   STATIC per level — tree arrays update with static slices, no scatter.
3. *verify* — one LLM ``TreeVerifyBatchConfig`` step: the commit descriptor
   carries the previous macro-step's accepted nodes (spec-buffer KV ->
   committed cache, computed once, never recomputed), then the whole tree is
   scored under the tree-topology mask (Pallas two-segment kernel).
4. *accept walk* — the greedy root-down walk, EOS masking, and the next
   step's commit/backlog bookkeeping, all as fixed-shape ``lax.scan`` steps.

The host syncs ONCE per ``n_macro`` scan: with sync latency L, per-token
overhead drops from ``(depth+2) * L / committed`` to
``L / (n_macro * committed)``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .batch_config import (
    BatchConfig,
    TreeSearchBatchConfig,
    TreeVerifyBatchConfig,
)
from .inference_manager import (
    EXIT_BUDGET,
    EXIT_EOS,
    EXIT_NOT_IN_BATCH,
    EXIT_RUNNING,
)

# per-slot budget sentinel for "no device-side max-new exit" (init_carry
# budget=None): far above any reachable emission count, so the budget
# truncation below is the identity
_NO_BUDGET = np.int32(2 ** 30)


def _pad_flat(arr, cap, fill):
    """Flatten ``arr`` and right-pad with ``fill`` to length ``cap``."""
    flat = arr.reshape(-1)
    n = flat.shape[0]
    if n > cap:
        raise ValueError(f"{n} tokens exceed batch capacity {cap}")
    out = jnp.full((cap,), fill, flat.dtype)
    return out.at[:n].set(flat)


class SpecDecodeScan:
    """Runs speculative macro-steps on device for up-to-capacity request sets.

    Built over two :class:`InferenceManager` instances (LLM + SSM) exactly
    like :class:`SpecInferManager`, but the per-macro-step work is a single
    jitted program.  Greedy invariant (tested): emitted sequences equal plain
    incremental decoding's for any draft model.
    """

    def __init__(self, llm, ssm, width: int = 2, depth: int = 3,
                 eos_token_id: Optional[int] = None):
        self.llm = llm
        self.ssm = ssm
        self.width = int(width)
        self.depth = int(depth)
        self.eos = eos_token_id
        self.n_tree = 1 + self.width * self.depth
        R = llm.max_requests
        if ssm.max_requests != R:
            raise ValueError("LLM and SSM must agree on max_requests")
        if llm.max_spec_tokens < self.n_tree or ssm.max_spec_tokens < self.n_tree:
            raise ValueError(
                f"spec buffers too small: need {self.n_tree}, have "
                f"llm={llm.max_spec_tokens} ssm={ssm.max_spec_tokens}"
            )
        if llm.max_tokens < R * self.n_tree:
            raise ValueError(
                f"LLM max_tokens_per_batch must fit {R}x{self.n_tree} tree tokens"
            )
        if ssm.max_tokens < R * max(self.width, self.depth + 1):
            raise ValueError(
                "SSM max_tokens_per_batch must fit the widest draft frontier "
                f"({R}x{self.width}) and the catch-up batch ({R}x{self.depth + 1})"
            )
        if ssm.topk < self.width:
            raise ValueError(f"SSM needs topk >= width ({self.width})")
        from .ops import DUS_MAX_TOKENS

        # _scatter_rows_pos switches paths on the flat array length the
        # step actually ships.  The scan sizes each phase's batch EXACTLY
        # (verify: R*n_tree, catch-up: R*(depth+1), draft: R*width ≤ both)
        # instead of padding to max_tokens — capacity padding multiplied the
        # per-step DUS chains / forward tokens / topk for nothing — so those
        # exact sizes are what must stay under the DUS threshold.
        for tag, cap_t in (("verify", R * self.n_tree),
                           ("catch-up", R * (self.depth + 1))):
            if cap_t > DUS_MAX_TOKENS:
                raise ValueError(
                    f"{tag} batch size ({cap_t}) exceeds the KV-write DUS "
                    f"threshold ({DUS_MAX_TOKENS}); the scatter fallback "
                    "would force a per-macro-step full-cache relayout — "
                    "use fewer request slots or a shallower/narrower tree"
                )
        # the verify batch always ships exactly n_tree tokens per request in
        # slot-major order -> the LLM can use the batched tree kernel (the
        # committed cache streams once per request, not once per tree token).
        # The layout is baked into the jitted step at first trace, so one
        # InferenceManager can serve only one (width, depth) shape.
        if llm.tree_token_layout not in (None, (R, self.n_tree)):
            raise ValueError(
                f"LLM is already bound to tree layout {llm.tree_token_layout}"
                f" != {(R, self.n_tree)}; build a separate InferenceManager"
            )
        llm.tree_token_layout = (R, self.n_tree)
        # node depth by static node index: root, then width nodes per level
        self._node_depth = np.zeros(self.n_tree, np.int32)
        for lvl in range(1, self.depth + 1):
            self._node_depth[1 + (lvl - 1) * self.width: 1 + lvl * self.width] = lvl
        from ..utils.platform import collective_safe_compiler_options

        self._scan = jax.jit(
            self._scan_impl, donate_argnums=(2,),
            static_argnames=("n_macro",),
            compiler_options=collective_safe_compiler_options(llm.model.mesh),
        )

    # ------------------------------------------------------------------
    def init_carry(self, root_tokens, llm_committed, ssm_committed, finished,
                   spec_mask=None, budget=None):
        """Build the scan carry from host bookkeeping (post-prefill).

        ``root_tokens[r]``: last generated token per slot (the tree root);
        ``llm_committed``/``ssm_committed``: committed cache depths (equal
        for active slots at macro-step boundaries); ``finished``: frozen
        slots (emit nothing, write nothing); ``spec_mask[r]`` (default
        all-True): per-slot speculation mode — False rows skip drafting
        and verify a ROOT-ONLY tree, i.e. they decode exactly one token
        per macro step in the SAME batched verify as the spec rows (the
        mixed spec/non-spec macro-step).  Plain rows still ride the
        catch-up feed, so their SSM cache stays current and a host-side
        flip between ``run()`` windows needs no rebuild.

        ``budget[r]`` (default unbounded): remaining new-token allowance
        per slot — the DEVICE-side max-new exit.  A macro-step truncates
        a row's emissions at its budget and freezes the slot, exactly
        where the host's ``_maybe_finish`` would (emission order: budget
        cut first, then EOS truncation of the survivors — first
        terminator along the token stream wins, like the per-token host
        check).  ``carry["exit_code"]`` reports why each slot froze
        (EXIT_EOS / EXIT_BUDGET; EXIT_RUNNING while live,
        EXIT_NOT_IN_BATCH for slots finished at entry) — one readback at
        window end covers lifecycle too.
        """
        R, D = self.llm.max_requests, self.depth
        if spec_mask is None:
            spec_mask = [True] * R
        if budget is None:
            budget = np.full(R, _NO_BUDGET, np.int32)
        fin0 = np.asarray(finished, bool)
        return dict(
            llm_state=self.llm.state,
            ssm_state=self.ssm.state,
            # global macro counter: the stochastic-verify key folds on THIS
            # (not the per-call scan index), so windowed run() calls sharing
            # one sample key never replay per-step keys
            macro_ctr=jnp.zeros((), jnp.int32),
            root=jnp.asarray(root_tokens, jnp.int32),
            llm_comm=jnp.asarray(llm_committed, jnp.int32),
            ssm_comm=jnp.asarray(ssm_committed, jnp.int32),
            commit_src=jnp.full((R, D + 1), -1, jnp.int32),
            commit_dst=jnp.zeros((R, D + 1), jnp.int32),
            commit_n=jnp.zeros((R,), jnp.int32),
            backlog_tok=jnp.zeros((R, D + 1), jnp.int32),
            backlog_n=jnp.zeros((R,), jnp.int32),
            finished=jnp.asarray(finished, bool),
            spec=jnp.asarray(spec_mask, bool),
            budget=jnp.asarray(budget, jnp.int32),
            exit_code=jnp.where(jnp.asarray(fin0), EXIT_NOT_IN_BATCH,
                                EXIT_RUNNING).astype(jnp.int32),
        )

    def run(self, carry, n_macro: int, sample=None):
        """Run ``n_macro`` macro-steps on device.

        Returns ``(emitted, carry)`` where ``emitted`` is
        ``i32[n_macro, R, depth+1]`` (-1 = no token) and the carry holds the
        updated KV caches + bookkeeping.  Caches are donated.  The caller
        must ensure ``llm_comm + n_macro*(depth+1) + depth < max_seq_len``.

        ``sample``: optional ``(key, temperature, top_p)`` — stochastic
        verification (see ``_macro_body``); greedy argmax walk if None.
        """
        worst = int(np.max(np.asarray(carry["llm_comm"]))) \
            + n_macro * (self.depth + 1) + self.depth
        if worst > self.llm.max_seq_len:
            raise ValueError(
                f"n_macro={n_macro} could reach position {worst} > "
                f"LLM max_seq_len {self.llm.max_seq_len}"
            )
        if worst > self.ssm.max_seq_len:
            raise ValueError(
                f"n_macro={n_macro} could reach position {worst} > "
                f"SSM max_seq_len {self.ssm.max_seq_len}"
            )
        # paged KV: committed depths advance ON DEVICE inside the scan, so
        # every page a slot's worst-case growth can reach is mapped (and
        # COW-resolved) up front — the block table is then constant for
        # the whole scan (slot-addressed: the scan has no rids)
        grow = n_macro * (self.depth + 1) + self.depth
        for im, comm_key in ((self.llm, "llm_comm"), (self.ssm, "ssm_comm")):
            kv = getattr(im, "kv", None)
            if not getattr(kv, "paged", False):
                continue
            comm = np.asarray(carry[comm_key])
            fin = np.asarray(carry["finished"])
            for r in range(im.max_requests):
                if not fin[r]:
                    kv.prepare_slot_span(
                        r, int(comm[r]),
                        min(int(comm[r]) + grow, im.max_seq_len))
        emitted, carry = self._scan(
            self.llm.params, self.ssm.params, carry, sample,
            self.llm._page_view(), self.ssm._page_view(), n_macro=n_macro
        )
        # keep the managers' views of their caches current
        self.llm.state = carry["llm_state"]
        self.ssm.state = carry["ssm_state"]
        return emitted, carry

    # ------------------------------------------------------------------
    def _scan_impl(self, llm_params, ssm_params, carry, sample,
                   llm_pages, ssm_pages, n_macro: int):
        def body(c, _):
            stp = None
            if sample is not None:
                key, temperature, top_p = sample
                stp = (jax.random.fold_in(key, c["macro_ctr"]),
                       temperature, top_p)
            return self._macro_body(llm_params, ssm_params, c, stp,
                                    llm_pages, ssm_pages)

        carry, emitted = jax.lax.scan(body, carry, None, length=n_macro)
        return emitted, carry

    def _macro_body(self, llm_params, ssm_params, c, sample=None,
                    llm_pages=None, ssm_pages=None):
        R, W, D, P = (self.llm.max_requests, self.width, self.depth,
                      self.n_tree)
        fin = c["finished"]
        smask = c["spec"]  # per-slot speculation mode (mixed macro-steps)
        slot = jnp.arange(R, dtype=jnp.int32)
        kk = jnp.arange(D + 1, dtype=jnp.int32)[None, :]          # [1, D+1]

        # ---- 1. SSM catch-up: previous macro-step's accepted tokens ----
        # every phase compiles its own program (distinct bc pytree), so
        # each uses EXACT flat sizes instead of padding to ssm.max_tokens —
        # capacity padding multiplied the per-step KV DUS chains, forward
        # tokens, and [T, vocab] topk by max_tokens/live (6x at the bench
        # shape) for no reason
        nb = jnp.where(fin, 0, c["backlog_n"])                     # [R]
        valid = kk < nb[:, None]                                   # [R, D+1]
        cap = R * (D + 1)
        bc_cu = BatchConfig(
            tokens=_pad_flat(jnp.where(valid, c["backlog_tok"], 0), cap, 0),
            request_index=_pad_flat(
                jnp.where(valid, slot[:, None], -1), cap, -1),
            token_position=_pad_flat(
                c["ssm_comm"][:, None] + kk, cap, 0),
            num_tokens=jnp.sum(valid),
            seq_lens=c["ssm_comm"] + nb,
        )
        _, ssm_state = self.ssm._step_impl(ssm_params, c["ssm_state"], bc_cu,
                                           pages=ssm_pages)
        ssm_comm = c["ssm_comm"] + nb

        # ---- 2. draft: unrolled beam levels (static node indices) ----
        Pb_s = self.ssm.max_spec_tokens
        tok = jnp.zeros((R, P), jnp.int32).at[:, 0].set(c["root"])
        par = jnp.full((R, P), -1, jnp.int32)
        cumlp = jnp.zeros((R, P), jnp.float32)
        amask = jnp.zeros((R, P, P), bool).at[:, 0, 0].set(True)

        for lvl in range(D):
            f_idx = (np.array([0], np.int32) if lvl == 0
                     else np.arange(1 + (lvl - 1) * W, 1 + lvl * W,
                                    dtype=np.int32))
            F = len(f_idx)
            ftok = tok[:, f_idx]                                   # [R, F]
            # non-spec rows never draft: their frontier tokens ship as
            # padding (no KV writes, logits ignored) — the SSM step's
            # shapes stay static, only the valid set shrinks
            reqi = jnp.broadcast_to(
                jnp.where(fin | ~smask, -1, slot)[:, None], (R, F))
            fpos = jnp.broadcast_to(
                (ssm_comm + lvl)[:, None], (R, F))
            spec = jnp.broadcast_to(jnp.asarray(f_idx)[None, :], (R, F))
            bc_d = TreeSearchBatchConfig(
                base=BatchConfig(
                    tokens=ftok.reshape(-1),        # exact R*F flat slots
                    request_index=reqi.reshape(-1),
                    token_position=fpos.reshape(-1),
                    num_tokens=jnp.sum(reqi >= 0),
                    seq_lens=ssm_comm,
                ),
                spec_index=spec.reshape(-1),
                ancestor_mask=self._pad_mask(amask, Pb_s),
                committed_lens=ssm_comm,
            )
            res, ssm_state = self.ssm._step_impl(ssm_params, ssm_state, bc_d,
                                                 pages=ssm_pages)
            k_ids = res.topk_ids[: R * F].reshape(R, F, -1)[:, :, :W]
            k_lp = res.topk_logprobs[: R * F].reshape(R, F, -1)[:, :, :W]
            cand_lp = (cumlp[:, f_idx][:, :, None] + k_lp).reshape(R, F * W)
            sel_lp, sel = jax.lax.top_k(cand_lp, W)                # [R, W]
            sel_par = jnp.asarray(f_idx)[sel // W]                 # [R, W]
            sel_tok = jnp.take_along_axis(
                k_ids.reshape(R, F * W), sel, axis=1)
            n0 = 1 + lvl * W                                       # static
            tok = jax.lax.dynamic_update_slice(tok, sel_tok, (0, n0))
            par = jax.lax.dynamic_update_slice(par, sel_par, (0, n0))
            cumlp = jax.lax.dynamic_update_slice(cumlp, sel_lp, (0, n0))
            # child mask row = parent's row + own bit (static positions)
            par_rows = jnp.take_along_axis(
                amask, sel_par[:, :, None], axis=1)                # [R, W, P]
            own = jax.nn.one_hot(
                np.arange(n0, n0 + W), P, dtype=bool)[None]        # [1, W, P]
            amask = jax.lax.dynamic_update_slice(
                amask, par_rows | own, (0, n0, 0))

        # ---- 3. LLM verify (commit descriptor from previous macro) ----
        cap_l = R * P  # exact: the verify batch is always R full trees
        depth_of = jnp.asarray(self._node_depth)                   # [P]
        # the MIXED verify batch: spec rows ship their whole tree, plain
        # rows ship the root node only (their decode token) — nodes past
        # the root become padding for non-spec slots
        node_ok = smask[:, None] | (jnp.arange(P) == 0)[None, :]   # [R, P]
        reqi_v = jnp.where(fin[:, None] | ~node_ok, -1,
                           jnp.broadcast_to(slot[:, None], (R, P)))
        pos_v = c["llm_comm"][:, None] + depth_of[None, :]
        commit_valid = kk < jnp.where(fin, 0, c["commit_n"])[:, None]
        bc_v = TreeVerifyBatchConfig(
            base=BatchConfig(
                tokens=_pad_flat(tok, cap_l, 0),
                request_index=_pad_flat(reqi_v, cap_l, -1),
                token_position=_pad_flat(pos_v, cap_l, 0),
                num_tokens=jnp.sum(reqi_v >= 0),
                seq_lens=c["llm_comm"],
            ),
            spec_index=_pad_flat(
                jnp.broadcast_to(jnp.arange(P)[None, :], (R, P)), cap_l, 0),
            ancestor_mask=self._pad_mask(amask, self.llm.max_spec_tokens),
            committed_lens=c["llm_comm"],
            commit_request_index=_pad_flat(
                jnp.where(commit_valid, slot[:, None], -1), cap_l, -1),
            commit_src_spec_index=_pad_flat(
                jnp.where(commit_valid, c["commit_src"], 0), cap_l, 0),
            commit_dst_position=_pad_flat(
                jnp.where(commit_valid, c["commit_dst"], 0), cap_l, 0),
        )
        # Stochastic verification (SpecInfer's sampling-based accept,
        # SURVEY §3.4): when ``sample`` is set, the verify step SAMPLES
        # y ~ p(target | node prefix) at every tree node (temperature +
        # top-p, seeded) instead of taking the argmax; the walk below then
        # accepts a child iff its draft token equals the sampled y.  Every
        # emitted token — accepted, correction, or bonus — is therefore a
        # fresh draw from the target conditional, so the output distribution
        # is EXACTLY the target model's sampling distribution for any draft
        # (per-node acceptance Σ p·q, vs Σ min(p,q) for the p/q-ratio
        # rejection rule — slightly lower acceptance, but no draft
        # distributions needed at verify time, and the same walk serves both
        # modes; T→0 recovers the greedy walk exactly).
        res_v, llm_state = self.llm._step_impl(
            llm_params, c["llm_state"], bc_v, sample, tree_layout=(R, P),
            pages=llm_pages)
        ids2 = res_v.token_ids[: R * P].reshape(R, P)              # [R, P]

        # ---- 4. accept walk (greedy or against the sampled tokens) ----
        def walk(wc, _):
            ni, alive = wc                                         # [R], [R]
            want = jnp.take_along_axis(ids2, ni[:, None], 1)[:, 0]
            match = (par == ni[:, None]) & (tok == want[:, None])  # [R, P]
            # non-spec rows accept no children (their tree arrays past the
            # root hold unexpanded garbage): they emit exactly the bonus
            # token per macro step — a plain decode in the shared batch
            found = match.any(1) & alive & smask
            child = jnp.argmax(match, 1).astype(jnp.int32)
            emit = jnp.where(alive, want, -1)
            src = jnp.where(found, child, -1)
            return (jnp.where(found, child, ni), found), (emit, src)

        (ni_f, alive_f), (emits, srcs) = jax.lax.scan(
            walk, (jnp.zeros((R,), jnp.int32), ~fin), None, length=D)
        emits = emits.T                                            # [R, D]
        srcs = srcs.T                                              # [R, D]
        bonus = jnp.where(
            alive_f,
            jnp.take_along_axis(ids2, ni_f[:, None], 1)[:, 0], -1)
        e = jnp.concatenate([emits, bonus[:, None]], axis=1)       # [R, D+1]
        f_cnt = jnp.sum(srcs >= 0, axis=1).astype(jnp.int32)       # children
        cnt = jnp.where(fin, 0, f_cnt + 1)   # accepted nodes incl. root

        # Device-side max-new exit: cut each row's emissions at its
        # remaining budget.  The budget cut runs BEFORE the EOS scan of
        # the survivors so the first terminator along the token stream
        # wins — exactly the host's per-token _maybe_finish order.
        bud = c["budget"]
        valid = e >= 0
        eidx = (jnp.cumsum(valid.astype(jnp.int32), axis=1)
                - valid.astype(jnp.int32))                         # [R, D+1]
        e_b = jnp.where(valid & (eidx < bud[:, None]), e, -1)

        # EOS: truncate after the first eos and freeze the slot
        if self.eos is not None:
            iseos = (e_b == self.eos) & (e_b >= 0)
            after = (jnp.cumsum(iseos.astype(jnp.int32), axis=1)
                     - iseos.astype(jnp.int32)) > 0
            e_out = jnp.where(after, -1, e_b)
            finishing = iseos.any(1)
        else:
            e_out = e_b
            finishing = jnp.zeros((R,), bool)
        n_emit = jnp.sum(e_out >= 0, axis=1).astype(jnp.int32)
        bud_new = jnp.where(fin, bud, bud - n_emit)
        hit_budget = ~fin & ~finishing & (bud_new <= 0)
        fin_new = fin | finishing | hit_budget
        cont = ~fin_new
        ecode = jnp.where(
            ~fin & finishing, EXIT_EOS,
            jnp.where(hit_budget, EXIT_BUDGET,
                      c["exit_code"])).astype(jnp.int32)

        # ---- bookkeeping for the next macro step ----
        commit_src = jnp.concatenate(
            [jnp.zeros((R, 1), jnp.int32), srcs], axis=1)          # [R, D+1]
        commit_dst = c["llm_comm"][:, None] + kk
        backlog_tok = jnp.concatenate([tok[:, :1], emits], axis=1)  # [R, D+1]
        root_new = jnp.take_along_axis(e, f_cnt[:, None], 1)[:, 0]  # bonus
        c2 = dict(
            llm_state=llm_state,
            ssm_state=ssm_state,
            macro_ctr=c["macro_ctr"] + 1,
            root=jnp.where(fin_new, c["root"], root_new),
            llm_comm=c["llm_comm"] + cnt,
            ssm_comm=ssm_comm,
            commit_src=commit_src,
            commit_dst=commit_dst,
            commit_n=jnp.where(cont, cnt, 0),
            backlog_tok=backlog_tok,
            backlog_n=jnp.where(cont, cnt, 0),
            finished=fin_new,
            spec=smask,
            budget=bud_new,
            exit_code=ecode,
        )
        return c2, e_out

    def _pad_mask(self, amask, pb: int):
        """[R, P, P] logical tree mask -> [R, pb, pb] buffer-shaped mask."""
        R, P, _ = amask.shape
        if pb == P:
            return amask
        out = jnp.zeros((R, pb, pb), bool)
        return jax.lax.dynamic_update_slice(out, amask, (0, 0, 0))
