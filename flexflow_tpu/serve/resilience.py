"""Resilient serving: admission control, retry policy, fault injection.

The serving stack through PR 4 is fail-fast: ``serve_with_arrivals`` admits
from an unbounded queue, a request can never be cancelled or time out, and
any transient dispatch/hop error kills the whole serve loop.  This module is
the host-side policy layer the :class:`~flexflow_tpu.serve.request_manager.
RequestManager` threads through its admit/retire loop (SpecInfer ASPLOS'24
keeps ALL of this in the host-side RequestManager; Orca OSDI'22's
iteration-level scheduling is what makes preemption-and-recompute natural —
a request's KV is always recomputable from ``prompt + generated``):

* :class:`ResilienceConfig` — admission control (bounded pending queue +
  ``plan_memory_bytes``-style KV headroom arithmetic), default TTL,
  preemption policy, and the dispatch-failure strategy;
* :class:`RetryPolicy` — exponential backoff with a bounded budget for
  transient dispatch faults;
* :class:`FaultInjector` — a SEEDED, deterministic chaos hook consulted at
  the InferenceManager's ``step``/``decode_scan``/``prefill_scan`` dispatch
  sites and at every pipeline-parallel stage dispatch/hop.  Faults raise
  BEFORE any work reaches the device, so a retried dispatch replays
  identical compute — survivors of a chaos run are bit-identical to the
  fault-free run (pinned by tests/test_resilience.py);
* :func:`kv_bytes_per_token` — the per-position committed-KV cost the
  admission gate prices new requests with.

Everything here is host-side Python: no policy decision is ever traced into
a jitted program, so attaching any of it cannot change compiled executables
or their outputs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np


class TransientServeError(RuntimeError):
    """A dispatch/hop failure that is worth retrying (the serve loop's
    retry guard catches exactly this type; anything else propagates)."""


class InjectedFault(TransientServeError):
    """Raised by :class:`FaultInjector` at an instrumented dispatch site."""

    def __init__(self, message: str, site: str = ""):
        super().__init__(message)
        self.site = site


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with a bounded budget.

    ``max_retries`` counts RE-dispatches after the first attempt
    (``max_retries=0`` fails a dispatch on its first fault).  Backoff for
    retry ``attempt`` (1-based) is ``backoff_s * backoff_mult**(attempt-1)``
    capped at ``max_backoff_s``.
    """

    max_retries: int = 3
    backoff_s: float = 0.01
    backoff_mult: float = 2.0
    max_backoff_s: float = 1.0

    def backoff(self, attempt: int) -> float:
        return min(self.backoff_s * self.backoff_mult ** max(attempt - 1, 0),
                   self.max_backoff_s)


@dataclasses.dataclass
class ResilienceConfig:
    """Policy knobs the RequestManager's resilient serving layer reads.

    Defaults keep every pre-existing behavior: unbounded pending queue, no
    KV admission gate, no TTL, no preemption — resilience is strictly
    opt-in per knob.

    * ``max_pending``: bounded pending queue — registrations beyond it get
      an explicit ``REJECTED`` outcome instead of silent unbounded growth.
    * ``kv_gate`` / ``kv_headroom_frac`` / ``kv_budget_bytes``: admission
      prices each request's worst-case cache need (``_seq_len_needed``
      positions x :func:`kv_bytes_per_token`) against a byte budget,
      summed over every live (pending + slotted) request — the same
      arithmetic family ``plan_memory_bytes`` gates serve plans with.
      ``kv_budget_bytes`` is an explicit cap (the knob under which int8
      and bf16 KV admit differently); when None the budget is
      ``kv_headroom_frac`` of the allocated cache's own capacity (pure
      position counting, since the cache prices itself).
    * ``default_ttl_s``: deadline applied to requests registered without an
      explicit ``ttl_s``/``deadline_s`` (None = no deadline).
    * ``preemption``: under slot pressure, evict the lowest-priority
      ``DECODING`` request (newest first among equals, bounded by
      ``max_preemptions``) to admit a strictly-higher-priority arrival; the
      victim re-enters the queue and recomputes ``prompt + generated`` on
      readmission, bit-identical to an unpreempted run.
    * ``on_dispatch_failure``: once a dispatch exhausts its retry budget,
      ``"requeue"`` recovers the affected requests by preempt-and-recompute
      (bounded by ``max_requeues``, then ``FAILED``); ``"fail"`` fails them
      immediately.  Either way the engine keeps serving everyone else.
    * ``host_tier_bytes``: capacity of the host-DRAM KV spill tier under
      the PAGED allocator (0 = off, the recompute-only status quo).  When
      on, every page-leaving path — preemption, page-pressure eviction,
      migration drain, brownout SPILL — copies the victim's written pages
      to host first, and readmission restores them (checksum-verified)
      instead of re-prefilling; a failed/corrupt restore falls back to
      the recompute feed bit-identically.  Swap transfers are guarded by
      the same ``retry`` policy at the ``kv_swap_out:``/``kv_swap_in:``
      fault sites.
    """

    max_pending: Optional[int] = None
    kv_gate: bool = False
    kv_headroom_frac: float = 1.0
    kv_budget_bytes: Optional[float] = None
    default_ttl_s: Optional[float] = None
    preemption: bool = False
    max_preemptions: int = 4
    on_dispatch_failure: str = "requeue"
    max_requeues: int = 2
    host_tier_bytes: int = 0
    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)

    def __post_init__(self):
        if self.on_dispatch_failure not in ("requeue", "fail"):
            raise ValueError(
                f"on_dispatch_failure {self.on_dispatch_failure!r} "
                "(expected 'requeue' or 'fail')")


class FaultInjector:
    """Seeded, deterministic dispatch-fault injection (chaos testing).

    Consulted host-side at each instrumented dispatch site BEFORE the work
    is handed to the device — an injected fault therefore never leaves
    partial device state behind, which is what makes retry-and-replay (and
    requeue-and-recompute) bit-identical to a fault-free run.

    ``p`` is the default per-call fault probability; ``p_by_site`` maps a
    substring of the site name to an override (first match wins), e.g.
    ``{"hop": 0.5}`` targets only pipeline stage hops, ``{"step": 1.0}``
    the single-program step dispatch.  ``max_faults`` bounds the total
    injected count — the lever that makes a seeded chaos run terminate
    deterministically whatever the retry budget.

    Instrumented site families: the manager dispatches (``step`` /
    ``decode_scan`` / ``prefill_scan`` + the pp ``stage{i}``/``hop``
    sites), the live-migration phases (``migration_drain`` /
    ``migration_rebuild`` / ``migration_readmit``), and the fleet
    router's per-replica sites (``fleet_dispatch:<name>`` — router →
    replica connectivity, consulted before every replica tick — and
    ``fleet_health:<name>``, the quarantine re-probe; see
    ``serve/fleet.py``'s health state machine).  The host-KV swap paths
    add ``kv_swap_out:<rid>`` / ``kv_swap_in:<rid>`` (spill capture and
    restore upload) — a fault there degrades to recompute, never to
    corruption, because the host copy is only trusted after its checksum
    verifies.
    """

    def __init__(self, seed: int = 0, p: float = 0.0,
                 p_by_site: Optional[Dict[str, float]] = None,
                 max_faults: Optional[int] = None):
        # retained verbatim so a traffic trace (obs/replay.py) can
        # record the full fault schedule's provenance and rebuild an
        # identical injector at replay time
        self.seed = int(seed)
        self.p = float(p)
        self.p_by_site = dict(p_by_site or {})
        self.max_faults = max_faults
        self.injected = 0
        self.calls = 0
        self._rng = np.random.RandomState(seed)

    def prob(self, site: str) -> float:
        for pat, pr in self.p_by_site.items():
            if pat in site:
                return float(pr)
        return self.p

    def maybe_fail(self, site: str) -> None:
        """Raise :class:`InjectedFault` for ``site`` per the seeded draw.

        Sites with probability 0 consume no randomness, so adding an
        un-targeted dispatch site never perturbs the fault schedule of a
        targeted one.
        """
        self.calls += 1
        pr = self.prob(site)
        if pr <= 0.0:
            return
        if self.max_faults is not None and self.injected >= self.max_faults:
            return
        if self._rng.random_sample() < pr:
            self.injected += 1
            raise InjectedFault(
                f"injected fault #{self.injected} at {site}", site=site)


def kv_bytes_per_token(im) -> Optional[float]:
    """Committed-KV bytes ONE REQUEST's cache position costs across the
    serve graph's attention ops (k + v planes and, under int8 KV, their
    f32 scale planes).

    THE one owner of this arithmetic is the manager's
    :class:`~flexflow_tpu.serve.kv_allocator.KVAllocator` — admission
    control, preemption pricing, and the memory ledger all read the same
    walk over the ALLOCATED buffers, so lane padding, kv dtype, and
    sharding can never diverge between them (the r9 duplicate shape walk
    that used to live here is deleted).  Returns None before
    ``init_operators_inference`` allocates caches — the admission gate
    then falls back to token-slot units.
    """
    kv = getattr(im, "kv", None)
    if kv is None:
        return None
    return kv.bytes_per_token()
