"""Fault-tolerant multi-replica fleet serving: the :class:`FleetRouter`.

The ROADMAP's "millions of users" axis needs a router over dp serve
replicas; through r18 a single manager was a single point of failure —
one faulted deployment took every in-flight request with it.  This
module composes the pieces that already landed into a fleet layer,
following the router-over-workers shape of Orca (OSDI'22) and the
disaggregated-worker direction of DistServe (OSDI'24):

* **N replica deployments** — each an ORDINARY manager built through the
  same :func:`~.migration.build_deployment` contract live migration's
  rebuild phase uses (any tp×pp×m×kv_dtype×paged×spec deployment is just
  a constructor call), each with its own KVAllocator and jitted
  programs, all sharing ONE GenerationConfig / Telemetry handle /
  ResilienceConfig / FaultInjector / clock / StepProfiler;
* **a shared admission queue** — requests register with the FLEET (one
  rid space spans every replica) and dispatch by telemetry-driven
  least-load: replica queue depth + KV occupancy fraction − open slots,
  plus a penalty for DEGRADED health and for an attached
  PlanHealthMonitor's breached checks
  (:func:`~flexflow_tpu.obs.plan_health.health_score`);
* **a per-replica health state machine** — ``HEALTHY → DEGRADED →
  QUARANTINED → DEAD``, driven by dispatch failures under the seeded
  :class:`~.resilience.FaultInjector` (new ``fleet_dispatch:<replica>``
  / ``fleet_health:<replica>`` sites) and by consecutive
  retry-exhaustions inside a replica's own dispatches (the
  ``RequestManager.on_exhausted`` hook routes exhaustion to the fleet
  instead of a terminal ``FAILED``).  QUARANTINED replicas re-probe on a
  period and readmit to the rotation; probes exhausting marks them DEAD
  (KV torn down, refcount no-leak);
* **failover with bit-identical recompute** — when a replica dies
  mid-decode, its in-flight requests re-dispatch onto survivors with
  their ORIGINAL rids through the r9 preemption-and-recompute path
  (re-prefill ``prompt + generated``).  Greedy AND seeded token streams
  are bit-identical to a never-failed run because every sample keys on
  the (rid, token_index) fold, which crosses replicas exactly as it
  crosses live-migration managers (pinned by tests/test_fleet.py);
* **graceful degradation under fleet shrink** — admission re-gates
  against the SURVIVING replicas' aggregate KV capacity, so shed load
  ends in an explicit ``REJECTED`` outcome, never ``FAILED``; a request
  no surviving replica can hold is rejected, not dropped;
* **rolling plan migration** — :meth:`FleetRouter.
  request_rolling_migration` drains/rebuilds ONE replica at a time
  through the existing :class:`~.migration.MigrationController`
  (drain → rebuild → readmit with rollback), so a fleet-wide plan
  switch never stops serving: at every tick at least ``n_replicas - 1``
  replicas keep admission open.

Everything here is host-side orchestration over existing manager
primitives; no fleet decision is ever traced into a jitted program, so
attaching the router cannot change what any replica's programs compute.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs.plan_health import health_score
from ..obs.profiler import profiler_or_null
from ..obs.telemetry import telemetry_or_null
from .migration import MigrationConfig, MigrationController, build_deployment
from .request_manager import (
    OUTCOMES,
    TERMINAL_STATUSES,
    GenerationConfig,
    Request,
    RequestManager,
    RequestStatus,
    parse_arrival_options,
)
from .resilience import ResilienceConfig, TransientServeError

# requests currently occupying an engine slot on a replica (the failover
# reclaim's preempt set — same tuple the migration drain uses)
_RUNNING = (RequestStatus.PREFILLING, RequestStatus.DECODING)


class ReplicaState(enum.Enum):
    """The per-replica health state machine.

    ``HEALTHY`` serves and takes new dispatches; ``DEGRADED`` keeps
    serving its in-flight requests but new dispatches avoid it (one
    success readmits it to HEALTHY); ``QUARANTINED`` holds no live
    requests (everything failed over on entry) and re-probes every
    ``FleetConfig.probe_every`` fleet ticks; ``DEAD`` is terminal — KV
    torn down, never probed again."""

    HEALTHY = 0
    DEGRADED = 1
    QUARANTINED = 2
    DEAD = 3


ALIVE_STATES = (ReplicaState.HEALTHY, ReplicaState.DEGRADED)


@dataclasses.dataclass
class FleetConfig:
    """Policy knobs for the fleet router.

    * ``degraded_after`` / ``quarantine_after``: consecutive dispatch
      failures (fleet-site faults or in-replica retry exhaustions)
      before a replica drops to DEGRADED / QUARANTINED.  One successful
      tick resets the streak (and readmits DEGRADED to HEALTHY).
    * ``probe_every``: fleet ticks between a QUARANTINED replica's
      re-probes (the seeded ``fleet_health:<name>`` injector site).
    * ``dead_after_probes``: failed probes before QUARANTINED becomes
      DEAD (KV teardown; terminal).
    * ``degraded_penalty``: least-load score penalty for DEGRADED
      replicas — new work prefers healthy ones but a degraded replica
      still beats an unbounded queue when it is all that remains.
    * ``max_failovers_per_request``: failovers one request may ride
      before it goes terminally FAILED — the bound that keeps a request
      from looping forever across a fleet whose every replica keeps
      failing (the fleet-level analog of r9's ``max_requeues``).
    * ``starvation_bound_ticks``: bounded aging for the dispatch queue's
      stable priority sort — a request queued longer than this many
      fleet ticks becomes OVERDUE and sorts ahead of every priority
      band (FIFO among overdue), so a lower-priority class held behind a
      sustained higher-priority stream is starved only up to this bound
      (pinned by the starvation test).  None disables aging.  A
      brownout DEFER hold is exempt: that is an explicit policy state
      with its own hysteresis-bounded exit, not priority competition.
    """

    degraded_after: int = 1
    quarantine_after: int = 3
    probe_every: int = 4
    dead_after_probes: int = 2
    degraded_penalty: float = 1000.0
    max_failovers_per_request: int = 8
    starvation_bound_ticks: Optional[int] = 256


@dataclasses.dataclass
class Replica:
    """One deployment in the rotation (router bookkeeping only — the
    serving state lives in ``rm``)."""

    name: str
    index: int
    rm: RequestManager
    state: ReplicaState = ReplicaState.HEALTHY
    failures: int = 0         # consecutive dispatch failures/exhaustions
    probe_failures: int = 0   # consecutive failed quarantine re-probes
    next_probe: int = 0       # fleet tick of the next re-probe
    had_exhaustion: bool = False  # set by the on_exhausted hook per tick
    ctrl: Optional[MigrationController] = None
    leaked: Optional[List[int]] = None  # teardown's no-leak check (DEAD)
    dispatched: int = 0       # requests ever placed here


def _allocators(rm: RequestManager) -> List:
    kvs = [getattr(rm.im, "kv", None)]
    ssm = getattr(rm, "ssm", None)
    if ssm is not None:
        kvs.append(getattr(ssm, "kv", None))
    return [kv for kv in kvs if kv is not None]


class FleetRouter:
    """Routes one request stream over N replica deployments.

    ``replicas``: deployments in the :func:`~.migration.build_deployment`
    contract — each a ready :class:`~.request_manager.RequestManager`, a
    single InferenceManager-like object, or an ``(llm_im, ssm_im)`` pair.
    Non-manager deployments are wrapped sharing the fleet's
    gen/telemetry/resilience/injector/clock/profiler, which is what makes
    seeded bit-identity hold across replicas by construction.  For
    bit-identity with a single-replica run, replicas of one model must be
    built with IDENTICAL weights (same init seed / checkpoint).

    The router owns the rid space: :meth:`register` validates and
    admission-gates against the surviving fleet, :meth:`serve_all` /
    :meth:`generate` / :meth:`serve_with_arrivals` drive the replicas
    round-robin (one replica tick each per fleet tick), and
    :meth:`kill_replica` / :meth:`schedule_kill` are the chaos levers the
    seeded tests and the hermetic bench section drive.
    """

    def __init__(self, replicas: Sequence, gen: Optional[GenerationConfig]
                 = None, telemetry=None,
                 resilience: Optional[ResilienceConfig] = None,
                 fault_injector=None, clock=None, profiler=None,
                 config: Optional[FleetConfig] = None,
                 names: Optional[Sequence[str]] = None,
                 slo=None, brownout=None):
        import time as _time

        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.gen = gen or GenerationConfig()
        self.telemetry = telemetry_or_null(telemetry)
        self.res = resilience or ResilienceConfig()
        self.injector = fault_injector
        self.clock = clock or _time.perf_counter
        self.profiler = profiler_or_null(profiler)
        self.config = config or FleetConfig()
        # SLO-class lanes + brownout (serve/slo.py): the FLEET owns the
        # policy and the one ladder over the whole fleet.  Replicas get
        # references to both (queue-hold/preemption gates) but the
        # ladder is EVALUATED only here — never double-driven, see the
        # replica loop below.  Attaching a policy without a controller
        # builds one on the fleet's clock/telemetry: configuring lanes
        # opts into graceful degradation.
        self.slo = slo
        if brownout is None and slo is not None:
            from .slo import BrownoutController

            brownout = BrownoutController(slo, telemetry=telemetry,
                                          clock=self.clock)
        self.brownout = brownout
        if brownout is not None and slo is None:
            self.slo = brownout.policy
        # per-class committed-need high-watermarks (same units as the
        # admission budget) — the observable the reservation contract is
        # asserted against ("batch never dipped into the lc reservation")
        self.lane_committed_hwm: Dict[str, float] = {}
        self._enqueue_tick: Dict[int, int] = {}  # rid -> fleet tick queued
        self.replicas: List[Replica] = []
        for i, dep in enumerate(replicas):
            name = names[i] if names else f"replica{i}"
            if isinstance(dep, RequestManager):
                rm = dep
                rm.clock = self.clock
            else:
                rm = build_deployment(
                    dep, self.gen, telemetry=telemetry,
                    resilience=self.res, fault_injector=fault_injector,
                    clock=self.clock,
                    profiler=profiler if self.profiler.enabled else None)
            rm.on_exhausted = self._on_replica_exhausted
            # replica-level bounded aging: the satellite's starvation
            # bound applies wherever the priority sort actually queues —
            # the fleet dispatch queue AND each replica's pending queue
            rm.starvation_bound_ticks = self.config.starvation_bound_ticks
            # the lane policy + ladder reach the replica's OWN queue
            # gates (_pop_pending holds, preemption eligibility) so a
            # DEFER really holds replica-pending work too; the ladder is
            # still EVALUATED only by the fleet — RequestManager's
            # _maybe_brownout runs from its own serve loops, which the
            # fleet never drives
            rm.slo = self.slo
            rm.brownout = self.brownout
            self.replicas.append(Replica(name=name, index=i, rm=rm))
            if self.telemetry.enabled:
                self.telemetry.replica_up(name, reason="fleet start")
        # fleet-owned request bookkeeping: ONE rid space over every
        # replica (the (rid, token_index) sample fold crosses replicas,
        # so a failed-over request's stream is bit-identical wherever it
        # lands); ``requests[rid]`` always points at the LIVE object —
        # re-pointed when a placement converts the record class
        self.requests: Dict[int, Request] = {}
        self.queue: List[int] = []       # fleet admission queue (rids)
        self.placement: Dict[int, str] = {}   # rid -> serving replica
        self._next_rid = 0
        self._tstamps: Dict[int, Dict[str, float]] = {}
        self._live: set = set()          # non-terminal rids (O(live) scans)
        self._spec_pref: Dict[int, Optional[bool]] = {}
        self._failover_from: Dict[int, str] = {}   # rid -> failed replica
        self._failover_counts: Dict[int, int] = {}
        self.ticks = 0
        self.history: List[Dict] = []    # fleet-level event log
        self._rolling: Optional[Dict] = None
        self._kills: Dict[str, int] = {}  # name -> fleet tick to kill at

    # ------------------------------------------------------------------
    # replica lookup / health accounting
    # ------------------------------------------------------------------
    def _by_name(self, name: str) -> Replica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica named {name!r}")

    def _alive(self) -> List[Replica]:
        return [rep for rep in self.replicas if rep.state in ALIVE_STATES]

    def _rep_of(self, rm) -> Optional[Replica]:
        for rep in self.replicas:
            if rep.rm is rm:
                return rep
        return None

    def replicas_serving(self) -> int:
        """Alive replicas with admission OPEN — the rolling-migration
        invariant the tests pin is that this never drops below
        ``len(alive) - 1`` (one replica drains at a time)."""
        return sum(1 for rep in self._alive()
                   if not rep.rm.admission_closed)

    def fleet_snapshot(self) -> Dict:
        """The router's live view (pure read): per-replica state/load +
        fleet aggregates."""
        return {
            "replicas": {
                rep.name: {
                    "state": rep.state.name,
                    "queue_depth": len(rep.rm.pending),
                    "open_slots": sum(1 for s in rep.rm.slots if s is None),
                    "admission_closed": rep.rm.admission_closed,
                    "dispatched": rep.dispatched,
                    "failures": rep.failures,
                } for rep in self.replicas},
            "healthy": sum(1 for r in self.replicas
                           if r.state is ReplicaState.HEALTHY),
            "alive": len(self._alive()),
            "queue_depth": len(self.queue),
            "ticks": self.ticks,
        }

    # ------------------------------------------------------------------
    # registration / shared admission queue
    # ------------------------------------------------------------------
    def _need(self, req: Request) -> int:
        """Worst-case cache positions a request commits — fleet-level
        arithmetic (a spec replica may need more; the per-replica gates
        still apply at its own ``_seq_len_needed``)."""
        return len(req.prompt) + req.max_new_tokens

    def _admission_reason(self, req: Request) -> Optional[str]:
        """The fleet capacity gate: rejection reason, or None to admit.

        Re-derives the budget from the SURVIVING replicas on every call —
        after a fleet shrink the same arrival stream gates against the
        smaller aggregate KV capacity, so shed load ends in an explicit
        ``REJECTED``, never a ``FAILED`` (the graceful-degradation
        contract)."""
        res = self.res
        alive = self._alive()
        if res.max_pending is not None:
            backlog = len(self.queue) + sum(len(rep.rm.pending)
                                            for rep in alive)
            if backlog >= res.max_pending:
                return (f"pending queue full ({backlog} >= "
                        f"{res.max_pending})")
        reason = self._lane_admission_reason(req)
        if reason is not None:
            return reason
        if res.kv_gate:
            cap_tokens = 0
            per_toks = []
            for rep in alive:
                kv = getattr(rep.rm.im, "kv", None)
                cap_tokens += (kv.capacity_tokens if kv is not None
                               else rep.rm.im.max_requests
                               * rep.rm.im.max_seq_len)
                pt = kv.bytes_per_token() if kv is not None else None
                if pt:
                    per_toks.append(pt)
            live = [self.requests[r] for r in self._live
                    if self.requests[r].status not in TERMINAL_STATUSES]
            need = sum(self._need(r) for r in live) + self._need(req)
            if res.kv_budget_bytes is not None:
                if not per_toks:
                    return ("kv_budget_bytes is a byte cap but no "
                            "surviving replica has allocated KV caches")
                # price at the PRICIEST surviving replica's bytes/token —
                # placement is not known at admission time, so the gate
                # errs high (fail-safe, the r9 capacity-contract family)
                per_tok = max(per_toks)
                if need * per_tok > res.kv_budget_bytes:
                    return (f"KV headroom: {need * per_tok / 2**20:.2f} "
                            f"MiB committed > "
                            f"{res.kv_budget_bytes / 2**20:.2f} MiB budget")
                budget, price = res.kv_budget_bytes, per_tok
            else:
                if need > res.kv_headroom_frac * cap_tokens:
                    return (f"KV headroom: {need} tokens committed > "
                            f"{res.kv_headroom_frac * cap_tokens:.0f} "
                            f"across {len(alive)} surviving replicas")
                budget, price = res.kv_headroom_frac * cap_tokens, 1.0
            # reserved-lane gate (serve/slo.py): same fleet-aggregate
            # budget and worst-case-need arithmetic — each class's
            # committed charges its own reservation first, only overflow
            # competes for the shared pool, so batch traffic can never
            # consume the latency-critical lane's reservation whatever
            # the arrival order (the hwm tracking in _maybe_brownout is
            # the observable this contract is asserted against)
            reason = self._lane_reservation_reason(req, live, budget,
                                                   price)
            if reason is not None:
                return reason
        return None

    def _lane_reservation_reason(self, req: Request, live, budget: float,
                                 per_tok: float) -> Optional[str]:
        slo = self.slo
        if slo is None or not any(c.kv_reservation_frac
                                  for c in slo.classes.values()):
            return None
        cls = slo.resolve(req.slo_class)
        if cls is None:
            return None
        from .slo import reservation_reason

        by_cls: Dict[str, float] = {}
        for r in live:
            rc = slo.resolve(r.slo_class)
            key = rc.name if rc is not None else r.slo_class
            by_cls[key] = by_cls.get(key, 0.0) + self._need(r) * per_tok
        return reservation_reason(slo, by_cls, cls,
                                  self._need(req) * per_tok, budget)

    def _lane_admission_reason(self, req: Request) -> Optional[str]:
        """Lane-level fleet admission: the brownout ladder's gate for
        degradable classes + the per-class bounded pending queue
        (fleet queue and replica pendings count together — one lane
        spans the fleet)."""
        if self.slo is None:
            return None
        cls = self.slo.resolve(req.slo_class)
        if cls is None:
            return None
        bo = self.brownout
        if bo is not None and not bo.admits(cls.name):
            if self.telemetry.enabled:
                self.telemetry.lane_shed(cls.name, trace_id=req.trace_id,
                                         reason=f"brownout:{bo.level.name}")
            return (f"brownout {bo.level.name}: class {cls.name!r} "
                    "admissions shed")
        if cls.max_pending is not None:
            depth = sum(
                1 for rid in self.queue
                if self.requests[rid].slo_class == cls.name)
            for rep in self._alive():
                depth += sum(1 for rid in rep.rm.pending
                             if rep.rm.requests[rid].slo_class == cls.name)
            if depth >= cls.max_pending:
                return (f"class {cls.name!r} pending queue full "
                        f"({depth} >= {cls.max_pending})")
        return None

    def register(self, prompt_tokens: Sequence[int],
                 max_new_tokens: Optional[int] = None, *,
                 priority: int = 0, ttl_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 reject_invalid: bool = False,
                 reject_reason: Optional[str] = None,
                 spec: Optional[bool] = None,
                 slo_class: Optional[str] = None) -> int:
        """Register a request with the fleet; returns its rid.

        Mirrors :meth:`RequestManager.register_new_request` semantics: a
        shape no SURVIVING replica can hold raises (or, with
        ``reject_invalid`` — the arrival loop's mode — registers a
        terminal ``REJECTED`` record); capacity rejections always take
        the explicit ``REJECTED`` path; ``max_new_tokens=0`` completes
        immediately.  ``spec`` is the request's speculation preference,
        applied when (and only when) it lands on a spec-capable replica.
        ``slo_class`` names the request's lane under an attached
        :class:`~.slo.SLOPolicy` (None/"" = the default class; unknown
        names reject) — the class's priority band, bounded queue, KV
        reservation, and brownout gates apply at the FLEET gate.
        """
        req = Request(
            -1, [int(t) for t in prompt_tokens],
            self.gen.max_new_tokens if max_new_tokens is None
            else int(max_new_tokens))
        band = 0
        if self.slo is not None:
            cls = self.slo.resolve(slo_class)
            if cls is None:
                req.slo_class = str(slo_class)
            else:
                req.slo_class = cls.name
                band = cls.priority_band
        alive = self._alive()
        err = reject_reason
        if err is None:
            if not alive:
                err = "no surviving replica"
            else:
                errs = [rep.rm._validate_request(req) for rep in alive]
                if all(e is not None for e in errs):
                    err = errs[0]
        if err is None and self.slo is not None \
                and self.slo.resolve(slo_class) is None:
            err = f"unknown slo_class {slo_class!r}"
        if err is not None and not reject_invalid:
            raise ValueError(err)
        rid = self._next_rid
        self._next_rid += 1
        req.rid = rid
        req.trace_id = f"r{rid:05d}"
        req.priority = int(priority) + band
        self.requests[rid] = req
        self._spec_pref[rid] = spec
        tel = self.telemetry
        if tel.enabled:
            self._tstamps[rid] = {
                "enqueue": tel.request_enqueued(
                    req.trace_id, prompt_len=len(req.prompt))}
        reason = err if err is not None else self._admission_reason(req)
        if reason is not None:
            self._terminate(req, RequestStatus.REJECTED, reason=reason)
            return rid
        if req.max_new_tokens == 0:
            req.status = RequestStatus.COMPLETED
            req.outcome = "ok"
            if tel.enabled:
                tel.request_finished(req.trace_id, n_tokens=0,
                                     slo_class=req.slo_class or None)
            return rid
        if self.brownout is not None and self.brownout.degrades(
                req.slo_class):
            # DEGRADE_BATCH in force: admitted, but speculation off and
            # the class output cap applied (prefix truncation only).
            # Counted only on real change (exact-compare counter)
            changed = bool(self._spec_pref.get(rid))
            self._spec_pref[rid] = False
            cap = self.brownout.output_cap(req.slo_class)
            if cap is not None and cap < req.max_new_tokens:
                req.max_new_tokens = max(cap, 1)
                changed = True
            if changed and tel.enabled:
                tel.lane_degraded(req.slo_class)
        if deadline_s is not None:
            req.deadline_s = float(deadline_s)
        else:
            ttl = ttl_s if ttl_s is not None else self.res.default_ttl_s
            if ttl is not None:
                req.deadline_s = self.clock() + float(ttl)
        self.queue.append(rid)
        self._live.add(rid)
        self._enqueue_tick[rid] = self.ticks
        return rid

    def cancel(self, rid: int) -> bool:
        """Fleet-wide cancel: reaped at the owning replica's next step
        boundary (or immediately if still fleet-queued).  Returns whether
        the request was live."""
        req = self.requests.get(rid)
        if req is None or req.status in TERMINAL_STATUSES:
            return False
        req.cancel_requested = True
        return True

    def _terminate(self, req: Request, status: RequestStatus,
                   reason: str = "") -> None:
        """Terminal transition for a request the FLEET holds (queued or
        reclaimed — never slotted; slotted requests terminate through
        their replica's own paths)."""
        if req.rid in self.queue:
            self.queue.remove(req.rid)
        self._live.discard(req.rid)
        self._enqueue_tick.pop(req.rid, None)
        req.status = status
        req.outcome = OUTCOMES[status]
        req.prefill_src = None
        tel = self.telemetry
        if status is RequestStatus.REJECTED:
            # shed load must not grow host memory (the r9 contract): the
            # retained record is a small fixed-size stub
            req.prompt = []
            if tel.enabled:
                tel.request_rejected(req.trace_id, reason=reason)
        elif tel.enabled:
            n = len(req.generated)
            if status is RequestStatus.CANCELLED:
                tel.request_cancelled(req.trace_id, n_tokens=n)
            elif status is RequestStatus.TIMED_OUT:
                tel.request_timed_out(req.trace_id, n_tokens=n)
            elif status is RequestStatus.FAILED:
                tel.request_failed(req.trace_id, site=reason)

    def _check_lifecycle(self) -> None:
        """Step-boundary reaping for FLEET-QUEUED requests (replica-held
        requests are reaped by their own manager's ``_check_lifecycle``
        each replica tick)."""
        expirable = [self.requests[rid] for rid in self.queue]
        expirable = [r for r in expirable
                     if r.cancel_requested or r.deadline_s is not None]
        if not expirable:
            return
        now = self.clock()
        for req in expirable:
            if req.cancel_requested:
                self._terminate(req, RequestStatus.CANCELLED)
            elif req.deadline_s is not None and now >= req.deadline_s:
                self._terminate(req, RequestStatus.TIMED_OUT)

    def _reap_terminal(self) -> None:
        for rid in list(self._live):
            if self.requests[rid].status in TERMINAL_STATUSES:
                self._live.discard(rid)

    def _swap_clock(self, new_clock):
        """Switch the fleet's deadline clock, re-basing armed deadlines
        of FLEET-QUEUED requests (replica-held ones re-base through
        their own manager's ``_swap_clock``).  Returns the previous
        clock for the symmetric restore."""
        old = self.clock
        if new_clock is old:
            return old
        armed = [self.requests[r] for r in self.queue
                 if self.requests[r].deadline_s is not None]
        if armed:
            old_now, new_now = old(), new_clock()
            for req in armed:
                req.deadline_s = new_now + (req.deadline_s - old_now)
        self.clock = new_clock
        return old

    # ------------------------------------------------------------------
    # least-load dispatch
    # ------------------------------------------------------------------
    def _load(self, rep: Replica) -> float:
        """Telemetry-driven least-load score: replica queue depth + KV
        occupancy fraction − open slots, plus DEGRADED and plan-health
        penalties.  Lower dispatches first; ties break on replica index
        (deterministic routing — the chaos tests replay it)."""
        rm = rep.rm
        open_slots = sum(1 for s in rm.slots if s is None)
        kv = getattr(rm.im, "kv", None)
        occ = 0.0
        if kv is not None and kv.capacity_tokens:
            occ = kv.live_tokens() / kv.capacity_tokens
        score = float(len(rm.pending)) + occ - float(open_slots)
        if rep.state is ReplicaState.DEGRADED:
            score += self.config.degraded_penalty
        mon = getattr(rm, "plan_health", None)
        if mon is not None:
            score += health_score(getattr(mon, "last_report", None))
        return score

    def _place(self, rid: int, rep: Replica) -> None:
        """Transplant a fleet-held request onto a replica, preserving its
        rid, recompute feed, deadline, and telemetry stamps (the
        migration ``_readmit`` pattern — record class converted when the
        replica's manager extends it)."""
        req = self.requests[rid]
        rm = rep.rm
        if type(req) is not rm.request_cls:
            nr = rm.request_cls(req.rid, list(req.prompt),
                                req.max_new_tokens)
            for f in ("trace_id", "priority", "deadline_s",
                      "cancel_requested", "preemptions", "requeues",
                      "kv_bytes", "n_prefed", "status", "slo_class",
                      "deferred_ticks"):
                setattr(nr, f, getattr(req, f))
            nr.generated = list(req.generated)
            nr.prefill_src = (list(req.prefill_src)
                              if req.prefill_src is not None else None)
            req = nr
            self.requests[rid] = nr
        pref = self._spec_pref.get(rid)
        req.spec = (bool(getattr(rm, "default_spec_mode", False))
                    if pref is None else bool(pref)) \
            if hasattr(rm, "ssm") else False
        req.slot = -1
        req.starved_steps = 0
        rm.requests[rid] = req
        rm.pending.append(rid)
        rm._pending_since[rid] = rm.steps
        rm._next_rid = max(rm._next_rid, self._next_rid)
        rm._tstamps[rid] = self._tstamps.setdefault(rid, {})
        self.placement[rid] = rep.name
        self._enqueue_tick.pop(rid, None)
        rep.dispatched += 1
        frm = self._failover_from.pop(rid, None)
        if frm is not None:
            # host-tier KV failover: the reclaim's preempt spilled this
            # request's pages into the FAILED replica's host tier (which
            # survives its KV teardown — host copies stay valid, KV is a
            # pure function of the fed tokens).  Adopt them onto the
            # survivor so readmission restores instead of re-prefilling;
            # a shape-mismatched survivor (adopt_spills signature check)
            # falls back to the recompute feed the reclaim preserved.
            src_rm = self._by_name(frm).rm
            for src_kv, dst_kv in zip(_allocators(src_rm),
                                      _allocators(rm)):
                dst_kv.adopt_spills(src_kv, [rid])
            if self.telemetry.enabled:
                self.telemetry.request_failed_over(req.trace_id, frm,
                                                   rep.name)

    def _dispatch_queue(self) -> None:
        if not self.queue:
            return
        alive = self._alive()
        if not alive:
            if all(rep.state is ReplicaState.DEAD
                   for rep in self.replicas):
                # total fleet loss: every queued request sheds EXPLICITLY
                for rid in list(self.queue):
                    self._terminate(self.requests[rid],
                                    RequestStatus.REJECTED,
                                    reason="no surviving replica")
            # otherwise QUARANTINED replicas may still re-probe and
            # readmit: an already-admitted request waits (its TTL and
            # the bounded probe schedule keep the wait finite) — only
            # the truly terminal all-DEAD fleet sheds it
            return
        # priority order, FIFO within a class (stable sort — the same
        # rule RequestManager._pop_pending applies per replica), with
        # BOUNDED AGING: a request queued past
        # ``config.starvation_bound_ticks`` becomes OVERDUE and sorts
        # ahead of every priority band (FIFO among overdue, by enqueue
        # tick), so a sustained higher-priority stream can starve a
        # lower class only up to the bound
        bound = self.config.starvation_bound_ticks

        def overdue(rid: int) -> bool:
            return (bound is not None
                    and self.ticks - self._enqueue_tick.get(rid, self.ticks)
                    >= bound)

        self.queue.sort(key=lambda rid: (
            (0, self._enqueue_tick.get(rid, 0)) if overdue(rid)
            else (1, -self.requests[rid].priority)))
        takers = [rep for rep in alive if not rep.rm.admission_closed]
        remaining: List[int] = []
        bo = self.brownout
        # snapshot: _terminate mutates self.queue (rejection path), and
        # iterating the live list would silently skip the next entry
        for rid in list(self.queue):
            req = self.requests[rid]
            if bo is not None and bo.holds(req.slo_class):
                # DEFER_BATCH: held in the fleet queue — an explicit
                # policy hold with its own hysteresis-bounded exit
                # (aging does not override it; TTLs still apply).  The
                # hold time is EXEMPT from aging: re-stamp so the held
                # backlog does not come out of a long brownout overdue
                # and jump the latency-critical lane at recovery
                self._enqueue_tick[rid] = self.ticks
                remaining.append(rid)
                continue
            cands = [rep for rep in takers
                     if rep.rm._validate_request(req) is None]
            if not cands:
                # shed only when NO non-dead replica could ever hold it
                # (a quarantined holder may readmit; a draining one
                # reopens) — explicit REJECTED, never FAILED
                if not any(rep.rm._validate_request(req) is None
                           for rep in self.replicas
                           if rep.state is not ReplicaState.DEAD):
                    self._terminate(
                        req, RequestStatus.REJECTED,
                        reason="no surviving replica can hold request")
                else:
                    remaining.append(rid)
                continue
            rep = min(cands, key=lambda p: (self._load(p), p.index))
            self._place(rid, rep)
        self.queue = remaining

    # ------------------------------------------------------------------
    # failover + the health state machine
    # ------------------------------------------------------------------
    def _reclaim(self, rep: Replica, rids: Sequence[int],
                 reason: str) -> List[int]:
        """Pull live requests OFF a replica back into the shared queue
        for failover: running ones preempt (slot + KV release, recompute
        feed built — the r9 path), queued ones just move.  Requests past
        the per-request failover bound go terminally FAILED."""
        rm = rep.rm
        moved: List[int] = []
        for rid in rids:
            req = rm.requests.get(rid)
            if req is None or req.status in TERMINAL_STATUSES:
                continue
            if req.status in _RUNNING:
                rm.preempt(rid)
            if rid in rm.pending:
                rm.pending.remove(rid)
            rm._pending_since.pop(rid, None)
            rm.requests.pop(rid, None)
            rm._tstamps.pop(rid, None)
            self.requests[rid] = req
            self._failover_from[rid] = rep.name
            self._failover_counts[rid] = \
                self._failover_counts.get(rid, 0) + 1
            moved.append(rid)
        kept: List[int] = []
        for rid in moved:
            if (self._failover_counts[rid]
                    > self.config.max_failovers_per_request):
                self._terminate(self.requests[rid], RequestStatus.FAILED,
                                reason=reason)
            else:
                kept.append(rid)
                # the wait clock restarts on failover: aging measures
                # time queued for THIS dispatch
                self._enqueue_tick[rid] = self.ticks
        self.queue.extend(kept)
        return kept

    def _live_rids_on(self, rm: RequestManager) -> List[int]:
        slotted = [r.rid for r in rm._active()
                   if r.status not in TERMINAL_STATUSES]
        return list(rm.pending) + [r for r in slotted
                                   if r not in rm.pending]

    def _failover_all(self, rep: Replica, reason: str) -> List[int]:
        return self._reclaim(rep, self._live_rids_on(rep.rm), reason)

    def _note_failure(self, rep: Replica, site: str) -> None:
        cfg = self.config
        rep.failures += 1
        tel = self.telemetry
        if (rep.state is ReplicaState.HEALTHY
                and rep.failures >= cfg.degraded_after):
            rep.state = ReplicaState.DEGRADED
            if tel.enabled:
                tel.replica_degraded(rep.name, reason=site)
        if (rep.state is ReplicaState.DEGRADED
                and rep.failures >= cfg.quarantine_after):
            self._quarantine(rep, site)

    def _note_success(self, rep: Replica) -> None:
        rep.failures = 0
        if rep.state is ReplicaState.DEGRADED:
            rep.state = ReplicaState.HEALTHY
            if self.telemetry.enabled:
                self.telemetry.replica_up(rep.name, reason="recovered")

    def _quarantine(self, rep: Replica, reason: str) -> None:
        rep.state = ReplicaState.QUARANTINED
        rep.probe_failures = 0
        rep.next_probe = self.ticks + self.config.probe_every
        if self.telemetry.enabled:
            self.telemetry.replica_quarantined(rep.name, reason=reason)
        moved = self._failover_all(rep, reason)
        self.history.append({"event": "replica_quarantined",
                             "replica": rep.name, "reason": reason,
                             "failed_over": len(moved),
                             "tick": self.ticks})

    def _mark_dead(self, rep: Replica, reason: str) -> List[int]:
        """Terminal replica death: fail over whatever still lives there,
        tear down its KV ownership (the refcount no-leak check — after
        the failover every binding released on its slot-leaving path),
        and retire it from the rotation."""
        moved = self._failover_all(rep, reason)
        leaked: List[int] = []
        for kv in _allocators(rep.rm):
            leaked.extend(kv.teardown())
        rep.leaked = sorted(set(leaked))
        rep.state = ReplicaState.DEAD
        rep.rm.admission_closed = True
        rep.rm.pending = []
        # release the dead deployment's jitted programs from the
        # profiler's recompile poll (the migration-commit pattern)
        prof = self.profiler
        if prof.enabled:
            prof.uninstall(rep.rm.im)
            ssm = getattr(rep.rm, "ssm", None)
            if ssm is not None:
                prof.uninstall(ssm)
        if self.telemetry.enabled:
            self.telemetry.replica_dead(rep.name, reason=reason,
                                        failed_over=len(moved))
        self.history.append({"event": "replica_dead", "replica": rep.name,
                             "reason": reason, "failed_over": len(moved),
                             "kv_leaked_rids": rep.leaked,
                             "tick": self.ticks})
        return moved

    def _maybe_probe(self, rep: Replica) -> None:
        """Quarantine re-probe on the seeded ``fleet_health:<name>``
        site: success readmits the replica HEALTHY; ``dead_after_probes``
        consecutive failures retire it DEAD."""
        if self.ticks < rep.next_probe:
            return
        site = f"fleet_health:{rep.name}"
        tel = self.telemetry
        try:
            if self.injector is not None:
                self.injector.maybe_fail(site)
        except TransientServeError as e:
            if tel.enabled:
                tel.fault_observed(site, detail=str(e))
            rep.probe_failures += 1
            if rep.probe_failures >= self.config.dead_after_probes:
                self._mark_dead(rep, "quarantine probes exhausted")
            else:
                rep.next_probe = self.ticks + self.config.probe_every
            return
        rep.state = ReplicaState.HEALTHY
        rep.failures = 0
        rep.probe_failures = 0
        if tel.enabled:
            tel.replica_up(rep.name, reason="probe ok")
        self.history.append({"event": "replica_readmitted",
                             "replica": rep.name, "tick": self.ticks})

    def _on_replica_exhausted(self, rm, site, exc, affected_fn) -> bool:
        """The ``RequestManager.on_exhausted`` hook: a replica dispatch
        exhausted its retry budget.  Instead of the single-manager
        requeue-or-FAIL, the affected requests fail over — preempted off
        the replica (r9 recompute feeds built) and re-queued for
        dispatch to a survivor — and the exhaustion counts against the
        replica's health streak.  Returns True (handled)."""
        rep = self._rep_of(rm)
        if rep is None or rep.state is ReplicaState.DEAD:
            return False  # not (or no longer) ours: default r9 recovery
        if affected_fn is not None:
            affected = list(affected_fn())
        else:
            affected = [r.rid for r in rm._active() if r.status in _RUNNING]
        self._reclaim(rep, affected, site)
        rep.had_exhaustion = True
        self._note_failure(rep, site)
        return True

    # ------------------------------------------------------------------
    # chaos levers
    # ------------------------------------------------------------------
    def kill_replica(self, name: str, reason: str = "operator kill"
                     ) -> List[int]:
        """Immediately kill a replica (chaos/operator lever): in-flight
        requests fail over to survivors mid-decode with their original
        rids, the dead replica's KV tears down refcount-clean, and the
        failovers re-dispatch without waiting for the next fleet tick.
        Returns the failed-over rids."""
        rep = self._by_name(name)
        if rep.state is ReplicaState.DEAD:
            return []
        if rep.ctrl is not None and rep.ctrl._staged is not None:
            # a migration staged on a dying replica can never execute
            rep.ctrl._staged = None
        moved = self._mark_dead(rep, reason)
        self._dispatch_queue()
        return moved

    def schedule_kill(self, name: str, at_tick: int) -> None:
        """Arrange :meth:`kill_replica` at fleet tick ``at_tick`` —
        deterministic on the virtual clock (the seeded chaos runs and
        the hermetic bench section stage mid-decode deaths with it)."""
        self._kills[name] = int(at_tick)

    # ------------------------------------------------------------------
    # rolling plan migration (one replica at a time)
    # ------------------------------------------------------------------
    def request_rolling_migration(self, candidate, build_manager: Callable,
                                  migration_config: Optional[
                                      MigrationConfig] = None) -> None:
        """Stage a fleet-wide plan switch executed as a ROLLING migration:
        each alive replica in turn drains/rebuilds/readmits through its
        own :class:`~.migration.MigrationController` (rollback included),
        strictly one at a time — so at every tick all but one replica
        keep admission open and the fleet never stops serving.  A
        rollback on any replica ABORTS the remaining rollout (the
        candidate plan demonstrably cannot build)."""
        if self._rolling is not None:
            raise ValueError("a rolling migration is already in progress")
        if isinstance(candidate, str):
            candidate = {"plan_key": candidate}
        self._rolling = {
            "candidate": dict(candidate),
            "build": build_manager,
            "config": migration_config
            or MigrationConfig(auto=False, drain_grace_ticks=1),
            "remaining": [rep.name for rep in self.replicas
                          if rep.state is not ReplicaState.DEAD],
            "active": None,
            "records": [],
        }

    def _ensure_controller(self, rep: Replica, build: Callable,
                           config: MigrationConfig) -> MigrationController:
        if rep.ctrl is None:
            def on_switch(new_rm, _rep=rep):
                self._adopt_successor(_rep, new_rm)

            rep.ctrl = MigrationController(rep.rm, build, config=config,
                                           on_switch=on_switch)
        else:
            rep.ctrl.build_manager = build
            rep.ctrl.config = config
        return rep.ctrl

    def _advance_rolling(self) -> None:
        r = self._rolling
        if r is None:
            return
        if r["active"] is not None:
            rep = self._by_name(r["active"])
            ctrl = rep.ctrl
            if rep.state is ReplicaState.DEAD:
                # the draining replica died mid-migration: its requests
                # already failed over; drop its slot in the schedule
                if ctrl is not None:
                    ctrl._staged = None
                r["records"].append({"replica": rep.name,
                                     "outcome": "died_mid_migration"})
                r["active"] = None
            elif ctrl is not None and ctrl._staged is not None:
                return  # in flight: ONE replica at a time
            else:
                rec = (ctrl.history[-1] if ctrl and ctrl.history
                       else {"outcome": "unknown"})
                r["records"].append({
                    "replica": rep.name,
                    **{k: rec.get(k) for k in
                       ("outcome", "candidate", "downtime_ticks",
                        "preempted_requests", "phase", "reason")
                       if k in rec}})
                r["active"] = None
                if rec.get("outcome") == "rolled_back":
                    self.history.append({
                        "event": "rolling_migration_aborted",
                        "candidate": r["candidate"].get("plan_key"),
                        "failed_replica": rep.name,
                        "replicas": r["records"], "tick": self.ticks})
                    self._rolling = None
                    return
        while r["active"] is None and r["remaining"]:
            name = r["remaining"].pop(0)
            rep = self._by_name(name)
            if rep.state not in ALIVE_STATES:
                r["records"].append({
                    "replica": name,
                    "outcome": f"skipped_{rep.state.name.lower()}"})
                continue
            ctrl = self._ensure_controller(rep, r["build"], r["config"])
            ctrl.request_migration(dict(r["candidate"]))
            r["active"] = name
        if r["active"] is None and not r["remaining"]:
            self.history.append({
                "event": "rolling_migration_completed",
                "candidate": r["candidate"].get("plan_key"),
                "replicas": r["records"], "tick": self.ticks})
            self._rolling = None

    # ------------------------------------------------------------------
    # the fleet serve loop
    # ------------------------------------------------------------------
    def has_work(self) -> bool:
        return bool(self.queue) or any(rep.rm.has_work()
                                       for rep in self._alive())

    def _adopt_successor(self, rep: Replica, new_rm) -> None:
        rep.rm = new_rm
        new_rm.on_exhausted = self._on_replica_exhausted
        new_rm.starvation_bound_ticks = self.config.starvation_bound_ticks
        new_rm.slo = self.slo
        new_rm.brownout = self.brownout
        # a live migration transplants requests into NEW record objects
        # (rids preserved) — re-point the fleet registry at the live
        # ones, or results/records would freeze at the drain snapshot
        for rid, req in new_rm.requests.items():
            if rid in self.requests:
                self.requests[rid] = req

    def _tick_replica(self, rep: Replica) -> None:
        """One replica's serve tick under the fleet's fault envelope:
        the seeded ``fleet_dispatch:<name>`` site models router→replica
        connectivity (a fault skips the tick and counts against the
        health streak), in-replica retry exhaustion arrives through the
        ``on_exhausted`` hook, and a clean tick resets the streak."""
        rm = rep.rm
        rm._check_lifecycle()
        if not rm.has_work():
            new_rm = rm._maybe_migrate(idle=True)
            if new_rm is not None:
                self._adopt_successor(rep, new_rm)
            return
        site = f"fleet_dispatch:{rep.name}"
        try:
            if self.injector is not None:
                self.injector.maybe_fail(site)
        except TransientServeError as e:
            if self.telemetry.enabled:
                self.telemetry.fault_observed(site, detail=str(e))
            self._note_failure(rep, site)
            return
        rep.had_exhaustion = False
        self.profiler.tick_begin()
        rm._tick()
        self.profiler.tick_end()
        rm._sync_kv()
        rm._maybe_check_health()
        if not rep.had_exhaustion and rep.state is not ReplicaState.DEAD:
            self._note_success(rep)
        new_rm = rm._maybe_migrate()
        if new_rm is not None:
            self._adopt_successor(rep, new_rm)

    def _maybe_brownout(self) -> None:
        """Evaluate the fleet-level BrownoutController every
        ``config.check_every`` fleet ticks and apply the ladder's
        actions across the whole fleet (see serve/slo.py): DEFER holds
        the fleet queue's degradable classes (``_dispatch_queue``),
        DEGRADE flips speculation off and caps output for LIVE
        degradable requests on every replica (the r14 ``set_spec_mode``
        path), SHED rejects their queued work fleet-wide, CRITICAL_ONLY
        also evicts their slotted work — every shed is an explicit
        ``REJECTED``, never ``FAILED``."""
        bo = self.brownout
        if bo is None:
            return
        if self.ticks % bo.config.check_every:
            return
        slo = self.slo
        tel = self.telemetry
        alive = self._alive()
        # signals: latency-critical lane depth (fleet queue + replica
        # pendings) and fleet-aggregate KV occupancy
        depths: Dict[str, int] = {c: 0 for c in slo.classes}
        lc_depth = 0
        held_queued: List[Request] = []

        def note(req: Request, queued: bool) -> None:
            nonlocal lc_depth
            cls = slo.resolve(req.slo_class)
            if cls is None:
                return
            depths[cls.name] = depths.get(cls.name, 0) + 1
            if not cls.degradable:
                lc_depth += 1
            elif queued:
                held_queued.append(req)

        for rid in self.queue:
            note(self.requests[rid], queued=True)
        live_tok = cap_tok = 0
        committed: Dict[str, float] = {}
        for rep in alive:
            for rid in rep.rm.pending:
                note(rep.rm.requests[rid], queued=True)
            kv = getattr(rep.rm.im, "kv", None)
            if kv is not None:
                live_tok += kv.live_tokens()
                cap_tok += kv.capacity_tokens
        # per-class committed-need high-watermark (token units — the
        # reservation contract's observable): replica-HELD requests
        # only, the same population the admission gate prices
        for rid in self._live:
            req = self.requests[rid]
            if req.status in TERMINAL_STATUSES or rid in self.queue:
                continue
            key = req.slo_class or ""
            committed[key] = committed.get(key, 0.0) + self._need(req)
        for key, tot in committed.items():
            if tot > self.lane_committed_hwm.get(key, 0.0):
                self.lane_committed_hwm[key] = tot
        if tel.enabled:
            tel.lane_depths(depths)
        bo.evaluate(lc_queue_depth=lc_depth,
                    kv_occupancy_frac=(live_tok / cap_tok if cap_tok
                                       else 0.0))
        if bo.level == 0:
            return
        # --- apply the level's actions fleet-wide ----------------------
        deferred: Dict[str, int] = {}
        for req in held_queued:
            if req.status in TERMINAL_STATUSES:
                continue
            if bo.sheds_queued(req.slo_class):
                if tel.enabled:
                    tel.lane_shed(req.slo_class, trace_id=req.trace_id,
                                  reason=f"brownout:{bo.level.name}")
                if req.rid in self.queue:
                    self._terminate(req, RequestStatus.REJECTED,
                                    reason="brownout shed")
                else:
                    # replica-pending: pull it off, then shed at the fleet
                    rep = self._by_name(self.placement[req.rid])
                    rep.rm.pending.remove(req.rid)
                    rep.rm._pending_since.pop(req.rid, None)
                    rep.rm.requests.pop(req.rid, None)
                    rep.rm._tstamps.pop(req.rid, None)
                    self._live.add(req.rid)
                    self._terminate(req, RequestStatus.REJECTED,
                                    reason="brownout shed")
            elif bo.holds(req.slo_class):
                req.deferred_ticks += 1
                deferred[req.slo_class] = deferred.get(req.slo_class, 0) + 1
        if tel.enabled:
            for cname, cnt in deferred.items():
                tel.lane_deferred(cname, count=cnt)
        # --- SPILL: the rung between DEFER and DEGRADE -----------------
        # on pressured replicas with a host tier attached, push
        # degradable decoding requests' pages to host DRAM (each
        # preempt() spills first) BEFORE any capping or shedding below —
        # readmission restores them, so this rung only trades latency
        # for headroom, never tokens.  An ACTION of DEFER_BATCH and
        # above, not a ladder level — the `bo.level < 2` gate right
        # after stays the untouched DEGRADE boundary.
        frac = bo.config.kv_pressure_frac
        for rep in alive:
            rm = rep.rm
            kv = getattr(rm.im, "kv", None)
            if kv is None or kv.host_tier is None or not kv.capacity_tokens:
                continue
            if kv.live_tokens() / kv.capacity_tokens < frac:
                continue
            victims = [r for r in rm._active()
                       if r.status is RequestStatus.DECODING
                       and bo.spills(r.slo_class)
                       and r.preemptions < rm.res.max_preemptions]
            victims.sort(key=lambda r: (r.priority, -r.rid))
            for req in victims:
                if kv.live_tokens() / kv.capacity_tokens < frac:
                    break
                rm.preempt(req.rid)
        if bo.level < 2:  # below DEGRADE_BATCH: nothing touches live work
            return
        for rep in alive:
            rm = rep.rm
            for req in list(rm._active()):
                if req.status in TERMINAL_STATUSES:
                    continue
                if bo.sheds_live(req.slo_class):
                    # CRITICAL_ONLY: evict + shed slotted degradable work
                    rm.preempt(req.rid)
                    rm.pending.remove(req.rid)
                    rm._pending_since.pop(req.rid, None)
                    rm.requests.pop(req.rid, None)
                    rm._tstamps.pop(req.rid, None)
                    self.requests[req.rid] = req
                    self._live.add(req.rid)
                    if tel.enabled:
                        tel.lane_shed(req.slo_class, trace_id=req.trace_id,
                                      reason="brownout:CRITICAL_ONLY")
                    self._terminate(req, RequestStatus.REJECTED,
                                    reason="brownout shed")
                elif bo.degrades(req.slo_class):
                    changed = False
                    if req.spec:
                        changed = rm.set_spec_mode(req.rid, False) \
                            or changed
                    cap = bo.output_cap(req.slo_class)
                    if cap is not None:
                        changed = rm.apply_output_cap(req.rid, cap) \
                            or changed
                    if changed and tel.enabled:
                        tel.lane_degraded(req.slo_class)

    def _fleet_tick(self) -> None:
        """One routing pass: scheduled kills, rolling-migration advance,
        brownout evaluation, queue dispatch, one tick per serving
        replica, quarantine re-probes, health gauges."""
        self.ticks += 1
        for name, at in list(self._kills.items()):
            if at <= self.ticks:
                del self._kills[name]
                self.kill_replica(name, reason="scheduled kill")
        self._advance_rolling()
        self._maybe_brownout()
        self._dispatch_queue()
        for rep in self.replicas:
            if rep.state is ReplicaState.DEAD:
                continue
            if rep.state is ReplicaState.QUARANTINED:
                self._maybe_probe(rep)
                continue
            self._tick_replica(rep)
        self._reap_terminal()
        if self.telemetry.enabled:
            self.telemetry.fleet_health(
                sum(1 for r in self.replicas
                    if r.state is ReplicaState.HEALTHY),
                len(self._alive()), len(self.replicas), len(self.queue))

    def serve_all(self) -> Dict[int, List[int]]:
        """Serve until every registered request reaches a terminal
        outcome (and any staged rolling migration finishes)."""
        while True:
            self._check_lifecycle()
            if not self.has_work():
                if self._rolling is not None:
                    self._fleet_tick()
                    continue
                break
            self._fleet_tick()
        return {rid: r.generated for rid, r in self.requests.items()}

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None) -> List[List[int]]:
        rids = [self.register(p, max_new_tokens) for p in prompts]
        out = self.serve_all()
        return [out[rid] for rid in rids]

    def trace_run_meta(self) -> Dict:
        """Provenance header a traffic trace (obs/replay.py) records for
        this fleet: the shared gen config + fault schedule like a single
        manager's, plus the topology (replica names + per-replica plan
        shapes) and the scheduled-kill schedule — what makes a recorded
        chaos run (replica death mid-stream) replayable from the
        artifact alone."""
        from ..obs.replay import engine_shape_of, injector_meta

        meta: Dict = {
            "driver": type(self).__name__,
            "gen": dataclasses.asdict(self.gen),
            # the fleet-level plan slot carries replica0's engine shape
            # (capacity fields the what-if simulator scales by fleet
            # size); per-replica shapes ride the fleet section
            "plan": (engine_shape_of(self.replicas[0].rm.im)
                     if self.replicas else {}),
            "fault": injector_meta(self.injector),
            "fleet": {
                "replicas": len(self.replicas),
                "names": [rep.name for rep in self.replicas],
                "plans": {rep.name: engine_shape_of(rep.rm.im)
                          for rep in self.replicas},
                "kills": {name: int(tick)
                          for name, tick in self._kills.items()},
            },
        }
        if self.slo is not None and hasattr(self.slo, "snapshot"):
            meta["slo"] = self.slo.snapshot()
        return meta

    def serve_with_arrivals(self, arrivals, clock=None, quantum: int = 8,
                            record_trace=None) -> Dict[int, Dict]:
        """Arrival-driven fleet serving — the multi-worker extension of
        :meth:`RequestManager.serve_with_arrivals` (same arrival tuple /
        options-dict contract, same record fields) plus the fleet
        stamps: ``replica`` (the serving replica — the LAST placement
        when a request failed over) and ``failovers`` (how many replica
        failures it rode).  ``obs.report.under_load_summary`` reduces
        the records to fleet-aggregate AND per-replica goodput / TTFT /
        TPOT / outcome mixes."""
        import time as _time

        clock = clock or _time.perf_counter
        saved_clock = self._swap_clock(clock)
        saved_chunks = {rep.name: rep.rm.scan_chunk
                        for rep in self.replicas}
        for rep in self.replicas:
            rep.rm._swap_clock(clock)
        t0 = clock()
        if record_trace is not None:
            record_trace.begin_run(self.trace_run_meta())
        pending = sorted(arrivals, key=lambda a: a[0])
        records: Dict[int, Dict] = {}
        open_rids: set = set()
        tel = self.telemetry

        def admit_due():
            now = clock() - t0
            while pending and pending[0][0] <= now:
                off, prompt, mnt, *rest = pending.pop(0)
                if record_trace is not None:
                    # RAW options element — a malformed dict replays its
                    # rejection identically
                    record_trace.record_arrival(
                        off, prompt, mnt, rest[0] if rest else None)
                opts, reject = parse_arrival_options(rest)
                rid = self.register(prompt, mnt, reject_invalid=True,
                                    reject_reason=reject, **opts)
                records[rid] = {"arrival_s": off, "admitted_s": now,
                                "prompt_len": len(prompt),
                                "trace_id": self.requests[rid].trace_id}
                open_rids.add(rid)
            return clock() - t0

        def stamp(now):
            for rid in list(open_rids):
                rec, req = records[rid], self.requests[rid]
                if "first_token_s" not in rec and req.generated:
                    rec["first_token_s"] = now
                if ("finish_s" not in rec
                        and req.status in TERMINAL_STATUSES):
                    rec["finish_s"] = now
                if "finish_s" in rec:
                    open_rids.discard(rid)

        try:
            while pending or self.has_work() or self._rolling is not None:
                now = admit_due()
                self._check_lifecycle()
                stamp(clock() - t0)
                if not self.has_work() and self._rolling is None:
                    if pending:
                        _time.sleep(min(1e-3,
                                        max(0.0, pending[0][0] - now)))
                    continue
                for rep in self._alive():
                    rep.rm.scan_chunk = (quantum if pending
                                         else saved_chunks.get(
                                             rep.name, quantum))
                starters = [
                    rid for rid in open_rids
                    if "prefill_start_s" not in records[rid]
                    and self.requests[rid].prefill_offset == 0
                    and self.requests[rid].status not in TERMINAL_STATUSES]
                self._fleet_tick()
                for rid in starters:
                    if self.requests[rid].prefill_offset > 0:
                        records[rid]["prefill_start_s"] = now
                        if tel.enabled:
                            tel.request_prefill_started(
                                self.requests[rid].trace_id)
                stamp(clock() - t0)
        finally:
            self._swap_clock(saved_clock)
            for rep in self.replicas:
                rep.rm.scan_chunk = saved_chunks.get(rep.name,
                                                     rep.rm.scan_chunk)
                rep.rm._swap_clock(saved_clock)
        end = clock() - t0
        for rid, rec in records.items():
            req = self.requests[rid]
            rec["tokens"] = req.generated
            rec["outcome"] = req.outcome or OUTCOMES.get(req.status, "ok")
            if req.slo_class:
                rec["slo_class"] = req.slo_class
            if req.deferred_ticks:
                rec["deferred_ticks"] = req.deferred_ticks
            rec["kv_bytes"] = req.kv_bytes
            rec["replica"] = self.placement.get(rid, "")
            rec["failovers"] = self._failover_counts.get(rid, 0)
            if self.profiler.enabled:
                rec["work"] = self.profiler.request_work(rid)
            start = rec.get("prefill_start_s",
                            rec.get("admitted_s", rec["arrival_s"]))
            stop = rec.get("first_token_s", rec.get("finish_s", end))
            rec["queue_wait_s"] = max(start - rec["arrival_s"], 0.0)
            rec["prefill_s"] = max(stop - start, 0.0)
        if record_trace is not None:
            record_trace.finalize(records)
        return records
