"""RequestManager: request queue, continuous batching, decode orchestration.

Reference: ``src/runtime/request_manager.cc`` — ``register_new_request``,
``prepare_next_batch`` (admit/retire requests, mix prompt-prefill chunks with
single decode tokens in one flat token batch), ``serve_incr_decoding``; the
speculative path (``prepare_next_batch_beam/_verify``, ``serve_spec_infer``)
lives in :mod:`flexflow_tpu.serve.spec_infer` and reuses this class.

Host-side Python is the right tool here (the reference uses host-side C++):
the per-step compute is one jitted TPU program; this class only does queue
bookkeeping and builds the next fixed-capacity BatchConfig.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.telemetry import telemetry_or_null
from .batch_config import BatchConfig, PrefillBatchConfig


class RequestStatus(enum.Enum):
    PENDING = 0
    PREFILLING = 1
    DECODING = 2
    COMPLETED = 3


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int = 64
    status: RequestStatus = RequestStatus.PENDING
    generated: List[int] = dataclasses.field(default_factory=list)
    prefill_offset: int = 0     # prompt tokens already fed to the model
    slot: int = -1
    trace_id: str = ""          # stable per-request telemetry/trace tag
    # consecutive mixed-batch steps in which the tiled budget rounded this
    # request's prefill take to zero (starvation fallback, ADVICE r5 low)
    starved_steps: int = 0

    @property
    def seq_len(self) -> int:
        """Tokens currently in the KV cache (after the last step)."""
        return self.prefill_offset + len(self.generated)


@dataclasses.dataclass
class GenerationConfig:
    max_new_tokens: int = 64
    eos_token_id: Optional[int] = None
    stop_on_eos: bool = True
    # sampling (reference: GenerationConfig in flexflow/inference.py + the
    # Sampling op).  temperature <= 0 -> exact greedy argmax.  Speculative
    # serving supports it too: the verify step samples per tree node and the
    # accept walk matches drafts against the sampled tokens (spec_infer
    # ._verify_phase / spec_scan._macro_body), preserving the target
    # sampling distribution for any draft model.
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0


class RequestManager:
    request_cls = Request  # subclasses (SpecInferManager) extend the record

    def __init__(self, im, gen_config: Optional[GenerationConfig] = None,
                 telemetry=None):
        self.im = im
        self.gen = gen_config or GenerationConfig()
        self.requests: Dict[int, Request] = {}
        self.pending: List[int] = []
        self.slots: List[Optional[int]] = [None] * im.max_requests
        self._next_rid = 0
        self.steps = 0
        self.tokens_decoded = 0
        self.scan_runs = 0      # decode stretches run as on-device scans
        self._sample_calls = 0  # folds the per-call key for seeded sampling
        # ONE Telemetry handle across the serving stack: syncing it onto the
        # InferenceManager (which forwards to pipeline stages) puts request
        # lifecycle, dispatch spans, and per-stage events on one clock/ring.
        # ALWAYS synced — exactly the handle passed here (or the no-op) —
        # so a shared/cached im can never leak a previous run's live handle
        # into a manager built without one.  Host-side only — a handle can
        # never change serve outputs (tests/test_obs.py bit-identity).
        self.telemetry = telemetry_or_null(telemetry)
        im.telemetry = self.telemetry
        self._tstamps: Dict[int, Dict[str, float]] = {}  # rid -> stamps

    def _sample_arg(self):
        """(key, temperature, top_p) for the step, or None for greedy."""
        if self.gen.temperature <= 0.0:
            return None
        import jax
        import jax.numpy as jnp

        self._sample_calls += 1
        key = jax.random.fold_in(
            jax.random.PRNGKey(self.gen.seed), self._sample_calls
        )
        return (key, jnp.float32(self.gen.temperature),
                jnp.float32(self.gen.top_p))

    # ------------------------------------------------------------------
    def _seq_len_needed(self, req: Request) -> int:
        """Cache depth a request may reach (overridden by speculation)."""
        return len(req.prompt) + req.max_new_tokens

    def register_new_request(
        self, prompt_tokens: Sequence[int], max_new_tokens: Optional[int] = None
    ) -> int:
        if not len(prompt_tokens):
            raise ValueError("empty prompt")
        rid = self._next_rid
        self._next_rid += 1
        req = self.request_cls(
            rid,
            list(int(t) for t in prompt_tokens),
            self.gen.max_new_tokens if max_new_tokens is None else max_new_tokens,
        )
        if self._seq_len_needed(req) > self.im.max_seq_len:
            raise ValueError(
                f"request needs {self._seq_len_needed(req)} cache slots, "
                f"exceeds max_seq_len {self.im.max_seq_len}"
            )
        req.trace_id = f"r{rid:05d}"
        self.requests[rid] = req
        self.pending.append(rid)
        tel = self.telemetry
        if tel.enabled:
            self._tstamps[rid] = {
                "enqueue": tel.request_enqueued(req.trace_id,
                                                prompt_len=len(req.prompt))
            }
        return rid

    def _admit(self):
        for i, occupant in enumerate(self.slots):
            if occupant is None and self.pending:
                rid = self.pending.pop(0)
                req = self.requests[rid]
                req.slot = i
                req.status = RequestStatus.PREFILLING
                self.slots[i] = rid
                tel = self.telemetry
                if tel.enabled:
                    ts = self._tstamps.setdefault(rid, {})
                    now = tel.request_admitted(
                        req.trace_id,
                        queue_wait_s=(tel.now() - ts["enqueue"]
                                      if "enqueue" in ts else None))
                    ts["admit"] = now

    def _active(self) -> List[Request]:
        return [
            self.requests[rid] for rid in self.slots if rid is not None
        ]

    def has_work(self) -> bool:
        return bool(self.pending) or any(
            r.status in (RequestStatus.PREFILLING, RequestStatus.DECODING)
            for r in self._active()
        )

    # ------------------------------------------------------------------
    def prepare_next_batch(self) -> Tuple[BatchConfig, List[Tuple[int, int]]]:
        """Build the next step's BatchConfig.

        Returns (bc, sample_points) where sample_points is
        ``[(flat_token_index, rid)]`` — the token slots whose model output is
        the next token of that request (last prefill token, or the decode
        token).  Mirrors ``RequestManager::prepare_next_batch``.
        """
        self._admit()
        tokens: List[int] = []
        req_idx: List[int] = []
        positions: List[int] = []
        sample_points: List[Tuple[int, int]] = []
        budget = self.im.max_tokens

        # decode tokens first: one per DECODING request (latency-critical)
        for req in self._active():
            if req.status is RequestStatus.DECODING and budget > 0:
                pos = req.seq_len - 1
                tokens.append(req.generated[-1])
                req_idx.append(req.slot)
                positions.append(pos)
                sample_points.append((len(tokens) - 1, req.rid))
                budget -= 1

        n_decode = len(tokens)

        # a pure-prefill step with Pallas enabled ships tile-aligned chunks
        # (PrefillBatchConfig -> the Q-tiled prefill kernel); mixed
        # decode+prefill steps keep the flat layout
        tile = getattr(self.im, "prefill_tile", 1)
        if (not tokens and tile > 1 and self.im.use_pallas
                and any(r.status is RequestStatus.PREFILLING
                        for r in self._active())
                # contract (d): tiled segments need tile-aligned starts; an
                # unaligned offset (hand-driven flat steps) rides the flat
                # path instead of crashing the builder
                and all(r.prefill_offset % tile == 0
                        for r in self._active()
                        if r.status is RequestStatus.PREFILLING)):
            segments = []
            for req in self._active():
                if req.status is not RequestStatus.PREFILLING or budget < tile:
                    continue
                # cap at whole tiles so the padded segment fits the capacity
                take = min((budget // tile) * tile,
                           len(req.prompt) - req.prefill_offset)
                start = req.prefill_offset
                segments.append(
                    (req.slot, req.prompt[start: start + take], start)
                )
                req.prefill_offset += take
                req.starved_steps = 0
                budget -= -(-take // tile) * tile  # padded tiles consumed
                if req.prefill_offset == len(req.prompt):
                    sample_points.append((req.slot, req.rid))
            seq_lens = np.zeros(self.im.max_requests, np.int32)
            for req in self._active():
                seq_lens[req.slot] = req.prefill_offset + len(req.generated)
            # LM-head gating: completing segments' sample points ride the
            # chunk's logit_slots, the step computes logits ONLY there, and
            # the result arrays are indexed by SLOT (shape [max_requests])
            gate = bool(getattr(self.im, "gate_lm_head", False))
            pbc, last_flat = PrefillBatchConfig.build(
                segments, seq_lens, tile,
                max_tokens=self.im.max_tokens,
                max_requests=self.im.max_requests,
                gate_slots=[slot for slot, _ in sample_points]
                if gate else None,
            )
            sample_points = [
                (slot if gate else last_flat[slot], rid)
                for slot, rid in sample_points
            ]
            self._note_batch(0, sum(len(s[1]) for s in segments), seq_lens)
            return pbc, sample_points

        # then prefill chunks fill the remaining budget.  Mid-prompt cuts
        # keep prefill_offset TILE-ALIGNED (round the take down to whole
        # tiles) so later pure-prefill steps can ride the tiled Pallas path
        # — PrefillBatchConfig's contract (d) rejects unaligned segment
        # starts.  Completing takes (remaining <= budget) need no rounding.
        for req in self._active():
            if req.status is not RequestStatus.PREFILLING or budget <= 0:
                continue
            remaining = len(req.prompt) - req.prefill_offset
            if remaining <= budget:
                take = remaining
            elif (tile > 1 and self.im.use_pallas
                    and req.prefill_offset % tile == 0):
                # only the Pallas tiled path consumes the alignment; the
                # gather path must not stall prefill for it — and a request
                # already off-tile (starvation fallback below) has nothing
                # left to protect, so it skips the rounding entirely
                take = (budget // tile) * tile
                if take == 0:
                    # budget < one tile: normally wait to keep alignment —
                    # but when decode tokens leave less than a tile of
                    # budget EVERY step, waiting starves the prompt until
                    # the decoders finish (unbounded TTFT, ADVICE r5 low).
                    # After ``starvation_limit`` consecutive dry steps, take
                    # an UNALIGNED flat chunk: the offset goes off-tile, so
                    # the tiled-branch alignment gate above routes this
                    # request's later chunks through the flat gather path —
                    # slower per token, but it makes progress every step.
                    req.starved_steps += 1
                    if req.starved_steps < self.starvation_limit:
                        continue
                    take = budget
            else:
                take = budget
                if tile > 1 and self.im.use_pallas and budget >= tile:
                    # an off-tile offset (starvation fallback above) blocks
                    # the tiled pure-prefill path for EVERY concurrently
                    # prefilling request (the alignment gate is all-or-
                    # nothing).  In budget-rich steps round the take so the
                    # offset lands back on a tile boundary: one slightly
                    # smaller take buys the Q-tiled kernel back for the
                    # whole batch.  Starved steps (budget < tile) keep the
                    # full take — progress beats re-alignment there.
                    over = (req.prefill_offset + take) % tile
                    if 0 < over < take:
                        take -= over
            start = req.prefill_offset
            for j in range(take):
                tokens.append(req.prompt[start + j])
                req_idx.append(req.slot)
                positions.append(start + j)
            req.prefill_offset += take
            req.starved_steps = 0
            budget -= take
            if req.prefill_offset == len(req.prompt):
                # output at the last prompt token = first generated token
                sample_points.append((len(tokens) - 1, req.rid))

        # cache depth after this step: prompt tokens fed so far + generated
        # tokens (the decode token fed this step is generated[-1], whose KV
        # lands at position seq_len-1 during the step)
        seq_lens = np.zeros(self.im.max_requests, np.int32)
        for req in self._active():
            seq_lens[req.slot] = req.prefill_offset + len(req.generated)
        bc = BatchConfig.build(
            tokens, req_idx, positions, seq_lens,
            max_tokens=self.im.max_tokens,
            max_requests=self.im.max_requests,
        )
        self._note_batch(n_decode, len(tokens) - n_decode, seq_lens)
        return bc, sample_points

    def _note_batch(self, n_decode: int, n_prefill: int, seq_lens) -> None:
        """Batch-composition telemetry for one step (token mix, slot
        occupancy, KV utilization) — host counters only."""
        tel = self.telemetry
        if not tel.enabled:
            return
        tel.batch_composition(
            n_decode, n_prefill,
            active_requests=sum(1 for s in self.slots if s is not None),
            max_requests=self.im.max_requests,
            kv_tokens=int(np.sum(seq_lens)),
            kv_capacity=self.im.max_requests * self.im.max_seq_len,
        )

    def _append_token(self, req: Request, tok: int) -> None:
        """Commit one generated token — the ONE place the first-token
        (TTFT) telemetry stamp can live, whatever path produced the token
        (per-step result, prefill stretch, decode scan, spec verify)."""
        req.generated.append(tok)
        self.tokens_decoded += 1
        tel = self.telemetry
        if tel.enabled and len(req.generated) == 1:
            ts = self._tstamps.setdefault(req.rid, {})
            now = tel.request_first_token(
                req.trace_id,
                ttft_s=(tel.now() - ts["enqueue"]
                        if "enqueue" in ts else None))
            ts["first_token"] = now

    def process_result(self, result, sample_points) -> None:
        if not sample_points:
            # mid-prefill step: nothing to read back — leave the result on
            # device so chunked prefill dispatches stay fully async
            return
        token_ids = np.asarray(result.token_ids)
        for flat_idx, rid in sample_points:
            req = self.requests[rid]
            tok = int(token_ids[flat_idx])
            if req.status is RequestStatus.PREFILLING:
                req.status = RequestStatus.DECODING
            self._append_token(req, tok)
            self._maybe_finish(req)

    def _maybe_finish(self, req: Request) -> None:
        eos = self.gen.eos_token_id
        if (
            len(req.generated) >= req.max_new_tokens
            or (self.gen.stop_on_eos and eos is not None
                and req.generated and req.generated[-1] == eos)
        ):
            req.status = RequestStatus.COMPLETED
            if req.slot >= 0:
                self.slots[req.slot] = None
                req.slot = -1
            tel = self.telemetry
            if tel.enabled:
                ts = self._tstamps.get(req.rid, {})
                now = tel.now()
                first = ts.get("first_token")
                tel.request_finished(
                    req.trace_id, n_tokens=len(req.generated),
                    tpot_s=((now - first)
                            / max(len(req.generated) - 1, 1)
                            if first is not None else None))

    # ------------------------------------------------------------------
    def _scan_steps_possible(self) -> int:
        """How many pure-decode steps can run as ONE on-device scan now.

        > 1 only when no admission/prefill work is pending and every active
        request is decoding; bounded by the smallest remaining token budget
        (so no slot overshoots max_new_tokens) and by cache headroom.
        """
        active = self._active()
        if (self.pending or not active
                or any(r.status is not RequestStatus.DECODING
                       for r in active)):
            return 0
        n = min(r.max_new_tokens - len(r.generated) for r in active)
        n = min(n, self.scan_chunk,
                self.im.max_seq_len - max(r.seq_len for r in active) + 1)
        # round down to a power of two: n is a STATIC arg of the jitted
        # scan, so every distinct value compiles the whole n-step model —
        # quantizing bounds the compile count to ~log2(scan_chunk) variants
        if n > 1:
            n = 1 << (n.bit_length() - 1)
        return n

    scan_chunk = 32  # sync-amortization window for the decode scan
    # mixed decode+prefill steps whose tiled budget rounds to 0 before the
    # starved request falls back to an unaligned flat-path take (bounds the
    # TTFT inflation at ~limit decode steps; see prepare_next_batch)
    starvation_limit = 4

    # ------------------------------------------------------------------
    def _prefill_stretch_possible(self) -> bool:
        """Can the whole current prefill wave run as on-device scans?

        True when every active request is PREFILLING (no decode latency to
        protect) and the InferenceManager has the tiled-prefill path.  The
        stretch then feeds every request's remaining prompt through
        ``prefill_scan`` — one dispatch per power-of-two chunk segment and
        ONE host sync at the end, vs a dispatch per chunk (+ a ~100ms tunnel
        sync per request boundary) on the per-step path.
        """
        self._admit()
        active = self._active()
        tile = getattr(self.im, "prefill_tile", 1)
        return (
            tile > 1
            and self.im.use_pallas
            and hasattr(self.im, "prefill_scan")
            and bool(active)
            and all(r.status is RequestStatus.PREFILLING for r in active)
            and any(r.prefill_offset < len(r.prompt) for r in active)
            and all(r.prefill_offset % tile == 0 for r in active)
        )

    def _prefill_stretch(self) -> None:
        """Prefill every active request's remaining prompt via prefill_scan."""
        import jax
        import jax.numpy as jnp

        im = self.im
        tile = im.prefill_tile
        cap = im.max_tokens
        gate = bool(getattr(im, "gate_lm_head", False))
        chunks: List = []  # per-chunk numpy field tuples (BatchConfig order)
        ls_chunks: List = []  # per-chunk logit_slots (gated path)
        # (chunk_idx, result_idx, rid): result_idx is the SLOT when gated
        # (result arrays are [max_requests]), the flat token index otherwise
        points: List[Tuple[int, int, int]] = []
        seq = np.zeros(im.max_requests, np.int32)
        for req in self._active():
            seq[req.slot] = req.prefill_offset + len(req.generated)
        for req in self._active():
            if req.status is not RequestStatus.PREFILLING:
                continue
            while req.prefill_offset < len(req.prompt):
                take = min((cap // tile) * tile,
                           len(req.prompt) - req.prefill_offset)
                start = req.prefill_offset
                seq[req.slot] = start + take
                fields, last_flat = PrefillBatchConfig.np_fields(
                    [(req.slot, req.prompt[start: start + take], start)],
                    seq, tile,
                    max_tokens=cap, max_requests=im.max_requests,
                )
                req.prefill_offset += take
                done = req.prefill_offset == len(req.prompt)
                if done:
                    points.append((len(chunks),
                                   req.slot if gate else last_flat[req.slot],
                                   req.rid))
                ls_chunks.append(PrefillBatchConfig.np_logit_slots(
                    [req.slot] if done else [], last_flat, im.max_requests))
                chunks.append(fields)
        # stack chunk fields host-side (ONE device transfer per field per
        # segment, not five tiny transfers per chunk) and scan in power-of-
        # two segments so each distinct scan length compiles at most once
        outs = []   # (start_chunk, token array [seg, cap]) — read after all
        at = 0
        while at < len(chunks):
            seg = 1 << (min(len(chunks) - at, 64).bit_length() - 1)
            stacked = PrefillBatchConfig(
                base=BatchConfig(*(
                    jnp.asarray(np.stack([c[i] for c in chunks[at: at + seg]]))
                    for i in range(5)
                )),
                tile_size=tile,
                logit_slots=jnp.asarray(np.stack(ls_chunks[at: at + seg]))
                if gate else None,
            )
            outs.append((at, im.prefill_scan(stacked, self._sample_arg())))
            at += seg
        toks = {start: np.asarray(t) for start, t in outs}  # one sync
        starts = sorted(toks)
        for chunk_idx, flat_idx, rid in points:
            start = max(s for s in starts if s <= chunk_idx)
            req = self.requests[rid]
            req.status = RequestStatus.DECODING
            self._append_token(req,
                               int(toks[start][chunk_idx - start, flat_idx]))
            self._maybe_finish(req)
        self.steps += len(chunks)
        self.scan_runs += 1

    def _decode_stretch(self, n: int) -> None:
        """Run n decode steps on device with one host sync (decode_scan)."""
        active = self._active()
        tokens, reqi, pos = [], [], []
        points = []
        for req in active:
            tokens.append(req.generated[-1])
            reqi.append(req.slot)
            pos.append(req.seq_len - 1)
            points.append(req.rid)
        seq_lens = np.zeros(self.im.max_requests, np.int32)
        for req in active:
            seq_lens[req.slot] = req.seq_len
        bc = BatchConfig.build(
            tokens, reqi, pos, seq_lens,
            max_tokens=self.im.max_tokens, max_requests=self.im.max_requests,
        )
        eos = self.gen.eos_token_id if self.gen.stop_on_eos else None
        toks, live, _ = self.im.decode_scan(
            bc, n, eos=eos, sample=self._sample_arg()
        )
        toks = np.asarray(toks)
        live = np.asarray(live)
        for s in range(n):
            for flat, rid in enumerate(points):
                req = self.requests[rid]
                if req.status is not RequestStatus.DECODING or not live[s, flat]:
                    continue
                self._append_token(req, int(toks[s, flat]))
                self._maybe_finish(req)
        self.steps += n
        self.scan_runs += 1

    def serve_with_arrivals(self, arrivals, clock=None, quantum: int = 8):
        """Arrival-driven serving: requests join the running admit/retire
        loop at their offered times (open-loop load, the serving_under_load
        bench's engine).

        ``arrivals``: iterable of ``(t_offset_s, prompt_tokens,
        max_new_tokens_or_None)`` — offsets from loop start; admitted once
        the clock passes them.  ``clock``: 0-arg seconds callable
        (injectable for hermetic tests; default ``time.perf_counter``).
        ``quantum``: cap on the on-device decode-scan stretch while
        arrivals are outstanding, so a long scan can't defer admission
        unboundedly (TTFT protection; the full ``scan_chunk`` window
        returns once every arrival is in).

        Returns ``{rid: record}`` with ``arrival_s``, ``first_token_s``
        (host-visible TTFT stamp), ``finish_s``, ``prompt_len``,
        ``trace_id``, ``tokens``, and the TTFT decomposition
        ``queue_wait_s`` / ``prefill_s``: ``prefill_start_s`` is stamped at
        the start of the step in which the request's FIRST prompt token was
        fed to the device, so queue wait (arrival -> prefill actually
        starting: pending queue + slot wait + tiled-budget starvation) is
        reported separately from prefill compute (``queue_wait_s +
        prefill_s == first_token_s - arrival_s``).  All stamps are
        host-visible at step-boundary granularity.  Per-request outputs are
        INVARIANT to arrival timing (continuous batching only reorders
        work, never results), pinned by tests/test_serving_under_load.py.
        """
        import time as _time

        clock = clock or _time.perf_counter
        t0 = clock()
        pending = sorted(arrivals, key=lambda a: a[0])
        records: Dict[int, Dict] = {}
        saved_chunk = self.scan_chunk
        tel = self.telemetry

        def admit_due():
            now = clock() - t0
            while pending and pending[0][0] <= now:
                off, prompt, mnt = pending.pop(0)
                rid = self.register_new_request(prompt, mnt)
                records[rid] = {"arrival_s": off, "admitted_s": now,
                                "prompt_len": len(prompt),
                                "trace_id": self.requests[rid].trace_id}
            return clock() - t0

        def prefill_starters():
            # requests whose first prompt token may enter the device in the
            # NEXT step: stamped with the step's start time if it does
            # (admission itself can also happen inside the step)
            return [rid for rid, rec in records.items()
                    if "prefill_start_s" not in rec
                    and self.requests[rid].prefill_offset == 0]

        def stamp(now):
            for rid, rec in records.items():
                req = self.requests[rid]
                if "first_token_s" not in rec and req.generated:
                    rec["first_token_s"] = now
                if ("finish_s" not in rec
                        and req.status is RequestStatus.COMPLETED):
                    rec["finish_s"] = now

        try:
            while pending or self.has_work():
                now = admit_due()
                if not self.has_work():
                    # idle until the next arrival: a short bounded sleep for
                    # ANY clock — real clocks stop busy-spinning, virtual
                    # clocks (which advance per call) lose at most ~1ms of
                    # wall time per idle poll
                    if pending:
                        _time.sleep(min(1e-3, max(0.0,
                                                  pending[0][0] - now)))
                    continue
                self.scan_chunk = quantum if pending else saved_chunk
                starters = prefill_starters()
                if self._prefill_stretch_possible():
                    with tel.span("prefill_stretch", cat="serve"):
                        self._prefill_stretch()
                else:
                    n = self._scan_steps_possible()
                    if n > 1:
                        with tel.span("decode_stretch", cat="serve",
                                      steps=n):
                            self._decode_stretch(n)
                    else:
                        with tel.span("serve_step", cat="serve"):
                            bc, sample_points = self.prepare_next_batch()
                            result = self.im.step(bc,
                                                  sample=self._sample_arg())
                            self.process_result(result, sample_points)
                            self.steps += 1
                for rid in starters:
                    if self.requests[rid].prefill_offset > 0:
                        records[rid]["prefill_start_s"] = now
                        if tel.enabled:
                            tel.request_prefill_started(
                                self.requests[rid].trace_id)
                stamp(clock() - t0)
        finally:
            self.scan_chunk = saved_chunk
        for rid, rec in records.items():
            rec["tokens"] = self.requests[rid].generated
            start = rec.get("prefill_start_s", rec.get("admitted_s"))
            if "first_token_s" in rec and start is not None:
                rec["queue_wait_s"] = start - rec["arrival_s"]
                rec["prefill_s"] = rec["first_token_s"] - start
        return records

    def serve_incr_decoding(self) -> Dict[int, List[int]]:
        """Run the incremental-decoding loop until all requests complete.

        Reference: ``RequestManager::serve_incr_decoding`` — but the pure-
        decode stretches run as ONE on-device ``lax.scan`` (EOS-masked), so
        the ~100ms tunnel sync amortizes over up to ``scan_chunk`` tokens;
        the per-step host path only handles admission/prefill boundaries.
        """
        tel = self.telemetry
        while self.has_work():
            if self._prefill_stretch_possible():
                with tel.span("prefill_stretch", cat="serve"):
                    self._prefill_stretch()
                continue
            n = self._scan_steps_possible()
            if n > 1:
                with tel.span("decode_stretch", cat="serve", steps=n):
                    self._decode_stretch(n)
                continue
            with tel.span("serve_step", cat="serve"):
                bc, sample_points = self.prepare_next_batch()
                result = self.im.step(bc, sample=self._sample_arg())
                self.process_result(result, sample_points)
                self.steps += 1
        return {rid: r.generated for rid, r in self.requests.items()}

    _serve = serve_incr_decoding  # overridden by SpecInferManager

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ) -> List[List[int]]:
        rids = [
            self.register_new_request(p, max_new_tokens) for p in prompts
        ]
        from ..utils.profiling import maybe_profile
        from ..utils.runlog import log_run

        profiling = bool(getattr(self.im.model.config, "profiling", False))
        import time as _time

        # snapshot the lifetime counters so the record is per-call deltas
        tok0, step0, scan0 = self.tokens_decoded, self.steps, self.scan_runs
        t0 = _time.perf_counter()
        with maybe_profile(profiling):
            out = self._serve()
        log_run("serve", {
            "manager": type(self).__name__,
            "requests": len(rids),
            "tokens": self.tokens_decoded - tok0,
            "steps": self.steps - step0,
            "scan_runs": self.scan_runs - scan0,
            "seconds": round(_time.perf_counter() - t0, 3),
        })
        return [out[rid] for rid in rids]
