"""RequestManager: request queue, continuous batching, decode orchestration.

Reference: ``src/runtime/request_manager.cc`` — ``register_new_request``,
``prepare_next_batch`` (admit/retire requests, mix prompt-prefill chunks with
single decode tokens in one flat token batch), ``serve_incr_decoding``; the
speculative path (``prepare_next_batch_beam/_verify``, ``serve_spec_infer``)
lives in :mod:`flexflow_tpu.serve.spec_infer` and reuses this class.

Host-side Python is the right tool here (the reference uses host-side C++):
the per-step compute is one jitted TPU program; this class only does queue
bookkeeping and builds the next fixed-capacity BatchConfig.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.telemetry import telemetry_or_null
from .batch_config import BatchConfig, PrefillBatchConfig
from .inference_manager import EXIT_NOT_IN_BATCH
from .resilience import ResilienceConfig, TransientServeError


class RequestStatus(enum.Enum):
    PENDING = 0
    PREFILLING = 1
    DECODING = 2
    COMPLETED = 3
    # resilient-serving lifecycle (serve/resilience.py): PREEMPTED requests
    # sit back in the pending queue and recompute prompt+generated on
    # readmission; the rest are terminal.
    PREEMPTED = 4
    CANCELLED = 5
    TIMED_OUT = 6
    REJECTED = 7
    FAILED = 8


TERMINAL_STATUSES = frozenset({
    RequestStatus.COMPLETED, RequestStatus.CANCELLED,
    RequestStatus.TIMED_OUT, RequestStatus.REJECTED, RequestStatus.FAILED,
})

# terminal status -> the ``outcome`` tag serving records carry
OUTCOMES = {
    RequestStatus.COMPLETED: "ok",
    RequestStatus.CANCELLED: "cancelled",
    RequestStatus.TIMED_OUT: "timeout",
    RequestStatus.REJECTED: "rejected",
    RequestStatus.FAILED: "failed",
}

# per-request options an arrival tuple's 4th element may carry — ONE
# vocabulary/coercion for every arrival-driven loop (RequestManager and
# the fleet router), so adding an option here reaches both and a
# malformed dict rejects identically instead of drifting
ARRIVAL_OPTION_KEYS = frozenset({"priority", "ttl_s", "deadline_s", "spec",
                                 "slo_class"})


def parse_arrival_options(rest) -> Tuple[Dict, Optional[str]]:
    """Parse an arrival tuple's optional trailing options dict into
    ``register_new_request`` kwargs.  Returns ``(opts, reject_reason)``
    — malformed dicts (unknown keys, uncoercible values) yield a reject
    reason so one bad arrival registers as ``REJECTED`` instead of
    killing the serve loop."""
    if not rest:
        return {}, None
    if not isinstance(rest[0], dict) or set(rest[0]) - ARRIVAL_OPTION_KEYS:
        return {}, f"bad arrival options {rest[0]!r}"
    try:
        return {k: (int(v) if k == "priority"
                    else bool(v) if k == "spec"
                    else str(v) if k == "slo_class"
                    else float(v))
                for k, v in rest[0].items() if v is not None}, None
    except (TypeError, ValueError):
        return {}, f"bad arrival options {rest[0]!r}"


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int = 64
    status: RequestStatus = RequestStatus.PENDING
    generated: List[int] = dataclasses.field(default_factory=list)
    prefill_offset: int = 0     # prefill tokens already fed to the model
    slot: int = -1
    trace_id: str = ""          # stable per-request telemetry/trace tag
    # consecutive mixed-batch steps in which the tiled budget rounded this
    # request's prefill take to zero (starvation fallback, ADVICE r5 low)
    starved_steps: int = 0
    # resilient serving (serve/resilience.py): scheduling priority (higher
    # wins admission; preemption only ever evicts strictly-lower priority),
    # an absolute deadline on the manager's clock, the host-side cancel
    # flag reaped at step boundaries, and the terminal outcome tag
    priority: int = 0
    deadline_s: Optional[float] = None
    cancel_requested: bool = False
    outcome: str = ""
    preemptions: int = 0
    requeues: int = 0
    # preemption-and-recompute: after eviction the request re-prefills
    # ``prompt + generated`` (KV is always recomputable from them);
    # ``prefill_src`` is that feed (None = the prompt itself) and
    # ``n_prefed`` how many generated tokens it contains — the correction
    # ``seq_len`` needs while the recompute prefill is in flight.
    prefill_src: Optional[List[int]] = None
    n_prefed: int = 0
    # host-tier KV (serve/kv_paged.py): True while this binding's cache
    # was (partly) restored from a host-tier spill instead of recomputed.
    # Once the catch-up prefill completes, the lifecycle scan retires the
    # recompute feed early (prefill_src is dead weight the moment the
    # cache is whole) — only terminal paths dropped it before.
    kv_restored: bool = False
    # memory observability (serve/kv_allocator.py): peak committed-KV bytes
    # this request held across its slot bindings — stamped by the
    # allocator's release() on every slot-leaving path, carried on finish
    # telemetry and serving records
    kv_bytes: float = 0.0
    # speculative serving (serve/spec_infer.py): per-request speculation
    # mode, set at admission (``register_new_request(spec=...)``) and
    # flippable at runtime (``set_spec_mode``).  Under a SpecInferManager,
    # spec rows carry a draft-token tree and verify multi-token per macro
    # step while plain rows decode one token in the SAME verify batch;
    # under a plain RequestManager the flag is inert (everything rides the
    # incremental loop).
    spec: bool = False
    # SLO-class lanes (serve/slo.py): the traffic class this request
    # resolved to at registration ("" = no policy attached — every lane
    # knob is inert).  ``deferred_ticks`` counts brownout windows the
    # request spent queue-held at DEFER_BATCH or above (explicit,
    # observable deferral — it still ends in a terminal outcome: ok,
    # timeout, or a brownout-shed REJECTED, never FAILED).
    slo_class: str = ""
    deferred_ticks: int = 0

    @property
    def prefill_tokens(self) -> List[int]:
        """The token sequence prefill feeds (prompt, or prompt+generated
        while recovering from preemption)."""
        return self.prompt if self.prefill_src is None else self.prefill_src

    @property
    def seq_len(self) -> int:
        """Tokens currently in the KV cache (after the last step)."""
        return self.prefill_offset + len(self.generated) - self.n_prefed


@dataclasses.dataclass
class GenerationConfig:
    max_new_tokens: int = 64
    eos_token_id: Optional[int] = None
    stop_on_eos: bool = True
    # sampling (reference: GenerationConfig in flexflow/inference.py + the
    # Sampling op).  temperature <= 0 -> exact greedy argmax.  Speculative
    # serving supports it too: the verify step samples per tree node and the
    # accept walk matches drafts against the sampled tokens (spec_infer
    # ._verify_phase / spec_scan._macro_body), preserving the target
    # sampling distribution for any draft model.
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0


class RequestManager:
    request_cls = Request  # subclasses (SpecInferManager) extend the record
    # speculation mode new requests default to (``register_new_request``'s
    # ``spec=None``): the plain manager serves everything incrementally;
    # SpecInferManager flips this to True so its historical all-spec
    # behavior is unchanged unless a caller opts rows out per request
    default_spec_mode = False

    def __init__(self, im, gen_config: Optional[GenerationConfig] = None,
                 telemetry=None, resilience: Optional[ResilienceConfig] = None,
                 fault_injector=None, clock=None, plan_health=None,
                 profiler=None, slo=None, brownout=None):
        import time as _time

        self.im = im
        self.gen = gen_config or GenerationConfig()
        self.requests: Dict[int, Request] = {}
        self.pending: List[int] = []
        # serve-step stamp of each rid's entry into ``pending`` — read
        # by _pop_pending's bounded aging (starvation_bound_ticks)
        self._pending_since: Dict[int, int] = {}
        self.slots: List[Optional[int]] = [None] * im.max_requests
        self._next_rid = 0
        self.steps = 0
        self.tokens_decoded = 0
        self.scan_runs = 0      # decode stretches run as on-device scans
        # ONE Telemetry handle across the serving stack: syncing it onto the
        # InferenceManager (which forwards to pipeline stages) puts request
        # lifecycle, dispatch spans, and per-stage events on one clock/ring.
        # ALWAYS synced — exactly the handle passed here (or the no-op) —
        # so a shared/cached im can never leak a previous run's live handle
        # into a manager built without one.  Host-side only — a handle can
        # never change serve outputs (tests/test_obs.py bit-identity).
        self.telemetry = telemetry_or_null(telemetry)
        im.telemetry = self.telemetry
        self._tstamps: Dict[int, Dict[str, float]] = {}  # rid -> stamps
        # step-level cost attribution (obs/profiler.py): ONE StepProfiler
        # handle shared with the InferenceManager (and every pp stage /
        # the spec draft model) exactly like the telemetry handle — and,
        # like it, ALWAYS synced so a shared/cached im can never leak a
        # previous run's live profiler.  Host-side only: phase timing +
        # deterministic counters computed from host bookkeeping, never a
        # device read — serve outputs are bit-identical with the profiler
        # on or off (tests/test_profiler.py).
        from ..obs.profiler import profiler_or_null

        self.profiler = profiler_or_null(profiler)
        im.profiler = self.profiler
        if self.profiler.enabled:
            self.profiler.install(im)
            self.profiler.bind(self.telemetry)
        # KV ownership (serve/kv_allocator.py): a fresh manager restarts
        # rids from 0, so any attribution a previous manager left on a
        # shared/cached im must not alias the new rid space; and the
        # deployment's predicted-vs-allocated HBM is recorded into the
        # handle's memory ledger once, here (host-side only — pinned
        # bit-identical with the layer on or off).
        kv = getattr(im, "kv", None)
        if kv is not None:
            kv.reset_attribution()
        if self.telemetry.enabled and hasattr(im, "publish_memory"):
            im.publish_memory(self.telemetry)
        # resilient serving (serve/resilience.py): admission/deadline/
        # preemption/retry policy + the seeded chaos hook.  The injector is
        # synced onto the InferenceManager like the telemetry handle (same
        # cached-im leak rationale); it is consulted at dispatch sites
        # BEFORE any work reaches the device.
        self.res = resilience or ResilienceConfig()
        if self.res.kv_gate and self.res.kv_budget_bytes is not None:
            from .resilience import kv_bytes_per_token

            # an explicit BYTE cap needs the allocated caches to price
            # requests in bytes — gating token-slot units against a byte
            # budget would silently admit everything
            if kv_bytes_per_token(im) is None:
                raise ValueError(
                    "kv_budget_bytes needs allocated KV caches to price "
                    "requests in bytes; call init_operators_inference() "
                    "before building the RequestManager (or use "
                    "kv_headroom_frac, which gates in position units)")
        self.injector = fault_injector
        im.fault_injector = fault_injector
        # host-tier KV spill/restore (serve/kv_paged.py): a positive
        # ``host_tier_bytes`` attaches the bounded host-DRAM tier under
        # the PAGED allocator — preemption/eviction then spill pages
        # instead of dropping them, and readmission restores (checksum-
        # verified) instead of re-prefilling.  No-op for the
        # slot-contiguous allocator (attach_host_tier returns None there).
        if kv is not None and self.res.host_tier_bytes:
            kv.attach_host_tier(self.res.host_tier_bytes)
        # deadline/TTL clock — serve_with_arrivals swaps in its loop clock
        # for its duration so virtual-clock tests stay hermetic; _sleep is
        # the retry backoff's wait (injectable for the same reason)
        self.clock = clock or _time.perf_counter
        self._sleep = _time.sleep
        # plan-health monitoring (obs/plan_health.py): an attached
        # PlanHealthMonitor is polled every ``health_check_every`` serve
        # ticks (and once when a serve loop drains) — host-side arithmetic
        # over the telemetry registry only, so attaching one can never
        # change serve outputs (tests/test_plan_health.py bit-identity).
        # The monitor emits ``replan_recommended``; an attached
        # MigrationController (serve/migration.py) consumes it and
        # executes the live plan switch at a tick boundary — without one,
        # the recommendation is report-only.
        # The manager's KVAllocator is handed to the monitor so its
        # OOM-risk check prices projected KV growth against REAL headroom.
        self.plan_health = plan_health
        if (plan_health is not None
                and getattr(plan_health, "kv_allocator", None) is None):
            plan_health.kv_allocator = kv
        self._health_ticks = 0
        # live plan migration (serve/migration.py): an attached
        # MigrationController gets a tick-boundary slot via
        # _maybe_migrate; while it drains the incumbent, admission to
        # engine slots is closed (requests still enqueue — nothing new
        # takes a slot) so the drain converges
        self.migration = None
        self.admission_closed = False
        # SLO-class lanes + brownout (serve/slo.py): an attached
        # SLOPolicy classifies requests at registration (priority band,
        # per-class bounded queue, reserved-KV-headroom gate); an
        # attached BrownoutController is evaluated every
        # ``config.check_every`` serve ticks and its level's actions
        # (defer / degrade / shed of degradable classes) apply at tick
        # boundaries.  Both default off — behavior is unchanged without
        # them.  Under a FleetRouter the FLEET owns policy + controller
        # (one ladder over the whole fleet); replicas get references for
        # their queue gates but only the fleet EVALUATES the ladder
        # (this manager's _maybe_brownout runs from its own serve loops,
        # which the fleet never drives).
        self.slo = slo
        self.brownout = brownout
        if brownout is not None and slo is None:
            self.slo = brownout.policy
        self._brownout_ticks = 0
        # an attached monitor inherits the manager's lane policy (the
        # per-class SLO checks) and ladder (batch breaches escalate
        # brownout before recommending replan) unless wired explicitly —
        # the same auto-wiring pattern as kv_allocator above
        if plan_health is not None:
            if getattr(plan_health, "slo", None) is None:
                plan_health.slo = self.slo
            if getattr(plan_health, "brownout", None) is None:
                plan_health.brownout = self.brownout

    @staticmethod
    def _fold_for(req: Request) -> Tuple[int, int]:
        """THE per-request sample-key fold: (rid, index of the token about
        to be sampled).  Every sampled dispatch path must build its folds
        through this one helper — the seeded bit-identity contract holds
        only while step, decode-scan, and prefill-stretch agree on it."""
        return (req.rid & 0x7FFFFFFF, len(req.generated))

    def _sample_for(self, points, n_rows: int):
        """Per-request sampling arg for an incremental step: ``(key,
        temperature, top_p, folds)`` with ``folds[row] = (rid, n)`` for each
        sample point — the key for request ``rid``'s ``n``-th generated
        token is ``fold_in(fold_in(PRNGKey(seed), rid), n)``.

        This schedule depends ONLY on (seed, rid, token index), so sampled
        outputs are invariant to batch composition, arrival timing,
        preemption-and-recompute, and dispatch retries — the resilient-
        serving bit-identity contract (tests/test_resilience.py).  Rows
        without a sample point draw from the (0, 0) fold; their samples are
        computed and discarded.  None for greedy.

        ``points`` entries are ``(row, rid)`` or ``(row, rid, offset)`` —
        the optional offset shifts the token index past ``len(generated)``
        (the speculative verify step samples index ``len(generated) +
        tree_depth`` per row; ONE assembly path for every sampled
        dispatch, so the fold scheme cannot silently diverge between the
        incremental and speculative paths).
        """
        if self.gen.temperature <= 0.0:
            return None
        import jax
        import jax.numpy as jnp

        folds = np.zeros((n_rows, 2), np.int32)
        for row, rid, *off in points:
            rid_fold, idx = self._fold_for(self.requests[rid])
            folds[row] = (rid_fold, idx + (off[0] if off else 0))
        return (jax.random.PRNGKey(self.gen.seed),
                jnp.float32(self.gen.temperature),
                jnp.float32(self.gen.top_p), jnp.asarray(folds))

    # ------------------------------------------------------------------
    def _seq_len_needed(self, req: Request) -> int:
        """Cache depth a request may reach (overridden by speculation)."""
        return len(req.prompt) + req.max_new_tokens

    def _validate_request(self, req: Request) -> Optional[str]:
        """Host-side shape validation: the reason string, or None if OK.

        Catching these HERE (satellite of ISSUE 5) turns what used to be a
        device-side shape failure (cache writes past ``max_seq_len`` clamp
        and corrupt the last slot) into a clear host error at registration.
        """
        if not req.prompt:
            return "empty prompt"
        if req.max_new_tokens < 0:
            return f"max_new_tokens {req.max_new_tokens} < 0"
        if len(req.prompt) > self.im.max_seq_len:
            return (f"prompt length {len(req.prompt)} exceeds max_seq_len "
                    f"{self.im.max_seq_len}")
        need = self._seq_len_needed(req)
        if need > self.im.max_seq_len:
            return (f"request needs {need} cache slots (prompt "
                    f"{len(req.prompt)} + max_new_tokens "
                    f"{req.max_new_tokens}), exceeds max_seq_len "
                    f"{self.im.max_seq_len}")
        return None

    def _kv_bytes_per_token(self) -> Optional[float]:
        """Per-position committed-KV cost for the admission gate, or None
        while the caches are unallocated.  Read live from the allocator
        on every call — a cached price could disagree in UNITS with the
        capacity arithmetic (which also degrades to token-slot units)
        after a caller frees the buffers."""
        from .resilience import kv_bytes_per_token

        return kv_bytes_per_token(self.im)

    def _admission_reason(self, req: Request) -> Optional[str]:
        """Capacity gate: the rejection reason, or None to admit.

        Prices the new request's worst-case cache need against the bounded
        pending queue and the KV headroom every live (pending + slotted)
        request has already committed — ``plan_memory_bytes``-style
        arithmetic over the allocated cache buffers.
        """
        res = self.res
        if res.max_pending is not None and len(self.pending) >= res.max_pending:
            return (f"pending queue full ({len(self.pending)} >= "
                    f"{res.max_pending})")
        reason = self._lane_admission_reason(req)
        if reason is not None:
            return reason
        if res.kv_gate:
            per_tok = self._kv_bytes_per_token()
            if per_tok is None and res.kv_budget_bytes is not None:
                # an explicit BYTE cap cannot be priced without allocated
                # caches (the __init__ guard checked once, but a caller
                # can free HBM later via ``im.state = None``) — gating
                # token-slot units against a byte budget would silently
                # admit everything, so fail SAFE and reject instead
                return ("kv_budget_bytes is a byte cap but the KV caches "
                        "are unallocated (no byte price); re-allocate "
                        "caches or gate with kv_headroom_frac")
            per_tok = per_tok or 1.0  # token-slot units for the frac gate
            live = [self.requests[r] for r in self.pending] + [
                r for r in self._active()
                if r.status in (RequestStatus.PREFILLING,
                                RequestStatus.DECODING)]
            # page-granular under a paged allocator: a request can only
            # ever hold whole pages, so its worst-case need rounds up to
            # the page size (round_need is identity for slot-contiguous)
            kv0 = getattr(self.im, "kv", None)
            rnd = kv0.round_need if kv0 is not None else (lambda t: t)
            committed = sum(rnd(self._seq_len_needed(r)) for r in live) \
                + rnd(self._seq_len_needed(req))
            # the budget: an explicit byte cap when configured (this is
            # where the per-token BYTE pricing decides — int8 vs bf16 KV
            # admit differently under the same cap), else the headroom
            # fraction of the allocator's own byte capacity — ONE
            # arithmetic, owned by the KVAllocator, shared with
            # preemption pricing and the memory ledger
            kv = getattr(self.im, "kv", None)
            cap_bytes = (res.kv_budget_bytes
                         if res.kv_budget_bytes is not None
                         else res.kv_headroom_frac
                         * (kv.capacity_tokens if kv is not None
                            else self.im.max_requests * self.im.max_seq_len)
                         * per_tok)
            if committed * per_tok > cap_bytes:
                return (f"KV headroom: {committed * per_tok / 2**20:.2f}"
                        f" MiB committed > {cap_bytes / 2**20:.2f} MiB "
                        "budget")
            # reserved-lane gate (serve/slo.py): same budget, same
            # rounded worst-case needs — each class's committed charges
            # its own reservation first, only the overflow competes for
            # the shared pool, so batch traffic can never consume the
            # latency-critical lane's reservation
            reason = self._lane_reservation_reason(
                req, live, cap_bytes,
                lambda r: rnd(self._seq_len_needed(r)) * per_tok)
            if reason is not None:
                return reason
        return None

    def _lane_reservation_reason(self, req: Request, live, budget: float,
                                 price) -> Optional[str]:
        """The per-class reserved-KV-headroom check (None without a
        policy or when no class reserves anything).  ``price(r)`` is the
        SAME worst-case-need arithmetic the total gate just used."""
        slo = self.slo
        if slo is None or not any(c.kv_reservation_frac
                                  for c in slo.classes.values()):
            return None
        cls = slo.resolve(req.slo_class)
        if cls is None:
            return None
        from .slo import reservation_reason

        by_cls: Dict[str, float] = {}
        for r in live:
            rc = slo.resolve(r.slo_class)
            key = rc.name if rc is not None else r.slo_class
            by_cls[key] = by_cls.get(key, 0.0) + price(r)
        return reservation_reason(slo, by_cls, cls, price(req), budget)

    def _lane_admission_reason(self, req: Request) -> Optional[str]:
        """Lane-level admission checks: the brownout ladder's admission
        gate for degradable classes and the per-class bounded pending
        queue.  None without a policy."""
        if self.slo is None:
            return None
        cls = self.slo.resolve(req.slo_class)
        if cls is None:
            return None  # unknown class is caller invalidity, not capacity
        bo = self.brownout
        if bo is not None and not bo.admits(cls.name):
            if self.telemetry.enabled:
                self.telemetry.lane_shed(cls.name, trace_id=req.trace_id,
                                         reason=f"brownout:{bo.level.name}")
            return (f"brownout {bo.level.name}: class {cls.name!r} "
                    "admissions shed")
        if cls.max_pending is not None:
            depth = sum(1 for rid in self.pending
                        if self.requests[rid].slo_class == cls.name)
            if depth >= cls.max_pending:
                return (f"class {cls.name!r} pending queue full "
                        f"({depth} >= {cls.max_pending})")
        return None

    def register_new_request(
        self, prompt_tokens: Sequence[int],
        max_new_tokens: Optional[int] = None, *,
        priority: int = 0, ttl_s: Optional[float] = None,
        deadline_s: Optional[float] = None, reject_invalid: bool = False,
        reject_reason: Optional[str] = None, spec: Optional[bool] = None,
        slo_class: Optional[str] = None,
    ) -> int:
        """Register a request; returns its rid.

        Invalid shapes (empty prompt, negative ``max_new_tokens``, prompt or
        prompt+max_new exceeding ``max_seq_len``) raise a host-side
        ``ValueError`` — unless ``reject_invalid`` is set (the arrival loop
        uses it), in which case the request is registered with a terminal
        ``REJECTED`` outcome instead, so one malformed arrival can never
        kill the serve loop.  Admission-control rejections (bounded queue /
        KV headroom, see :class:`~.resilience.ResilienceConfig`) always
        take the explicit ``REJECTED``-outcome path.  ``ttl_s`` (relative)
        or ``deadline_s`` (absolute on the manager's clock) arm a per-
        request deadline; ``max_new_tokens=0`` completes immediately with
        an ``ok`` outcome and zero tokens.  ``spec`` sets the request's
        speculation mode (None = the manager's ``default_spec_mode``);
        meaningful under a :class:`~.spec_infer.SpecInferManager`, inert
        otherwise.  ``slo_class`` names the request's traffic lane under
        an attached :class:`~.slo.SLOPolicy` (None/"" = the policy's
        default class; an unknown name is caller invalidity, rejected
        like a bad shape); the class's priority band adds to
        ``priority``, its brownout/queue/reservation gates apply, and an
        in-force DEGRADE_BATCH output cap truncates ``max_new_tokens``
        at admission.
        """
        req = self.request_cls(
            -1,
            list(int(t) for t in prompt_tokens),
            self.gen.max_new_tokens if max_new_tokens is None else int(max_new_tokens),
        )
        req.spec = bool(self.default_spec_mode if spec is None else spec)
        band = 0
        if self.slo is not None:
            cls = self.slo.resolve(slo_class)
            if cls is None:
                req.slo_class = str(slo_class)
            else:
                req.slo_class = cls.name
                band = cls.priority_band
        # reject_reason: caller-side invalidity (e.g. malformed arrival
        # options) that must take the REJECTED path like any shape error
        err = reject_reason if reject_reason is not None \
            else self._validate_request(req)
        if err is None and self.slo is not None \
                and self.slo.resolve(slo_class) is None:
            err = f"unknown slo_class {slo_class!r}"
        if err is not None and not reject_invalid:
            raise ValueError(err)
        rid = self._next_rid
        self._next_rid += 1
        req.rid = rid
        req.trace_id = f"r{rid:05d}"
        req.priority = int(priority) + band
        self.requests[rid] = req
        tel = self.telemetry
        if tel.enabled:
            self._tstamps[rid] = {
                "enqueue": tel.request_enqueued(req.trace_id,
                                                prompt_len=len(req.prompt))
            }
        reason = err if err is not None else self._admission_reason(req)
        if reason is not None:
            req.status = RequestStatus.REJECTED
            req.outcome = "rejected"
            # shed load must not grow host memory: the prompt tokens of a
            # rejected request are never served, so drop them — the
            # retained record is a small fixed-size stub (backpressure
            # would be pointless if every shed arrival kept its payload)
            req.prompt = []
            if tel.enabled:
                tel.request_rejected(req.trace_id, reason=reason)
            return rid
        if req.max_new_tokens == 0:
            # nothing to generate: terminal immediately, never takes a slot
            req.status = RequestStatus.COMPLETED
            req.outcome = "ok"
            if tel.enabled:
                tel.request_finished(req.trace_id, n_tokens=0,
                                     slo_class=req.slo_class or None)
            return rid
        if self.brownout is not None and self.brownout.degrades(
                req.slo_class):
            # DEGRADE_BATCH in force: admit, but speculation off and the
            # class's output cap applied up front (truncation only — the
            # served tokens stay a bit-identical PREFIX of the unloaded
            # run's stream).  Counted only when something actually
            # changed — lane_degraded_total is in bench_compare's exact
            # class, so a no-op "degradation" must not inflate it
            changed = req.spec
            req.spec = False
            cap = self.brownout.output_cap(req.slo_class)
            if cap is not None and cap < req.max_new_tokens:
                req.max_new_tokens = cap
                changed = True
            if changed and tel.enabled:
                tel.lane_degraded(req.slo_class)
        if deadline_s is not None:
            req.deadline_s = float(deadline_s)
        else:
            ttl = ttl_s if ttl_s is not None else self.res.default_ttl_s
            if ttl is not None:
                req.deadline_s = self.clock() + float(ttl)
        self.pending.append(rid)
        self._pending_since[rid] = self.steps
        return rid

    # ------------------------------------------------------------------
    # resilient-serving lifecycle: cancel / deadline / preempt / fail
    # ------------------------------------------------------------------
    def cancel(self, rid: int) -> bool:
        """Request cancellation of ``rid``; returns whether it was live.

        Takes effect at the NEXT host step boundary (``_check_lifecycle``):
        the slot and KV release immediately there, already-committed tokens
        are kept, and in-flight device work for the current step/scan is
        never interrupted — scan results for other requests are unchanged.
        A cancel issued while a decode STRETCH is in flight therefore lands
        only when that stretch returns (up to ``scan_chunk`` steps; once
        the flag is visible before dispatch, stretches are capped at
        ``lifecycle_quantum`` steps, the same bound armed deadlines get).
        """
        req = self.requests.get(rid)
        if req is None or req.status in TERMINAL_STATUSES:
            return False
        req.cancel_requested = True
        return True

    def set_spec_mode(self, rid: int, enabled: bool) -> bool:
        """Flip a live request's speculation mode at runtime; returns
        whether it was live.  Takes effect at the next macro-step/tick
        boundary — in-flight device work is never interrupted, so a flip
        can never change already-committed tokens.  Under a plain
        RequestManager the flag is inert; SpecInferManager reacts via
        :meth:`_on_spec_flip` (draft-cache catch-up on enable)."""
        req = self.requests.get(rid)
        if req is None or req.status in TERMINAL_STATUSES:
            return False
        enabled = bool(enabled)
        if req.spec == enabled:
            return True
        req.spec = enabled
        self._on_spec_flip(req)
        if self.telemetry.enabled:
            self.telemetry.spec_mode_changed(req.trace_id, spec=enabled)
        return True

    def _on_spec_flip(self, req: Request) -> None:
        """Hook for managers that keep per-mode state (the spec manager
        rebuilds the draft model's catch-up feed on enable)."""

    def _release_slot(self, req: Request) -> None:
        if req.slot >= 0:
            self.slots[req.slot] = None
            req.slot = -1
            # EVERY slot-leaving path — completion, cancel, timeout,
            # failure, preemption — releases the request's KV attribution
            # here, so no terminal outcome can leak it (pinned by
            # tests/test_kv_allocator.py); the returned peak-bytes stamp
            # rides finish telemetry and serving records
            kv = getattr(self.im, "kv", None)
            if kv is not None:
                req.kv_bytes = max(
                    req.kv_bytes, kv.release(req.rid, tokens=req.seq_len))

    def _terminate(self, req: Request, status: RequestStatus,
                   site: str = "") -> None:
        """Move a request to a terminal status, releasing queue slot + KV.
        The outcome tag derives from the one status->outcome table
        (``OUTCOMES``) so the two can never drift; ``site`` attributes a
        FAILED termination to the dispatch site that exhausted its
        retries."""
        if req.rid in self.pending:
            self.pending.remove(req.rid)
        self._pending_since.pop(req.rid, None)
        self._release_slot(req)
        req.prefill_src = None  # recompute feed is dead weight once terminal
        kv = getattr(self.im, "kv", None)
        if kv is not None and kv.host_tier is not None:
            # a terminal request's host-tier pages are garbage too — drop
            # them now instead of waiting for the tier's LRU (the no-leak
            # contract extends to the host tier per terminal outcome)
            kv.drop_spill(req.rid)
        req.status = status
        req.outcome = OUTCOMES[status]
        if status is RequestStatus.REJECTED:
            # post-registration shed (brownout): same contract as the
            # admission path — shed load must not grow host memory
            req.prompt = []
        tel = self.telemetry
        if tel.enabled:
            n = len(req.generated)
            if status is RequestStatus.CANCELLED:
                tel.request_cancelled(req.trace_id, n_tokens=n)
            elif status is RequestStatus.TIMED_OUT:
                tel.request_timed_out(req.trace_id, n_tokens=n)
            elif status is RequestStatus.REJECTED:
                tel.request_rejected(req.trace_id,
                                     reason=site or "brownout shed")
            elif status is RequestStatus.FAILED:
                tel.request_failed(req.trace_id, site=site)

    def _swap_clock(self, new_clock):
        """Switch the deadline clock, RE-BASING every live armed deadline
        so its remaining budget is preserved — a TTL armed on the default
        ``perf_counter`` clock must still fire correctly once
        ``serve_with_arrivals`` swaps in an injected loop clock (and back).
        Returns the previous clock for the symmetric restore."""
        old = self.clock
        if new_clock is old:
            return old
        live = [self.requests[r] for r in self.pending] + self._active()
        armed = [r for r in live if r.deadline_s is not None]
        if armed:
            old_now, new_now = old(), new_clock()
            for req in armed:
                req.deadline_s = new_now + (req.deadline_s - old_now)
        self.clock = new_clock
        return old

    def _check_lifecycle(self, now: Optional[float] = None) -> None:
        """Step-boundary reaping of cancellations and deadline expiries —
        the ONE place a live request can leave the engine for a reason
        other than completing (host bookkeeping only; a reap between two
        steps can never change other requests' results).

        Scans only the LIVE requests (pending queue + slots), never the
        full registration history, so per-tick cost stays O(live) over
        long serving sessions.
        """
        live = [self.requests[r] for r in self.pending] + self._active()
        # host-tier satellite: a restored request that finished its
        # (shortened) catch-up prefill retires the recompute feed HERE —
        # before this, only terminal paths dropped ``prefill_src``
        # (_terminate), so a swap-restored request would carry a
        # dead-weight prompt+generated copy for its whole decode.  The
        # rebase is seq_len-invariant: ``prefill_offset - n_prefed`` is
        # exactly the prompt-only offset the unpreempted run would hold.
        for r in live:
            if (r.kv_restored and r.prefill_src is not None
                    and r.prefill_offset >= len(r.prefill_src)):
                r.prefill_offset -= r.n_prefed
                r.n_prefed = 0
                r.prefill_src = None
                r.kv_restored = False
        expirable = [r for r in live
                     if r.cancel_requested or r.deadline_s is not None]
        if not expirable:
            return
        if now is None:
            now = self.clock()
        for req in expirable:
            if req.cancel_requested:
                self._terminate(req, RequestStatus.CANCELLED)
            elif req.deadline_s is not None and now >= req.deadline_s:
                self._terminate(req, RequestStatus.TIMED_OUT)

    def preempt(self, rid: int) -> None:
        """Evict a running request, releasing its slot + KV immediately.

        The request re-enters the pending queue (status ``PREEMPTED``) and
        on readmission RE-PREFILLS ``prompt + generated`` — after which
        its served tokens are bit-identical to an unpreempted run for
        greedy AND seeded sampling (the per-request sample-key schedule
        keys on (rid, token index) only; pinned by
        tests/test_resilience.py, incl. int8 KV).  With a host tier
        attached, the victim's written pages spill to host DRAM first:
        readmission then restores them and recomputes only the unspilled
        tail — same bit-identity contract, O(transfer) instead of
        O(prefill).
        """
        req = self.requests[rid]
        if req.status not in (RequestStatus.PREFILLING,
                              RequestStatus.DECODING):
            raise ValueError(
                f"cannot preempt request {rid} in status {req.status.name}")
        self._kv_spill(req, getattr(self.im, "kv", None))
        self._release_slot(req)
        req.prefill_src = list(req.prompt) + list(req.generated)
        req.n_prefed = len(req.generated)
        req.prefill_offset = 0
        req.starved_steps = 0
        req.kv_restored = False
        req.status = RequestStatus.PREEMPTED
        req.preemptions += 1
        self.pending.append(rid)
        # the aging clock restarts on preemption: it measures time
        # waiting for THIS admission, not lifetime
        self._pending_since[rid] = self.steps
        tel = self.telemetry
        if tel.enabled:
            tel.request_preempted(req.trace_id,
                                  recompute_tokens=len(req.prefill_src))

    # whether dispatch-failure recovery may requeue-and-recompute by
    # re-prefilling prompt+generated — True across the serving stack
    # (SpecInferManager included since ISSUE 11: its preempt() resets the
    # spec bookkeeping and the readmission re-prefills BOTH models'
    # caches); a subclass without a recompute story would flip this off
    # to make its failures go terminal instead
    supports_recompute = True

    # fleet failover hook (serve/fleet.py): when a dispatch exhausts its
    # retry budget, an attached ``on_exhausted(rm, site, exc,
    # affected_fn)`` may take over recovery — returning True means it
    # handled the affected requests (the fleet router preempts them and
    # fails them over to a surviving replica, so exhaustion on a dying
    # replica never goes terminally ``FAILED``); returning False (or no
    # hook — the default, pinned by tests/test_resilience.py) keeps the
    # single-replica r9 behavior: requeue-on-this-manager or FAILED per
    # ``res.on_dispatch_failure``.
    on_exhausted = None

    def _rids_in_batch(self, bc) -> List[int]:
        """The rids whose tokens are actually IN a built batch (a slotted
        request can sit out a step, e.g. a prefill starved of budget —
        dispatch failure must not touch it)."""
        base = bc if isinstance(bc, BatchConfig) else bc.base
        n = int(np.asarray(base.num_tokens))
        slots = {int(s) for s in np.asarray(base.request_index)[:n]
                 if int(s) >= 0}
        return [self.slots[s] for s in sorted(slots)
                if self.slots[s] is not None]

    def _fail_inflight(self, site: str, exc: Exception,
                       affected_fn=None) -> None:
        """Dispatch exhausted its retry budget: degrade gracefully.

        Only the requests whose tokens were in the failed batch
        (``affected_fn``, defaulting to every running slotted request for
        the stretch paths, where that is exact) are affected — per
        ``res.on_dispatch_failure`` they are requeued for recompute
        (bounded by ``max_requeues``) or failed terminally; everyone else
        keeps serving.  Faults are injected/raised before dispatch, so no
        partial device state exists to clean up.
        """
        if affected_fn is not None:
            affected = [self.requests[rid] for rid in affected_fn()]
        else:
            affected = self._active()
        affected = [r for r in affected
                    if r.status in (RequestStatus.PREFILLING,
                                    RequestStatus.DECODING)]
        for req in affected:
            if (self.supports_recompute
                    and self.res.on_dispatch_failure == "requeue"
                    and req.requeues < self.res.max_requeues):
                req.requeues += 1
                self.preempt(req.rid)
            else:
                self._terminate(req, RequestStatus.FAILED, site=site)

    def _guarded(self, site: str, fn, affected_fn=None):
        """Run one dispatch under the retry policy.

        Retries :class:`~.resilience.TransientServeError` with exponential
        backoff up to ``res.retry.max_retries`` times; a retried dispatch
        replays identical compute (faults raise pre-dispatch; device KV
        writes are positional and value-deterministic, so replay is
        idempotent).  Returns ``fn()``, or None once the budget is
        exhausted — the affected requests (``affected_fn``, evaluated only
        then) were requeued or failed via :meth:`_fail_inflight` and the
        serve loop continues.
        """
        pol = self.res.retry
        tel = self.telemetry
        attempt = 0
        while True:
            try:
                return fn()
            except TransientServeError as e:
                if tel.enabled:
                    tel.fault_observed(site, detail=str(e))
                if attempt >= pol.max_retries:
                    hook = self.on_exhausted
                    if hook is not None and hook(self, site, e,
                                                 affected_fn):
                        return None
                    self._fail_inflight(site, e, affected_fn)
                    return None
                attempt += 1
                delay = pol.backoff(attempt)
                if tel.enabled:
                    tel.dispatch_retry(site, attempt=attempt,
                                       backoff_s=delay)
                if delay > 0:
                    self._sleep(delay)

    # ------------------------------------------------------------------
    def _prof_account(self, spans, passes: int = 1, logit_rows=None,
                      im=None) -> None:
        """Deterministic work accounting for one dispatch group
        (obs/profiler.py): ``spans`` are the same ``(rid, lo, hi)``
        cache-write spans ``_kv_prepare`` consumes — ``hi - lo`` tokens
        fed, reading the ``hi``-deep causally-live prefix.  Host
        arithmetic only; no-op for the null profiler."""
        prof = self.profiler
        if not prof.enabled or not spans:
            return
        prof.account(prof.card_for(im or self.im),
                     [(rid, hi - lo, hi) for rid, lo, hi in spans],
                     passes=passes, logit_rows=logit_rows)

    # bounded aging for the priority queue (the fleet router sets this
    # from ``FleetConfig.starvation_bound_ticks`` on every replica): a
    # request pending longer than this many serve steps becomes OVERDUE
    # and is admitted ahead of every priority band (FIFO among overdue),
    # so a lower-priority class behind a sustained higher-priority
    # stream is starved only up to the bound.  None (the single-manager
    # default) keeps the historical strict-priority behavior.  A
    # brownout DEFER hold is exempt — an explicit policy state with its
    # own hysteresis-bounded exit, not priority competition.
    starvation_bound_ticks: Optional[int] = None

    def _held(self, req: Request) -> bool:
        """DEFER_BATCH semantics: is this queued request held out of
        engine slots by the brownout ladder this tick?  (Explicit policy
        hold — distinct from priority starvation, which the bounded
        aging above caps.)"""
        return (self.brownout is not None
                and self.brownout.holds(req.slo_class))

    def _pop_pending(self) -> Optional[int]:
        """Highest-priority ELIGIBLE pending rid, FIFO within a priority
        class — except OVERDUE requests (pending past the aging bound),
        which jump every band, oldest first.  None when every pending
        request is brownout-held."""
        cands = []
        for i in range(len(self.pending)):
            if self._held(self.requests[self.pending[i]]):
                # hold time is EXEMPT from aging (the documented
                # contract): re-stamp so the age measures only time
                # spent losing priority competition, not policy holds —
                # otherwise a long DEFER would mark the whole held
                # backlog overdue and batch would jump the
                # latency-critical lane exactly at recovery
                self._pending_since[self.pending[i]] = self.steps
            else:
                cands.append(i)
        if not cands:
            return None
        bound = self.starvation_bound_ticks
        if bound is not None:
            # setdefault: rids whose entry was not stamped (e.g. a
            # migration successor's wholesale pending list) start aging
            # from their first admission attempt
            overdue = [i for i in cands
                       if self.steps - self._pending_since.setdefault(
                           self.pending[i], self.steps) >= bound]
            if overdue:
                best = min(overdue,
                           key=lambda i: (self._pending_since.get(
                               self.pending[i], self.steps), i))
                self._pending_since.pop(self.pending[best], None)
                return self.pending.pop(best)
        best = max(cands,
                   key=lambda i: (self.requests[self.pending[i]].priority,
                                  -i))
        self._pending_since.pop(self.pending[best], None)
        return self.pending.pop(best)

    def _fill_slots(self):
        for i, occupant in enumerate(self.slots):
            if occupant is None and self.pending:
                rid = self._pop_pending()
                if rid is None:
                    break  # everything pending is brownout-held
                req = self.requests[rid]
                req.slot = i
                req.status = RequestStatus.PREFILLING
                self.slots[i] = rid
                self._kv_bind(rid)
                tel = self.telemetry
                if tel.enabled:
                    ts = self._tstamps.setdefault(rid, {})
                    # admission telemetry fires ONCE per request: a
                    # preempted request's READMISSION must not double-count
                    # requests_admitted or push its whole first service
                    # period into the queue_wait histogram
                    if "admit" not in ts:
                        ts["admit"] = tel.request_admitted(
                            req.trace_id,
                            queue_wait_s=(tel.now() - ts["enqueue"]
                                          if "enqueue" in ts else None))

    def _try_preempt(self) -> bool:
        """Preempt the lowest-priority DECODING request (newest first among
        equals) iff a strictly-higher-priority request is waiting and no
        slot is free.  Returns whether an eviction happened."""
        if not self.pending or any(s is None for s in self.slots):
            return False
        # brownout-held requests can neither take a slot nor evict for one
        eligible = [r for r in self.pending
                    if not self._held(self.requests[r])]
        if not eligible:
            return False
        head_pri = max(self.requests[r].priority for r in eligible)
        victims = [r for r in self._active()
                   if r.status is RequestStatus.DECODING
                   and r.priority < head_pri
                   and r.preemptions < self.res.max_preemptions]
        if not victims:
            return False
        victim = min(victims, key=lambda r: (r.priority, -r.rid))
        self.preempt(victim.rid)
        return True

    def _admit(self):
        if self.admission_closed:
            # a migration drain is in progress: nothing new takes a slot
            # (pending requests wait; they transplant to — or readmit
            # after a rollback on — whichever manager serves next)
            return
        self._fill_slots()
        if self.res.preemption:
            # bounded: each iteration either admits into a freed slot or
            # stops (no admissible victim)
            for _ in range(len(self.slots)):
                if not (self.pending and self._try_preempt()):
                    break
                self._fill_slots()

    def _active(self) -> List[Request]:
        return [
            self.requests[rid] for rid in self.slots if rid is not None
        ]

    def has_work(self) -> bool:
        return bool(self.pending) or any(
            r.status in (RequestStatus.PREFILLING, RequestStatus.DECODING)
            for r in self._active()
        )

    # ------------------------------------------------------------------
    def prepare_next_batch(self) -> Tuple[BatchConfig, List[Tuple[int, int]]]:
        """Build the next step's BatchConfig.

        Returns (bc, sample_points) where sample_points is
        ``[(flat_token_index, rid)]`` — the token slots whose model output is
        the next token of that request (last prefill token, or the decode
        token).  Mirrors ``RequestManager::prepare_next_batch``.

        Phase attribution (StepProfiler): admission/slot-fill runs under
        ``host_admit``, batch assembly under ``host_prepare`` — separate
        accumulators, so the time budget shows scheduling cost apart from
        batch-build cost.
        """
        with self.profiler.phase("host_admit"):
            self._admit()
        with self.profiler.phase("host_prepare"):
            return self._build_next_batch()

    def _build_next_batch(self) -> Tuple[BatchConfig, List[Tuple[int, int]]]:
        tokens: List[int] = []
        req_idx: List[int] = []
        positions: List[int] = []
        sample_points: List[Tuple[int, int]] = []
        # cache-write spans this step will perform (rid, lo, hi) — the
        # paged allocator maps/COWs those pages BEFORE dispatch
        spans: List[Tuple[int, int, int]] = []
        budget = self.im.max_tokens

        # decode tokens first: one per DECODING request (latency-critical)
        for req in self._active():
            if req.status is RequestStatus.DECODING and budget > 0:
                pos = req.seq_len - 1
                tokens.append(req.generated[-1])
                req_idx.append(req.slot)
                positions.append(pos)
                sample_points.append((len(tokens) - 1, req.rid))
                spans.append((req.rid, pos, pos + 1))
                budget -= 1

        n_decode = len(tokens)

        # a pure-prefill step with Pallas enabled ships tile-aligned chunks
        # (PrefillBatchConfig -> the Q-tiled prefill kernel); mixed
        # decode+prefill steps keep the flat layout
        tile = getattr(self.im, "prefill_tile", 1)
        if (not tokens and tile > 1 and self.im.use_pallas
                and any(r.status is RequestStatus.PREFILLING
                        for r in self._active())
                # contract (d): tiled segments need tile-aligned starts; an
                # unaligned offset (hand-driven flat steps) rides the flat
                # path instead of crashing the builder
                and all(r.prefill_offset % tile == 0
                        for r in self._active()
                        if r.status is RequestStatus.PREFILLING)):
            segments = []
            for req in self._active():
                if req.status is not RequestStatus.PREFILLING or budget < tile:
                    continue
                # cap at whole tiles so the padded segment fits the capacity
                take = min((budget // tile) * tile,
                           len(req.prefill_tokens) - req.prefill_offset)
                start = req.prefill_offset
                segments.append(
                    (req.slot, req.prefill_tokens[start: start + take], start)
                )
                spans.append((req.rid, start, start + take))
                req.prefill_offset += take
                req.starved_steps = 0
                budget -= -(-take // tile) * tile  # padded tiles consumed
                if req.prefill_offset == len(req.prefill_tokens):
                    sample_points.append((req.slot, req.rid))
            seq_lens = np.zeros(self.im.max_requests, np.int32)
            for req in self._active():
                seq_lens[req.slot] = req.seq_len
            # LM-head gating: completing segments' sample points ride the
            # chunk's logit_slots, the step computes logits ONLY there, and
            # the result arrays are indexed by SLOT (shape [max_requests])
            gate = bool(getattr(self.im, "gate_lm_head", False))
            pbc, last_flat = PrefillBatchConfig.build(
                segments, seq_lens, tile,
                max_tokens=self.im.max_tokens,
                max_requests=self.im.max_requests,
                gate_slots=[slot for slot, _ in sample_points]
                if gate else None,
            )
            sample_points = [
                (slot if gate else last_flat[slot], rid)
                for slot, rid in sample_points
            ]
            self._kv_prepare(spans)
            self._prof_account(
                spans, logit_rows=len(sample_points) if gate else None)
            self._note_batch(0, sum(len(s[1]) for s in segments), seq_lens)
            return pbc, sample_points

        # then prefill chunks fill the remaining budget.  Mid-prompt cuts
        # keep prefill_offset TILE-ALIGNED (round the take down to whole
        # tiles) so later pure-prefill steps can ride the tiled Pallas path
        # — PrefillBatchConfig's contract (d) rejects unaligned segment
        # starts.  Completing takes (remaining <= budget) need no rounding.
        for req in self._active():
            if req.status is not RequestStatus.PREFILLING or budget <= 0:
                continue
            remaining = len(req.prefill_tokens) - req.prefill_offset
            if remaining <= budget:
                take = remaining
            elif (tile > 1 and self.im.use_pallas
                    and req.prefill_offset % tile == 0):
                # only the Pallas tiled path consumes the alignment; the
                # gather path must not stall prefill for it — and a request
                # already off-tile (starvation fallback below) has nothing
                # left to protect, so it skips the rounding entirely
                take = (budget // tile) * tile
                if take == 0:
                    # budget < one tile: normally wait to keep alignment —
                    # but when decode tokens leave less than a tile of
                    # budget EVERY step, waiting starves the prompt until
                    # the decoders finish (unbounded TTFT, ADVICE r5 low).
                    # After ``starvation_limit`` consecutive dry steps, take
                    # an UNALIGNED flat chunk: the offset goes off-tile, so
                    # the tiled-branch alignment gate above routes this
                    # request's later chunks through the flat gather path —
                    # slower per token, but it makes progress every step.
                    req.starved_steps += 1
                    if req.starved_steps < self.starvation_limit:
                        continue
                    take = budget
            else:
                take = budget
                if tile > 1 and self.im.use_pallas and budget >= tile:
                    # an off-tile offset (starvation fallback above) blocks
                    # the tiled pure-prefill path for EVERY concurrently
                    # prefilling request (the alignment gate is all-or-
                    # nothing).  In budget-rich steps round the take so the
                    # offset lands back on a tile boundary: one slightly
                    # smaller take buys the Q-tiled kernel back for the
                    # whole batch.  Starved steps (budget < tile) keep the
                    # full take — progress beats re-alignment there.
                    over = (req.prefill_offset + take) % tile
                    if 0 < over < take:
                        take -= over
            start = req.prefill_offset
            for j in range(take):
                tokens.append(req.prefill_tokens[start + j])
                req_idx.append(req.slot)
                positions.append(start + j)
            if take:
                spans.append((req.rid, start, start + take))
            req.prefill_offset += take
            req.starved_steps = 0
            budget -= take
            if req.prefill_offset == len(req.prefill_tokens):
                # output at the last prefill token = next generated token
                sample_points.append((len(tokens) - 1, req.rid))

        # cache depth after this step: prefill tokens fed so far + generated
        # tokens not already in the feed (the decode token fed this step is
        # generated[-1], whose KV lands at position seq_len-1 during the
        # step) — Request.seq_len is exactly that arithmetic
        seq_lens = np.zeros(self.im.max_requests, np.int32)
        for req in self._active():
            seq_lens[req.slot] = req.seq_len
        bc = BatchConfig.build(
            tokens, req_idx, positions, seq_lens,
            max_tokens=self.im.max_tokens,
            max_requests=self.im.max_requests,
        )
        self._kv_prepare(spans)
        self._prof_account(spans)
        self._note_batch(n_decode, len(tokens) - n_decode, seq_lens)
        return bc, sample_points

    def _note_batch(self, n_decode: int, n_prefill: int, seq_lens) -> None:
        """Batch-composition telemetry for one step (token mix, slot
        occupancy, KV utilization) — host counters only."""
        tel = self.telemetry
        if not tel.enabled:
            return
        tel.batch_composition(
            n_decode, n_prefill,
            active_requests=sum(1 for s in self.slots if s is not None),
            max_requests=self.im.max_requests,
            kv_tokens=int(np.sum(seq_lens)),
            kv_capacity=self.im.max_requests * self.im.max_seq_len,
        )

    def _append_token(self, req: Request, tok: int) -> None:
        """Commit one generated token — the ONE place the first-token
        (TTFT) telemetry stamp can live, whatever path produced the token
        (per-step result, prefill stretch, decode scan, spec verify)."""
        req.generated.append(tok)
        self.tokens_decoded += 1
        tel = self.telemetry
        if tel.enabled and len(req.generated) == 1:
            ts = self._tstamps.setdefault(req.rid, {})
            now = tel.request_first_token(
                req.trace_id,
                ttft_s=(tel.now() - ts["enqueue"]
                        if "enqueue" in ts else None),
                slo_class=req.slo_class or None)
            ts["first_token"] = now

    def process_result(self, result, sample_points) -> None:
        if not sample_points:
            # mid-prefill step: nothing to read back — leave the result on
            # device so chunked prefill dispatches stay fully async
            return
        prof = self.profiler
        with prof.phase("readback"):
            token_ids = np.asarray(result.token_ids)
        prof.host_sync()
        for flat_idx, rid in sample_points:
            req = self.requests[rid]
            if req.status not in (RequestStatus.PREFILLING,
                                  RequestStatus.DECODING):
                # the request left its slot between batch build and result
                # readback (page-pressure preemption in _kv_prepare runs
                # AFTER the batch is built): its emission is dead — the
                # readmission recomputes it, and appending here would
                # double-count the token in the recompute feed
                continue
            tok = int(token_ids[flat_idx])
            if req.status is RequestStatus.PREFILLING:
                req.status = RequestStatus.DECODING
            self._append_token(req, tok)
            self._maybe_finish(req)

    def _maybe_finish(self, req: Request) -> None:
        eos = self.gen.eos_token_id
        if (
            len(req.generated) >= req.max_new_tokens
            or (self.gen.stop_on_eos and eos is not None
                and req.generated and req.generated[-1] == eos)
        ):
            req.status = RequestStatus.COMPLETED
            req.outcome = "ok"
            req.prefill_src = None  # recompute feed is dead once terminal
            self._release_slot(req)
            tel = self.telemetry
            if tel.enabled:
                ts = self._tstamps.get(req.rid, {})
                now = tel.now()
                first = ts.get("first_token")
                tel.request_finished(
                    req.trace_id, n_tokens=len(req.generated),
                    tpot_s=((now - first)
                            / max(len(req.generated) - 1, 1)
                            if first is not None else None),
                    kv_bytes=req.kv_bytes or None,
                    slo_class=req.slo_class or None)

    # ------------------------------------------------------------------
    def _scan_steps_possible(self) -> int:
        """How many pure-decode steps can run as ONE on-device scan now.

        > 1 only when no admission/prefill work is pending and every active
        request is decoding; bounded by the smallest remaining token budget
        (so no slot overshoots max_new_tokens) and by cache headroom.
        """
        active = self._active()
        if (not active
                or any(r.status is not RequestStatus.DECODING
                       for r in active)):
            return 0
        if self.pending:
            # pending work blocks a stretch ONLY when the per-tick path
            # could actually act on it right now — a free slot to fill, or
            # a preemption that would fire.  Otherwise (all slots busy, no
            # victim) the queue is waiting regardless, and the chained
            # stretch path admits mid-stretch joiners itself the moment a
            # slot frees, so the stretch proceeds
            eligible = [rid for rid in self.pending
                        if not self._held(self.requests[rid])]
            chained = (self.chain_segments
                       and hasattr(self.im, "decode_scan_async")
                       and not self.admission_closed)
            if eligible and (not chained
                             or any(s is None for s in self.slots)
                             or self._preempt_would_fire()):
                return 0
        n = min(r.max_new_tokens - len(r.generated) for r in active)
        n = min(n, self.scan_chunk,
                self.im.max_seq_len - max(r.seq_len for r in active) + 1)
        # armed deadlines or pending cancels bound the stretch: lifecycle
        # reaping happens at host step boundaries, so an uncapped scan
        # would overshoot a deadline by up to scan_chunk device steps.
        # (Under the chained path this bounds SEGMENTS, not the stretch —
        # the chain clock-checks between dispatches; see _decode_stretch.)
        if any(r.deadline_s is not None or r.cancel_requested
               for r in active):
            n = min(n, self.lifecycle_quantum)
        # round down to a power of two: n is a STATIC arg of the jitted
        # scan, so every distinct value compiles the whole n-step model —
        # quantizing bounds the compile count to ~log2(scan_chunk) variants
        if n > 1:
            n = 1 << (n.bit_length() - 1)
        return n

    def _preempt_would_fire(self) -> bool:
        """Would _try_preempt evict someone for the head of the queue?
        Mirrors its victim scan without acting — the chained stretch gate
        must fall back to the per-tick path whenever preemption could
        admit pending work (preempting a row the device is mid-scan on
        would corrupt its cache)."""
        if not self.res.preemption or not self.pending:
            return False
        eligible = [rid for rid in self.pending
                    if not self._held(self.requests[rid])]
        if not eligible:
            return False
        head_pri = max(self.requests[rid].priority for rid in eligible)
        return any(r.status is RequestStatus.DECODING
                   and r.priority < head_pri
                   and r.preemptions < self.res.max_preemptions
                   for r in self._active())

    scan_chunk = 32  # sync-amortization window for the decode scan
    # chain decode-scan segments back-to-back (no readback in between) up
    # to scan_chunk total steps, admitting arrivals into the RUNNING
    # batch at segment boundaries (on-device continuous batching).  Off:
    # the legacy one-dispatch-per-stretch path (the bit-identity
    # comparator tests/test_host_tick.py pins against)
    chain_segments = True
    # serve_with_arrivals hooks for the chained path: pump registers
    # newly-due arrivals at segment boundaries; stamp records
    # prefill_start_s for mid-stretch joiners
    _arrival_pump = None
    _join_stamp = None
    # rid -> device exit code of the last chained stretch (EXIT_* in
    # inference_manager.py); rebound per stretch, never mutated in place
    last_exit_codes: Dict[int, int] = {}
    # mixed decode+prefill steps whose tiled budget rounds to 0 before the
    # starved request falls back to an unaligned flat-path take (bounds the
    # TTFT inflation at ~limit decode steps; see prepare_next_batch)
    starvation_limit = 4
    # decode-scan cap while any active request carries a deadline or a
    # pending cancel: bounds how far past a deadline a stretch can run
    # (lifecycle reaping is step-boundary-granular)
    lifecycle_quantum = 8
    # serve ticks between plan-health polls when a monitor is attached
    # (each poll is host-side percentile/PSI arithmetic — cheap, but not
    # free enough for every tick of a hot decode loop)
    health_check_every = 16

    # ------------------------------------------------------------------
    def _prefill_stretch_possible(self) -> bool:
        """Can the whole current prefill wave run as on-device scans?

        True when every active request is PREFILLING (no decode latency to
        protect) and the InferenceManager has the tiled-prefill path.  The
        stretch then feeds every request's remaining prompt through
        ``prefill_scan`` — one dispatch per power-of-two chunk segment and
        ONE host sync at the end, vs a dispatch per chunk (+ a ~100ms tunnel
        sync per request boundary) on the per-step path.
        """
        with self.profiler.phase("host_admit"):
            self._admit()
        active = self._active()
        tile = getattr(self.im, "prefill_tile", 1)
        return (
            tile > 1
            and self.im.use_pallas
            and hasattr(self.im, "prefill_scan")
            and bool(active)
            and all(r.status is RequestStatus.PREFILLING for r in active)
            and any(r.prefill_offset < len(r.prefill_tokens) for r in active)
            and all(r.prefill_offset % tile == 0 for r in active)
        )

    def _prefill_stretch(self) -> None:
        """Prefill every active request's remaining feed via prefill_scan."""
        import jax
        import jax.numpy as jnp

        im = self.im
        tile = im.prefill_tile
        cap = im.max_tokens
        # the whole stretch's write spans, prepared before the first
        # dispatch (the scans run back-to-back with no host boundary to
        # map pages at)
        self._kv_prepare([
            (r.rid, r.prefill_offset, len(r.prefill_tokens))
            for r in self._active()
            if r.status is RequestStatus.PREFILLING
            and r.prefill_offset < len(r.prefill_tokens)])
        gate = bool(getattr(im, "gate_lm_head", False))
        sampling = self.gen.temperature > 0.0
        n_rows = im.max_requests if gate else cap
        chunks: List = []  # per-chunk numpy field tuples (BatchConfig order)
        ls_chunks: List = []  # per-chunk logit_slots (gated path)
        fold_chunks: List = []  # per-chunk (rid, token-index) sample folds
        # (chunk_idx, result_idx, rid): result_idx is the SLOT when gated
        # (result arrays are [max_requests]), the flat token index otherwise
        points: List[Tuple[int, int, int]] = []
        seq = np.zeros(im.max_requests, np.int32)
        for req in self._active():
            seq[req.slot] = req.seq_len
        for req in self._active():
            if req.status is not RequestStatus.PREFILLING:
                continue
            while req.prefill_offset < len(req.prefill_tokens):
                take = min((cap // tile) * tile,
                           len(req.prefill_tokens) - req.prefill_offset)
                start = req.prefill_offset
                seq[req.slot] = start + take
                fields, last_flat = PrefillBatchConfig.np_fields(
                    [(req.slot, req.prefill_tokens[start: start + take],
                      start)],
                    seq, tile,
                    max_tokens=cap, max_requests=im.max_requests,
                )
                req.prefill_offset += take
                done = req.prefill_offset == len(req.prefill_tokens)
                ridx = req.slot if gate else last_flat[req.slot]
                if done:
                    points.append((len(chunks), ridx, req.rid))
                # deterministic accounting: one model pass per chunk;
                # gated chunks materialize logits only at the (single)
                # completing request's slot
                self._prof_account(
                    [(req.rid, start, start + take)],
                    logit_rows=(1 if done else 0) if gate else None)
                if sampling:
                    fc = np.zeros((n_rows, 2), np.int32)
                    if done:
                        fc[ridx] = self._fold_for(req)
                    fold_chunks.append(fc)
                ls_chunks.append(PrefillBatchConfig.np_logit_slots(
                    [req.slot] if done else [], last_flat, im.max_requests))
                chunks.append(fields)
        # stack chunk fields host-side (ONE device transfer per field per
        # segment, not five tiny transfers per chunk) and scan in power-of-
        # two segments so each distinct scan length compiles at most once
        outs = []   # (start_chunk, token array [seg, cap]) — read after all
        at = 0
        while at < len(chunks):
            seg = 1 << (min(len(chunks) - at, 64).bit_length() - 1)
            stacked = PrefillBatchConfig(
                base=BatchConfig(*(
                    jnp.asarray(np.stack([c[i] for c in chunks[at: at + seg]]))
                    for i in range(5)
                )),
                tile_size=tile,
                logit_slots=jnp.asarray(np.stack(ls_chunks[at: at + seg]))
                if gate else None,
            )
            smp = None
            if sampling:
                # per-request key schedule: the chunk carrying request rid's
                # completion samples its token n with fold (rid, n) — same
                # key whatever chunking/segmentation/preemption produced it
                smp = (jax.random.PRNGKey(self.gen.seed),
                       jnp.float32(self.gen.temperature),
                       jnp.float32(self.gen.top_p),
                       jnp.asarray(np.stack(fold_chunks[at: at + seg])))
            res = self._guarded(
                "prefill_scan", lambda s=stacked, a=smp: im.prefill_scan(s, a))
            if res is None:
                # dispatch failed past the retry budget: _fail_inflight
                # already requeued/failed every prefilling request (their
                # advanced offsets were reset by the recompute path) — the
                # partial segments' KV is dead weight the next occupant of
                # each slot overwrites
                self.scan_runs += 1
                return
            outs.append((at, res))
            at += seg
        with self.profiler.phase("readback"):
            toks = {start: np.asarray(t) for start, t in outs}  # one sync
        self.profiler.host_sync(len(outs))
        starts = sorted(toks)
        for chunk_idx, flat_idx, rid in points:
            start = max(s for s in starts if s <= chunk_idx)
            req = self.requests[rid]
            req.status = RequestStatus.DECODING
            self._append_token(req,
                               int(toks[start][chunk_idx - start, flat_idx]))
            self._maybe_finish(req)
        self.steps += len(chunks)
        self.scan_runs += 1

    def _decode_stretch(self, n: int) -> None:
        """Run one decode stretch with ONE host sync.

        With :attr:`chain_segments` on (and an ``im`` exposing the async
        scan path) the stretch is a CHAIN of back-to-back
        ``decode_scan_async`` segments — dispatched with no readback
        between them — that keeps running up to ``scan_chunk`` total
        steps while any row has budget left:

        * rows of UNEQUAL remaining budgets ride one stretch (the device
          freezes each row at ITS budget via the ``allowed`` mask and
          reports a per-row exit code; the host no longer stops the whole
          scan at the smallest budget);
        * armed deadlines/cancels bound SEGMENTS (the host clock-checks
          between dispatches, same ``lifecycle_quantum`` granularity)
          instead of terminating the stretch;
        * arrivals landing mid-stretch JOIN the running batch at the next
          segment boundary — async flat prefill of the prompt, then
          ``join_slot`` splices the held first token into the batch — so
          pending work no longer degenerates serving to one dispatch per
          token.

        Everything materializes in ONE readback at stretch end (tokens,
        emission masks, exit codes), then commits in dispatch order —
        bit-identical to the per-tick loop by construction (same sample
        folds, same masks).
        """
        if not (self.chain_segments
                and hasattr(self.im, "decode_scan_async")):
            return self._decode_stretch_single(n)
        im = self.im
        prof = self.profiler
        eos = self.gen.eos_token_id if self.gen.stop_on_eos else None
        # whole first-segment write spans up front, BEFORE building the
        # batch (page-pressure preemption inside the prepare can evict a
        # victim, which must drop out of the batch)
        self._kv_prepare([(r.rid, r.seq_len - 1, r.seq_len - 1 + n)
                          for r in self._active()])
        active = [r for r in self._active()
                  if r.status is RequestStatus.DECODING]
        if not active:
            return
        rows: List[Tuple[Request, int]] = []   # (req, flat row) in order
        sched: Dict[int, int] = {}    # rid -> tokens produced this stretch
        dev_seq: Dict[int, int] = {}  # rid -> device-side cache depth
        with prof.phase("host_prepare"):
            tokens, reqi, pos = [], [], []
            for req in active:
                tokens.append(req.generated[-1])
                reqi.append(req.slot)
                pos.append(req.seq_len - 1)
                rows.append((req, len(rows)))
                sched[req.rid] = 0
                dev_seq[req.rid] = req.seq_len
            seq_lens = np.zeros(im.max_requests, np.int32)
            for req in active:
                seq_lens[req.slot] = req.seq_len
            bc = BatchConfig.build(
                tokens, reqi, pos, seq_lens,
                max_tokens=im.max_tokens, max_requests=im.max_requests)

        def remaining(req):
            return req.max_new_tokens - len(req.generated) - sched[req.rid]

        # chronological commit log: ("scan", seg, [(flat, rid)], toks,
        # live, ecode) per dispatched segment, ("join", req, token_ids,
        # src_idx) per spliced arrival — all values LAZY until the single
        # readback below
        commits: List[Tuple] = []
        total = 0
        n_segments = 0
        n_joins = 0
        seg = n
        while True:
            ks: Dict[int, int] = {}
            allowed = np.zeros(im.max_tokens, np.int32)
            pts = []
            for req, flat in rows:
                k = max(min(seg, remaining(req)), 0)
                ks[req.rid] = k
                # the emission budget is the row's FULL remaining, not
                # the segment cap: a row that outlives this segment must
                # end it alive so its exit code reads RUNNING, not BUDGET
                allowed[flat] = max(remaining(req), 0)
                pts.append((flat, req.rid, sched[req.rid]))
            if prof.enabled:
                # k_i decode steps per row: each streams the weights and
                # reads the growing causally-live prefix
                prof.account(
                    prof.card_for(im),
                    [(req.rid, ks[req.rid],
                      ks[req.rid] * dev_seq[req.rid]
                      + ks[req.rid] * (ks[req.rid] - 1) // 2)
                     for req, _ in rows if ks[req.rid] > 0],
                    passes=seg)
            # sample folds advance past the stretch's UNCOMMITTED tokens:
            # row i's next key is (rid_i, len(generated_i) + sched_i)
            smp = self._sample_for(pts, im.max_tokens)
            max_pos = max(dev_seq[req.rid] - 1 + ks[req.rid]
                          for req, _ in rows) - seg
            this_seg = seg
            out = self._guarded(
                "decode_scan",
                lambda: im.decode_scan_async(
                    bc, this_seg, eos=eos, sample=smp,
                    allowed=allowed, max_position=max_pos))
            if out is None:
                # the whole stretch's emissions were in flight and nothing
                # was committed: the requeue recompute regenerates every
                # token deterministically, earlier segments included
                self.scan_runs += 1
                return
            toks, live, ecode, bc = out
            commits.append(("scan", this_seg,
                            [(flat, req.rid) for req, flat in rows],
                            toks, live, ecode))
            for req, _ in rows:
                sched[req.rid] += ks[req.rid]
                dev_seq[req.rid] += ks[req.rid]
            total += this_seg
            n_segments += 1

            # ---- segment boundary: extend, join, or stop --------------
            reqs = [req for req, _ in rows]
            if any(r.cancel_requested for r in reqs):
                break                      # reap at the tick boundary
            if any(r.slot < 0 or self.slots[r.slot] != r.rid
                   for r in reqs):
                break   # a clock-callback preempted/terminated a row
            if self._arrival_pump is not None:
                with prof.phase("host_admit"):
                    self._arrival_pump()   # register newly-due arrivals
            rem_cap = self.scan_chunk - total
            if rem_cap < 2:
                break
            if (self.pending and not self.admission_closed
                    and len(rows) < im.max_tokens
                    and any(s is None for s in self.slots)
                    and any(not self._held(self.requests[rid])
                            for rid in self.pending)):
                bc = self._stretch_join(bc, rows, sched, dev_seq,
                                        commits, eos)
                n_joins = sum(1 for c in commits if c[0] == "join")
            armed = [r.deadline_s for r, _ in rows
                     if r.deadline_s is not None]
            if armed and self.clock() >= min(armed):
                break                      # reap at the tick boundary
            rem = [remaining(req) for req, _ in rows]
            rem_max = max(rem) if rem else 0
            if rem_max < 2:
                break   # a 1-step trailer rides the next tick's mixed
                        # step (no single-step scan compile class)
            seg = min(rem_cap, rem_max)
            if armed:
                seg = min(seg, self.lifecycle_quantum)
            seg = 1 << (seg.bit_length() - 1)
            if seg < 2:
                break
            spans = [(req.rid, dev_seq[req.rid] - 1,
                      dev_seq[req.rid] - 1 + min(seg, remaining(req)))
                     for req, _ in rows if remaining(req) > 0]
            if not self._kv_prepare_nopreempt(spans):
                break   # page pressure resolves on the per-tick path

        # ---- single readback + chronological commit -------------------
        with prof.phase("readback"):
            ready = []
            for item in commits:
                if item[0] == "scan":
                    _, sg, pts2, toks, live, ecode = item
                    ready.append(("scan", sg, pts2, np.asarray(toks),
                                  np.asarray(live), np.asarray(ecode)))
                else:
                    _, req, token_ids, src = item
                    ready.append(("join", req,
                                  int(np.asarray(token_ids)[src])))
        prof.host_sync()
        codes: Dict[int, int] = {}
        for item in ready:
            if item[0] == "join":
                _, req, tok = item
                if req.status not in (RequestStatus.PREFILLING,
                                      RequestStatus.DECODING):
                    continue   # left its slot before commit: emission is
                               # dead, the readmission recomputes it
                if req.status is RequestStatus.PREFILLING:
                    req.status = RequestStatus.DECODING
                self._append_token(req, tok)
                self._maybe_finish(req)
                continue
            _, sg, pts2, toks, live, ecode = item
            for s in range(sg):
                for flat, rid in pts2:
                    req = self.requests[rid]
                    if (req.status is not RequestStatus.DECODING
                            or not live[s, flat]):
                        continue
                    self._append_token(req, int(toks[s, flat]))
                    self._maybe_finish(req)
            for flat, rid in pts2:
                c = int(ecode[flat])
                if c != EXIT_NOT_IN_BATCH:
                    codes[rid] = c   # the segment where the row ran last
        self.last_exit_codes = codes
        self.steps += total
        self.scan_runs += 1
        if prof.enabled:
            prof.note(decode_quantum=n, stretch_steps=total,
                      stretch_segments=n_segments,
                      stretch_joins=n_joins)

    def _stretch_join(self, bc, rows, sched, dev_seq, commits, eos):
        """Admit pending arrivals INTO the running stretch (on-device
        continuous batching): fill free slots, asynchronously prefill
        each joiner's prompt (flat chunks, no readback), then splice its
        held first token into the live batch via ``join_slot`` — the
        device decodes it from the next segment on.  Page exhaustion or
        dispatch failure un-joins the request back to the queue; the
        per-tick path retries it with the full pressure machinery."""
        im = self.im
        with self.profiler.phase("host_admit"):
            pre = {rid for rid in self.slots if rid is not None}
            self._fill_slots()
            newly = [rid for rid in self.slots
                     if rid is not None and rid not in pre]
        stamped = []
        for rid in newly:
            req = self.requests[rid]
            if len(rows) >= im.max_tokens:
                # no flat-row capacity left: the leftover stays slotted
                # and prefills on the next tick's per-step path
                continue
            out = self._stretch_prefill(req, rows, dev_seq)
            if out is None:
                if (req.status is RequestStatus.PREFILLING
                        and req.slot >= 0
                        and self.slots[req.slot] == req.rid):
                    self._unjoin(req)
                continue
            res, src = out
            stamped.append(rid)
            L = len(req.prefill_tokens)
            commits.append(("join", req, res.token_ids, src))
            if req.max_new_tokens - len(req.generated) <= 1:
                # the held token is the whole remaining budget: nothing
                # to decode — it completes at the stretch readback
                continue
            dst = len(rows)
            bc = im.join_slot(bc, res.token_ids, src, dst, req.slot,
                              L, L + 1, dst + 1, eos=eos)
            rows.append((req, dst))
            sched[req.rid] = 1
            dev_seq[req.rid] = L + 1
        if stamped:
            if self._join_stamp is not None:
                self._join_stamp(stamped)
            if self.telemetry.enabled:
                self.telemetry.metrics.counter("stretch_joins").inc(
                    len(stamped))
        return bc

    def _stretch_prefill(self, req, rows, dev_seq):
        """Asynchronously feed one joining request's whole prompt (flat
        chunks, results left on device) and return ``(result, src_idx)``
        of the final chunk — the joiner's first generated token, read
        back only at the stretch's single readback.  None when the feed
        could not run (page-pool exhaustion before dispatch, or a
        dispatch failure after retries — the latter already requeued the
        request via the retry guard)."""
        im = self.im
        feed = req.prefill_tokens
        L = len(feed)
        if not self._kv_prepare_nopreempt(
                [(req.rid, req.prefill_offset, L)]):
            return None
        res = src = None
        while req.prefill_offset < L:
            start = req.prefill_offset
            take = min(im.max_tokens, L - start)
            done = start + take == L
            with self.profiler.phase("host_prepare"):
                # running rows' cache depths are their DEVICE depths (the
                # chain is ahead of the committed host view); only the
                # joiner's own entry is read by its feed
                seq_lens = np.zeros(im.max_requests, np.int32)
                for r2, _ in rows:
                    seq_lens[r2.slot] = dev_seq[r2.rid]
                seq_lens[req.slot] = start + take
                bc2 = BatchConfig.build(
                    list(feed[start: start + take]), [req.slot] * take,
                    list(range(start, start + take)), seq_lens,
                    max_tokens=im.max_tokens,
                    max_requests=im.max_requests)
            smp = (self._sample_for([(take - 1, req.rid)], im.max_tokens)
                   if done else None)
            self._prof_account([(req.rid, start, start + take)])
            out = self._guarded(
                "step", lambda b=bc2, s=smp: im.step(b, sample=s),
                affected_fn=lambda: [req.rid])
            if out is None:
                return None
            req.prefill_offset = start + take
            res, src = out, take - 1
        if res is None:
            return None   # nothing left to feed (cannot happen: the
                          # prefix cache keeps at least the last token)
        return res, src

    def _unjoin(self, req) -> None:
        """Back a failed mid-stretch join out to the queue: release the
        slot (and its pages) and requeue at the head — the per-tick
        admission path re-admits it with preemption/page-pressure
        handling the stretch must not run."""
        self._release_slot(req)
        req.prefill_offset = 0
        req.status = (RequestStatus.PREEMPTED if req.preemptions
                      else RequestStatus.PENDING)
        self.pending.insert(0, req.rid)
        self._pending_since.setdefault(req.rid, self.steps)

    def _kv_prepare_nopreempt(self, spans, kv=None) -> bool:
        """Page preparation for a mid-stretch dispatch: the batch rows
        are live in a RUNNING chain, so pool pressure must NOT preempt
        (evicting a row the device is still decoding would corrupt its
        cache).  Returns False on exhaustion — the caller stops extending
        the stretch (or skips the join) and the per-tick path resolves
        the pressure with the full victim machinery."""
        kv = kv if kv is not None else getattr(self.im, "kv", None)
        if kv is None or not getattr(kv, "paged", False) or not spans:
            return True
        from .kv_paged import PagePoolExhausted
        try:
            for rid, lo, hi in spans:
                kv.prepare_write(rid, lo, hi)
        except PagePoolExhausted:
            return False
        return True

    def _decode_stretch_single(self, n: int) -> None:
        """The unchained stretch: n decode steps as ONE decode_scan
        dispatch, one host sync (the ``chain_segments=False`` baseline
        the continuous-batching bit-identity tests compare against)."""
        # the scan writes n positions per request with no host boundary in
        # between — map (and COW-resolve) the whole span up front, BEFORE
        # building the batch: page-pressure preemption inside the prepare
        # can evict a victim (slot -> -1), which must drop out of the
        # batch instead of corrupting seq_lens via negative indexing
        self._kv_prepare([(r.rid, r.seq_len - 1, r.seq_len - 1 + n)
                          for r in self._active()])
        active = [r for r in self._active()
                  if r.status is RequestStatus.DECODING]
        if not active:
            return
        prof = self.profiler
        with prof.phase("host_prepare"):
            tokens, reqi, pos = [], [], []
            points = []
            for req in active:
                tokens.append(req.generated[-1])
                reqi.append(req.slot)
                pos.append(req.seq_len - 1)
                points.append(req.rid)
            seq_lens = np.zeros(self.im.max_requests, np.int32)
            for req in active:
                seq_lens[req.slot] = req.seq_len
            bc = BatchConfig.build(
                tokens, reqi, pos, seq_lens,
                max_tokens=self.im.max_tokens,
                max_requests=self.im.max_requests,
            )
        if prof.enabled:
            # n decode steps: each streams the weights and reads the
            # growing causally-live prefix (seq, seq+1, ... seq+n-1)
            prof.account(
                prof.card_for(self.im),
                [(r.rid, n, n * r.seq_len + n * (n - 1) // 2)
                 for r in active],
                passes=n)
        eos = self.gen.eos_token_id if self.gen.stop_on_eos else None
        # per-request sample keys: row i starts at (rid_i, len(generated_i))
        # and the scan advances the token index per step on device
        smp = self._sample_for(list(enumerate(points)), self.im.max_tokens)
        out = self._guarded(
            "decode_scan",
            lambda: self.im.decode_scan(bc, n, eos=eos, sample=smp))
        if out is None:
            self.scan_runs += 1
            return
        toks, live, _ = out
        with prof.phase("readback"):
            toks = np.asarray(toks)
            live = np.asarray(live)
        prof.host_sync()
        for s in range(n):
            for flat, rid in enumerate(points):
                req = self.requests[rid]
                if req.status is not RequestStatus.DECODING or not live[s, flat]:
                    continue
                self._append_token(req, int(toks[s, flat]))
                self._maybe_finish(req)
        self.steps += n
        self.scan_runs += 1
        if prof.enabled:
            prof.note(decode_quantum=n, stretch_steps=n,
                      stretch_segments=1, stretch_joins=0)

    def _serve_tick(self) -> None:
        """One scheduling decision + dispatch of the incremental loop:
        prefill stretch, decode stretch, or a single mixed step — every
        dispatch runs under the retry guard, so a transient fault degrades
        to requeue/reject of the affected requests instead of killing the
        loop."""
        tel = self.telemetry
        if self._prefill_stretch_possible():
            with tel.span("prefill_stretch", cat="serve"):
                self._prefill_stretch()
            return
        n = self._scan_steps_possible()
        if n > 1:
            with tel.span("decode_stretch", cat="serve", steps=n):
                self._decode_stretch(n)
            return
        with tel.span("serve_step", cat="serve"):
            # prepare_next_batch attributes its own host_admit /
            # host_prepare phases
            bc, sample_points = self.prepare_next_batch()
            base = bc if isinstance(bc, BatchConfig) else bc.base
            if int(np.asarray(base.num_tokens)) == 0:
                # nothing slotted fed a token (admission closed during a
                # migration drain with only pending work): dispatching an
                # empty batch would burn a device step for nothing
                return
            gated = (isinstance(bc, PrefillBatchConfig)
                     and bc.logit_slots is not None)
            smp = self._sample_for(
                sample_points,
                self.im.max_requests if gated else self.im.max_tokens)
            result = self._guarded(
                "step", lambda: self.im.step(bc, sample=smp),
                affected_fn=lambda: self._rids_in_batch(bc))
            if result is not None:
                self.process_result(result, sample_points)
            self.steps += 1

    def _tick(self) -> None:
        """One unit of serving work between lifecycle checks — THE
        dispatch the serve loops (``serve_incr_decoding`` and
        ``serve_with_arrivals``) drive.  The incremental manager's tick is
        :meth:`_serve_tick`; :class:`~.spec_infer.SpecInferManager`
        overrides this with its spec-aware dispatch (a mixed speculative
        macro-step while any live request is in spec mode, the incremental
        fast path otherwise), which is what makes speculation compose with
        arrivals, deadlines, cancellation, and admission control with ONE
        lifecycle implementation."""
        self._serve_tick()

    def _kv_bind(self, rid: int) -> None:
        """Attribution hook when a request takes a slot (overridden by
        managers holding more than one deployment's caches — the spec
        manager binds the draft model's allocator too).

        Under a PAGED allocator (serve/kv_paged.py) this is also the
        prefix-reuse hook: bind() maps every registered prefix page the
        request's fed tokens match and returns the cached offset — the
        prefill resumes THERE, so a shared system prompt is prefilled
        once per fleet instead of once per request (TTFT collapses to the
        unshared suffix).  The cached offset is tile-aligned by
        construction (``align=prefill_tile``), preserving the tiled
        prefill path's contract (d).
        """
        kv = getattr(self.im, "kv", None)
        if kv is None:
            return
        req = self.requests[rid]
        # the tile alignment only matters when the tiled Pallas prefill
        # path will consume the resumed offset; the flat gather path
        # accepts any start, so it keeps every matched token
        align = (getattr(self.im, "prefill_tile", 1)
                 if getattr(self.im, "use_pallas", False) else 1)
        info = kv.bind(rid, slot=req.slot, tokens=req.prefill_tokens,
                       need=self._seq_len_needed(req), align=align)
        if info is None:
            return
        cached = int(info.get("cached_tokens", 0))
        if cached:
            req.prefill_offset = cached
        # host-tier readmission: upload this rid's spilled pages onto the
        # freshly-bound row and resume the prefill at the restored write
        # frontier — recompute covers only the unrestored tail.  Prefix
        # hits already below the frontier cost nothing extra (restore
        # skips the span bind covered).
        restored = self._kv_restore(req, kv, align)
        if restored > cached:
            req.prefill_offset = restored
            req.kv_restored = True
        tel = self.telemetry
        if tel.enabled:
            if cached:
                tel.prefix_cache_hit(req.trace_id, tokens_reused=cached,
                                     pages=int(info.get("hit_pages", 0)))
            else:
                tel.prefix_cache_miss(req.trace_id)

    def _kv_spill(self, req: Request, kv) -> None:
        """Copy a victim's written pages to the host tier BEFORE its slot
        releases (every page-leaving path funnels through preempt()).
        Guarded by the retry policy at the ``kv_swap_out:<rid>`` chaos
        site; a fault schedule that exhausts the budget just skips the
        spill — the r9 recompute feed still covers recovery
        bit-identically, so a failed spill can never corrupt, only cost.
        """
        if kv is None or kv.host_tier is None or req.slot < 0:
            return
        site = f"kv_swap_out:{req.rid}"
        tokens = list(req.prompt) + list(req.generated)
        pol = self.res.retry
        tel = self.telemetry
        attempt = 0
        while True:
            try:
                if self.injector is not None:
                    self.injector.maybe_fail(site)
                info = kv.spill(req.rid, tokens)
                break
            except TransientServeError as e:
                if tel.enabled:
                    tel.fault_observed(site, detail=str(e))
                if attempt >= pol.max_retries:
                    kv.drop_spill(req.rid)
                    return
                attempt += 1
                delay = pol.backoff(attempt)
                if tel.enabled:
                    tel.dispatch_retry(site, attempt=attempt,
                                       backoff_s=delay)
                if delay > 0:
                    self._sleep(delay)
        if info and tel.enabled:
            tel.kv_spilled(req.trace_id, pages=info["pages"],
                           nbytes=info["nbytes"], tokens=info["tokens"])

    def _kv_restore(self, req: Request, kv, align: int) -> int:
        """Upload ``req``'s spilled pages back after its readmission bind;
        returns the restored write frontier (0 = nothing restored — the
        recompute feed covers everything, bit-identically).  Guarded at
        the ``kv_swap_in:<rid>`` chaos site under the retry policy;
        :class:`~.kv_paged.HostTierCorruption` (checksum mismatch) is NOT
        retried — the host copy itself is damaged, so the entry drops and
        recompute takes over."""
        if kv is None or kv.host_tier is None or not kv.has_spill(req.rid):
            return 0
        from .kv_paged import HostTierCorruption

        site = f"kv_swap_in:{req.rid}"
        pol = self.res.retry
        tel = self.telemetry
        attempt = 0
        while True:
            try:
                if self.injector is not None:
                    self.injector.maybe_fail(site)
                info = kv.restore(req.rid, align=align)
                break
            except HostTierCorruption as e:
                kv.drop_spill(req.rid)
                if tel.enabled:
                    tel.kv_restore_failed(req.trace_id, reason=str(e))
                return 0
            except TransientServeError as e:
                if tel.enabled:
                    tel.fault_observed(site, detail=str(e))
                if attempt >= pol.max_retries:
                    kv.drop_spill(req.rid)
                    if tel.enabled:
                        tel.kv_restore_failed(
                            req.trace_id,
                            reason=f"retry budget exhausted at {site}")
                    return 0
                attempt += 1
                delay = pol.backoff(attempt)
                if tel.enabled:
                    tel.dispatch_retry(site, attempt=attempt,
                                       backoff_s=delay)
                if delay > 0:
                    self._sleep(delay)
        if not info:
            return 0
        if tel.enabled:
            tel.kv_restored(req.trace_id, pages=info["pages"],
                            nbytes=info["nbytes"],
                            tokens_resumed=info["restored_tokens"],
                            tokens_saved=info["tokens_saved"])
        return int(info["restored_tokens"])

    def _kv_prepare(self, spans, kv=None) -> None:
        """Pre-dispatch page preparation for every (rid, lo, hi) cache
        write span the next dispatch will perform: the paged allocator
        maps missing pages and copy-on-writes shared ones HERE, so the
        block table is constant while the device works.  No-op for the
        slot-contiguous allocator.

        Pool exhaustion degrades like slot pressure does: with
        ``res.preemption`` on, the lowest-priority decoding victim is
        preempted — releasing its pages page-granularly — and the span
        retries; otherwise the exhaustion propagates (an admission gate
        sized with ``round_need`` prevents reaching it).
        """
        kv = kv if kv is not None else getattr(self.im, "kv", None)
        if kv is None or not getattr(kv, "paged", False) or not spans:
            return
        from .kv_paged import PagePoolExhausted

        for rid, lo, hi in spans:
            for _ in range(len(self.slots) + 1):
                try:
                    kv.prepare_write(rid, lo, hi)
                    break
                except PagePoolExhausted:
                    victim = self._page_pressure_victim(rid)
                    if victim is None:
                        raise
                    self.preempt(victim.rid)

    def _page_pressure_victim(self, needer_rid: int):
        """Lowest-priority DECODING request (newest first among equals,
        bounded by max_preemptions) whose priority is STRICTLY below the
        needer's — the same invariant the slot-pressure path enforces
        ("preemption only ever evicts strictly-lower priority"; a page
        shortfall must not priority-invert).  None when preemption is off
        or nothing admissible is evictable — the exhaustion then
        propagates."""
        if not self.res.preemption:
            return None
        need_pri = self.requests[needer_rid].priority
        victims = [r for r in self._active()
                   if r.status is RequestStatus.DECODING
                   and r.rid != needer_rid
                   and r.priority < need_pri
                   and r.preemptions < self.res.max_preemptions]
        if not victims:
            return None
        return min(victims, key=lambda r: (r.priority, -r.rid))

    def kv_snapshot(self) -> Optional[Dict]:
        """The deployment's live KV view (pure read — see
        :meth:`KVAllocator.snapshot`); overridden by managers holding
        more than one deployment's caches (the spec manager returns the
        combined target+draft view its gauges publish).  None without an
        allocator."""
        kv = getattr(self.im, "kv", None)
        return kv.snapshot() if kv is not None else None

    def _sync_kv(self) -> None:
        """One per-tick snapshot of live cache depths into the allocator
        (per-request peaks, watermarks, occupancy/headroom/fragmentation
        gauges when telemetry is live) — host bookkeeping only."""
        kv = getattr(self.im, "kv", None)
        if kv is None:
            return
        kv.observe(
            {r.rid: r.seq_len for r in self._active()
             if r.status in (RequestStatus.PREFILLING,
                             RequestStatus.DECODING)},
            self.telemetry)

    def _maybe_check_health(self, force: bool = False) -> None:
        """Poll the attached plan-health monitor every
        ``health_check_every`` ticks (``force`` = loop drained: one final
        check so short runs still get evaluated exactly once)."""
        if self.plan_health is None:
            return
        self._health_ticks += 1
        if force or self._health_ticks % self.health_check_every == 0:
            self.plan_health.check()

    def apply_output_cap(self, rid: int, cap: int) -> bool:
        """Cap a live request's ``max_new_tokens`` (DEGRADE_BATCH): the
        committed stream stays a bit-identical PREFIX of the uncapped
        run.  A request already at/past the cap completes at this tick
        boundary with its committed tokens and an ``ok`` outcome.
        Returns whether the cap shortened the request."""
        req = self.requests.get(rid)
        if req is None or req.status in TERMINAL_STATUSES:
            return False
        new_max = max(int(cap), len(req.generated))
        if new_max >= req.max_new_tokens:
            return False
        req.max_new_tokens = new_max
        if req.status is RequestStatus.DECODING \
                and len(req.generated) >= req.max_new_tokens:
            self._maybe_finish(req)
        return True

    def _maybe_brownout(self) -> None:
        """Evaluate an attached BrownoutController every
        ``config.check_every`` serve ticks and apply the level's actions
        at this tick boundary (see serve/slo.py for the ladder).  Owned
        by the FLEET when serving under a FleetRouter (per-replica
        managers keep ``brownout`` None)."""
        bo = self.brownout
        if bo is None:
            return
        self._brownout_ticks += 1
        if self._brownout_ticks % bo.config.check_every:
            return
        slo = self.slo
        tel = self.telemetry
        kv = getattr(self.im, "kv", None)
        occ = (kv.live_tokens() / kv.capacity_tokens
               if kv is not None and kv.capacity_tokens else 0.0)
        depths: Dict[str, int] = {c: 0 for c in slo.classes}
        lc_depth = 0
        for rid in self.pending:
            req = self.requests[rid]
            cls = slo.resolve(req.slo_class)
            if cls is None:
                continue
            depths[cls.name] = depths.get(cls.name, 0) + 1
            if not cls.degradable:
                lc_depth += 1
        if tel.enabled:
            tel.lane_depths(depths)
        bo.evaluate(lc_queue_depth=lc_depth, kv_occupancy_frac=occ)
        if bo.level == 0:
            return
        # --- apply the level's actions (idempotent per window) ---------
        deferred: Dict[str, int] = {}
        for rid in list(self.pending):
            req = self.requests[rid]
            if req.status in TERMINAL_STATUSES:
                continue
            if bo.sheds_queued(req.slo_class):
                if tel.enabled:
                    tel.lane_shed(req.slo_class, trace_id=req.trace_id,
                                  reason=f"brownout:{bo.level.name}")
                self._terminate(req, RequestStatus.REJECTED)
            elif self._held(req):
                req.deferred_ticks += 1
                deferred[req.slo_class] = deferred.get(req.slo_class, 0) + 1
        if tel.enabled:
            for cname, cnt in deferred.items():
                tel.lane_deferred(cname, count=cnt)
        # --- SPILL: the rung between DEFER and DEGRADE -----------------
        # before capping or shedding anything, push degradable decoding
        # requests' pages to the host tier while KV pressure holds — each
        # preempt() below spills first (tier attached), so the freed
        # pages cost a swap on readmission, not a recompute, and the
        # bit-identical-prefix contract is untouched (preemption already
        # carries it).  Only fires with a tier attached and real page
        # pressure; the level walk/hysteresis pins stay as they are
        # because SPILL is an action DEFER_BATCH and above carry, not a
        # new enum member (fleet.py hardcodes level comparisons).
        if (kv is not None and kv.host_tier is not None
                and occ >= bo.config.kv_pressure_frac):
            victims = [r for r in self._active()
                       if r.status is RequestStatus.DECODING
                       and bo.spills(r.slo_class)
                       and r.preemptions < self.res.max_preemptions]
            victims.sort(key=lambda r: (r.priority, -r.rid))
            cap_toks = max(kv.capacity_tokens, 1)
            for req in victims:
                if kv.live_tokens() / cap_toks < bo.config.kv_pressure_frac:
                    break
                self.preempt(req.rid)
        for req in list(self._active()):
            if bo.sheds_live(req.slo_class):
                # CRITICAL_ONLY: evict and shed even slotted degradable
                # work — explicit REJECTED (committed tokens stay on the
                # record), never FAILED
                self._release_slot(req)
                if tel.enabled:
                    tel.lane_shed(req.slo_class, trace_id=req.trace_id,
                                  reason="brownout:CRITICAL_ONLY")
                self._terminate(req, RequestStatus.REJECTED)
            elif bo.degrades(req.slo_class):
                changed = False
                if req.spec:
                    # the r14 runtime flip: spec off for degraded lanes
                    changed = self.set_spec_mode(req.rid, False) or changed
                cap = bo.output_cap(req.slo_class)
                if cap is not None:
                    changed = self.apply_output_cap(req.rid, cap) or changed
                if changed and tel.enabled:
                    tel.lane_degraded(req.slo_class)

    def _maybe_migrate(self, idle: bool = False):
        """Tick-boundary slot for an attached
        :class:`~flexflow_tpu.serve.migration.MigrationController`:
        returns the SUCCESSOR manager when a live plan switch completed
        at this boundary (the serve loops hand off to it mid-run), else
        None.  ``idle`` = the loop has no work — a staged migration
        executes immediately there (the zero-preemption window)."""
        if self.migration is None:
            return None
        new_rm = self.migration.tick(self, idle=idle)
        return new_rm if new_rm is not None and new_rm is not self else None

    def trace_run_meta(self) -> Dict:
        """Provenance header a traffic trace (obs/replay.py) records for
        this deployment: what a ReplayHarness needs to rebuild an
        IDENTICAL run — the full gen config (sampling seed included),
        the plan key + engine shape, the fault-injector schedule, and
        the SLO-policy snapshot.  Subclasses extend (SpecInferManager
        adds its draft-tree shape)."""
        from ..obs.replay import engine_shape_of, injector_meta

        meta: Dict = {
            "driver": type(self).__name__,
            "gen": dataclasses.asdict(self.gen),
            "plan": engine_shape_of(self.im),
            "fault": injector_meta(self.injector),
        }
        if self.slo is not None and hasattr(self.slo, "snapshot"):
            meta["slo"] = self.slo.snapshot()
        return meta

    def serve_with_arrivals(self, arrivals, clock=None, quantum: int = 8,
                            record_trace=None,
                            _t0=None, _records=None, _open=None):
        """Arrival-driven serving: requests join the running admit/retire
        loop at their offered times (open-loop load, the serving_under_load
        bench's engine).

        ``arrivals``: iterable of ``(t_offset_s, prompt_tokens,
        max_new_tokens_or_None)`` — offsets from loop start; admitted once
        the clock passes them.  An optional 4th element is an options dict
        forwarded to :meth:`register_new_request` (``priority``, ``ttl_s``,
        ``deadline_s``, ``spec`` — per-request speculation mode under a
        SpecInferManager).  ``clock``: 0-arg seconds callable (injectable for
        hermetic tests; default ``time.perf_counter``); it also drives the
        deadline/TTL checks for the loop's duration.  ``quantum``: cap on
        the on-device decode-scan stretch while arrivals are outstanding
        — LEGACY-PATH ONLY (``chain_segments=False``): the chained
        stretch admits arrivals into the RUNNING scan at segment
        boundaries (on-device continuous batching, see
        :meth:`_decode_stretch`), so pending arrivals no longer cap the
        stretch at all; cancellations and deadlines still land at
        segment-boundary granularity.

        Returns ``{rid: record}`` with ``arrival_s``, ``first_token_s``
        (host-visible TTFT stamp), ``finish_s``, ``prompt_len``,
        ``trace_id``, ``tokens``, a terminal ``outcome``
        (``ok|cancelled|timeout|rejected|failed``), and the TTFT
        decomposition ``queue_wait_s`` / ``prefill_s``: ``prefill_start_s``
        is stamped at the start of the step in which the request's FIRST
        prefill token was fed to the device, so queue wait (arrival ->
        prefill actually starting: pending queue + slot wait + tiled-budget
        starvation) is reported separately from prefill compute
        (``queue_wait_s + prefill_s == first_token_s - arrival_s`` for
        ``ok`` requests).  The decomposition and outcome are ALWAYS
        emitted, including for requests that never produce a first token
        (cancelled, rejected, timed out, ``max_new_tokens=0``) — their
        ``prefill_s`` measures up to the terminal stamp instead.  All
        stamps are host-visible at step-boundary granularity.  Per-request
        outputs are INVARIANT to arrival timing (continuous batching only
        reorders work, never results), pinned by
        tests/test_serving_under_load.py.

        ``record_trace`` (a :class:`~flexflow_tpu.obs.replay.
        TrafficTraceRecorder`) captures this run as a versioned trace
        artifact: run provenance (gen/sampling seeds, plan key, fault
        schedule) on entry, every offered arrival at admit time, and
        every finished record at the tail — capture is append-only host
        bookkeeping that never reads this loop's clock, so a recorded
        run is bit-identical to an unrecorded one.

        ``_t0``/``_records``/``_open`` are the live-migration continuation
        (serve/migration.py): when a plan switch completes mid-loop, the
        SUCCESSOR manager re-enters this method with the remaining
        arrivals and the accumulated records/open set on the ORIGINAL
        time base, so one arrival session spans managers seamlessly.
        """
        import time as _time

        clock = clock or _time.perf_counter
        t0 = clock() if _t0 is None else _t0
        if record_trace is not None:
            # idempotent: a migration successor re-entering this loop
            # appends its plan provenance as a continuation, not a new
            # header
            record_trace.begin_run(self.trace_run_meta())
        pending = sorted(arrivals, key=lambda a: a[0])
        records: Dict[int, Dict] = {} if _records is None else _records
        saved_chunk = self.scan_chunk
        saved_clock = self._swap_clock(clock)  # rebases armed deadlines
        tel = self.telemetry

        # rids whose record still awaits a stamp — scanned per tick instead
        # of the full (mostly-terminal) records history, so per-step host
        # work stays O(live) over long sessions (same contract as
        # _check_lifecycle)
        open_rids: set = set() if _open is None else _open

        def admit_due():
            now = clock() - t0
            while pending and pending[0][0] <= now:
                off, prompt, mnt, *rest = pending.pop(0)
                if record_trace is not None:
                    # the RAW options element (not the parsed form), so
                    # a malformed dict replays its rejection identically
                    record_trace.record_arrival(
                        off, prompt, mnt, rest[0] if rest else None)
                # malformed arrivals — bad prompt shapes AND bad options
                # dicts — register as REJECTED records instead of raising
                # out of (and killing) the serve loop
                opts, reject = parse_arrival_options(rest)
                rid = self.register_new_request(
                    prompt, mnt, reject_invalid=True,
                    reject_reason=reject, **opts)
                records[rid] = {"arrival_s": off, "admitted_s": now,
                                "prompt_len": len(prompt),
                                "trace_id": self.requests[rid].trace_id}
                open_rids.add(rid)
            return clock() - t0

        def prefill_starters():
            # requests whose first prefill token may enter the device in
            # the NEXT step: stamped with the step's start time if it does
            # (admission itself can also happen inside the step)
            return [rid for rid in open_rids
                    if "prefill_start_s" not in records[rid]
                    and self.requests[rid].prefill_offset == 0
                    and self.requests[rid].status not in TERMINAL_STATUSES]

        def stamp(now):
            for rid in list(open_rids):
                rec = records[rid]
                req = self.requests[rid]
                if "first_token_s" not in rec and req.generated:
                    rec["first_token_s"] = now
                if ("finish_s" not in rec
                        and req.status in TERMINAL_STATUSES):
                    rec["finish_s"] = now
                if "finish_s" in rec:
                    open_rids.discard(rid)

        def continue_on(new_rm):
            # live migration completed at this boundary: the successor
            # carries every request (rids preserved) — it re-enters this
            # loop with the remaining arrivals on the original time base
            return new_rm.serve_with_arrivals(
                pending, clock=clock, quantum=quantum,
                record_trace=record_trace,
                _t0=t0, _records=records, _open=open_rids)

        def stamp_joined(rids):
            # mid-stretch joiners started (and usually finished) prefill
            # INSIDE the tick: stamp prefill_start_s at join time, same
            # step-boundary clock the per-tick starters path uses
            now2 = clock() - t0
            for rid in rids:
                rec = records.get(rid)
                if rec is not None and "prefill_start_s" not in rec:
                    rec["prefill_start_s"] = now2
                    if tel.enabled:
                        tel.request_prefill_started(
                            self.requests[rid].trace_id)

        chained = (self.chain_segments
                   and hasattr(self.im, "decode_scan_async"))
        try:
            # the chained stretch pulls newly-due arrivals in at segment
            # boundaries itself (and stamps joiners' records)
            self._arrival_pump = admit_due if chained else None
            self._join_stamp = stamp_joined if chained else None
            while pending or self.has_work():
                now = admit_due()
                self._check_lifecycle()
                stamp(clock() - t0)
                if not self.has_work():
                    new_rm = self._maybe_migrate(idle=True)
                    if new_rm is not None:
                        return continue_on(new_rm)
                    # idle until the next arrival: a short bounded sleep for
                    # ANY clock — real clocks stop busy-spinning, virtual
                    # clocks (which advance per call) lose at most ~1ms of
                    # wall time per idle poll
                    if pending:
                        _time.sleep(min(1e-3, max(0.0,
                                                  pending[0][0] - now)))
                    continue
                if not chained:
                    # legacy TTFT protection: cap the stretch while
                    # arrivals are outstanding (the chained path joins
                    # them mid-stretch instead)
                    self.scan_chunk = quantum if pending else saved_chunk
                starters = prefill_starters()
                self.profiler.tick_begin()
                self._tick()
                self.profiler.tick_end()
                self._sync_kv()
                self._maybe_check_health()
                self._maybe_brownout()
                for rid in starters:
                    # a mid-stretch join already stamped (and telemetered)
                    # its own prefill start — don't re-stamp it here
                    if (self.requests[rid].prefill_offset > 0
                            and "prefill_start_s" not in records[rid]):
                        records[rid]["prefill_start_s"] = now
                        if tel.enabled:
                            tel.request_prefill_started(
                                self.requests[rid].trace_id)
                stamp(clock() - t0)
                new_rm = self._maybe_migrate()
                if new_rm is not None:
                    return continue_on(new_rm)
            self._maybe_check_health(force=True)
        finally:
            self.scan_chunk = saved_chunk
            self._arrival_pump = None
            self._join_stamp = None
            self._swap_clock(saved_clock)
        end = clock() - t0
        for rid, rec in records.items():
            req = self.requests[rid]
            rec["tokens"] = req.generated
            rec["outcome"] = req.outcome or OUTCOMES.get(req.status, "ok")
            # SLO-class lanes (serve/slo.py): the lane the request rode
            # and how many brownout windows it spent queue-held — the
            # per-class report breakdown keys on these
            if req.slo_class:
                rec["slo_class"] = req.slo_class
            if req.deferred_ticks:
                rec["deferred_ticks"] = req.deferred_ticks
            # byte-side attribution: peak committed-KV this request held
            # (0.0 for rejected/never-slotted requests)
            rec["kv_bytes"] = req.kv_bytes
            # deterministic per-request work counters (obs/profiler.py):
            # flops / kv_bytes_touched / dispatches — device-free fields
            # the under-load summary totals and bench_compare guards
            if self.profiler.enabled:
                rec["work"] = self.profiler.request_work(rid)
            # ALWAYS emit the TTFT decomposition: queue wait runs from
            # arrival to prefill start (falling back to registration, then
            # arrival, when prefill never began); prefill runs from there
            # to the first token (falling back to the terminal stamp)
            start = rec.get("prefill_start_s",
                            rec.get("admitted_s", rec["arrival_s"]))
            stop = rec.get("first_token_s", rec.get("finish_s", end))
            rec["queue_wait_s"] = max(start - rec["arrival_s"], 0.0)
            rec["prefill_s"] = max(stop - start, 0.0)
        if record_trace is not None:
            # only the FINAL manager of a migration chain reaches this
            # tail (intermediate callers return via continue_on above),
            # so the artifact finalizes exactly once, with every record
            record_trace.finalize(records)
        return records

    def serve_incr_decoding(self) -> Dict[int, List[int]]:
        """Run the incremental-decoding loop until all requests reach a
        terminal state.

        Reference: ``RequestManager::serve_incr_decoding`` — but the pure-
        decode stretches run as ONE on-device ``lax.scan`` (EOS-masked), so
        the ~100ms tunnel sync amortizes over up to ``scan_chunk`` tokens;
        the per-step host path only handles admission/prefill boundaries.
        Cancellations and deadline expiries are reaped at every step
        boundary; transient dispatch faults retry-with-backoff and degrade
        to requeue/fail of only the affected requests.  An attached
        MigrationController (serve/migration.py) can swap the executing
        plan at any tick boundary — the loop hands off to the successor
        manager, which carries every request under its original rid.
        """
        while True:
            self._check_lifecycle()
            if not self.has_work():
                new_rm = self._maybe_migrate(idle=True)
                if new_rm is not None:
                    return new_rm.serve_incr_decoding()
                break
            self.profiler.tick_begin()
            self._tick()
            self.profiler.tick_end()
            self._sync_kv()
            self._maybe_check_health()
            self._maybe_brownout()
            new_rm = self._maybe_migrate()
            if new_rm is not None:
                return new_rm.serve_incr_decoding()
        self._maybe_check_health(force=True)
        return {rid: r.generated for rid, r in self.requests.items()}

    _serve = serve_incr_decoding  # overridden by SpecInferManager

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ) -> List[List[int]]:
        rids = [
            self.register_new_request(p, max_new_tokens) for p in prompts
        ]
        from ..utils.profiling import maybe_profile
        from ..utils.runlog import log_run

        profiling = bool(getattr(self.im.model.config, "profiling", False))
        import time as _time

        # snapshot the lifetime counters so the record is per-call deltas
        tok0, step0, scan0 = self.tokens_decoded, self.steps, self.scan_runs
        t0 = _time.perf_counter()
        with maybe_profile(profiling):
            out = self._serve()
        log_run("serve", {
            "manager": type(self).__name__,
            "requests": len(rids),
            "tokens": self.tokens_decoded - tok0,
            "steps": self.steps - step0,
            "scan_runs": self.scan_runs - scan0,
            "seconds": round(_time.perf_counter() - t0, 3),
        })
        return [out[rid] for rid in rids]
