"""Falcon serve graph builder.

Reference: ``inference/models/falcon.cc``.  Two supported decoder shapes:

* Falcon-7B (``parallel_attn=True``, no biases): single pre-LN feeding
  attention AND MLP in parallel, residual = x + attn + mlp.
* Falcon-RW (``parallel_attn=False``, ``bias=True``): sequential pre-LN
  blocks with ``post_attention_layernorm`` and biased linears.

The ``new_decoder_architecture`` (40B/180B: dual ln_attn/ln_mlp + per-group
interleaved fused QKV) is rejected explicitly until its weight layout is
implemented.
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import ServeModelConfig, register_model


@register_model("falcon")
def build_falcon(ff, cfg: ServeModelConfig, max_tokens: int):
    if cfg.new_decoder_architecture:
        raise NotImplementedError(
            "falcon new_decoder_architecture (40B/180B) is not supported yet: "
            "it needs ln_attn/ln_mlp and the per-kv-group interleaved QKV layout"
        )
    tokens = ff.create_tensor((max_tokens,), dtype=jnp.int32)
    x = ff.embedding(
        tokens, cfg.vocab_size, cfg.hidden_size,
        name="transformer.word_embeddings", dtype=jnp.dtype(cfg.dtype))
    for i in range(cfg.num_hidden_layers):
        p = f"transformer.h.{i}"
        h = ff.layer_norm(x, eps=cfg.layer_norm_eps,
                          name=f"{p}.input_layernorm")
        a = ff.inc_multihead_self_attention(
            h, cfg.hidden_size, cfg.num_attention_heads, cfg.kv_heads,
            cfg.hdim, rotary_embedding=not cfg.use_alibi,
            rope_theta=cfg.rope_theta, use_bias=cfg.bias,
            use_alibi=cfg.use_alibi, name=f"{p}.self_attention",
        )
        if cfg.parallel_attn:
            # Falcon-7B: residual = x + attn + mlp, both from the same LN
            m = ff.dense(h, cfg.intermediate_size, activation="gelu_exact",
                         use_bias=cfg.bias, name=f"{p}.mlp.dense_h_to_4h")
            m = ff.dense(m, cfg.hidden_size, use_bias=cfg.bias,
                         name=f"{p}.mlp.dense_4h_to_h")
            x = ff.add(x, ff.add(a, m, name=f"{p}.attn_mlp"),
                       name=f"{p}.residual")
        else:
            # Falcon-RW: sequential blocks with a post-attention LN
            x = ff.add(x, a, name=f"{p}.attn_residual")
            h2 = ff.layer_norm(x, eps=cfg.layer_norm_eps,
                               name=f"{p}.post_attention_layernorm")
            m = ff.dense(h2, cfg.intermediate_size, activation="gelu_exact",
                         use_bias=cfg.bias, name=f"{p}.mlp.dense_h_to_4h")
            m = ff.dense(m, cfg.hidden_size, use_bias=cfg.bias,
                         name=f"{p}.mlp.dense_4h_to_h")
            x = ff.add(x, m, name=f"{p}.mlp_residual")
    x = ff.layer_norm(x, eps=cfg.layer_norm_eps, name="transformer.ln_f")
    return ff.dense(x, cfg.vocab_size, use_bias=False, name="lm_head")
