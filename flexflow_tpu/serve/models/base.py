"""Serve model zoo scaffolding: config + graph-builder registry.

Reference: ``inference/models/*.cc/.h`` — each architecture is a function that
builds the serve PCG on an ``FFModel`` from an HF-style config.  Here a
:class:`ServeModelConfig` mirrors the HF ``config.json`` fields we need, and
each family registers a builder keyed by HF ``model_type``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

MODEL_REGISTRY: Dict[str, Callable] = {}


def register_model(model_type: str):
    def deco(fn):
        MODEL_REGISTRY[model_type] = fn
        return fn

    return deco


@dataclasses.dataclass
class ServeModelConfig:
    """Architecture hyperparameters (HF config.json field names)."""

    model_type: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None
    head_dim: Optional[int] = None
    rms_norm_eps: float = 1e-6
    layer_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    max_position_embeddings: int = 2048
    bos_token_id: int = 1
    eos_token_id: int = 2
    tie_word_embeddings: bool = False
    # opt/mpt/starcoder-family extras
    do_layer_norm_before: bool = True
    word_embed_proj_dim: Optional[int] = None  # opt-350m embed != hidden
    parallel_attn: bool = False       # falcon: attn & mlp in parallel
    bias: bool = False                # falcon-rw: linear biases
    use_alibi: bool = False           # mpt
    new_decoder_architecture: bool = False  # falcon >= 40b
    # compute/cache dtype for the whole graph: the token embedding is built
    # in this dtype and every downstream op inherits it (x.dtype plumbing),
    # including the attention ops' KV caches.  "bfloat16" is the TPU-native
    # serving dtype (HF config.json's torch_dtype maps here).
    dtype: str = "float32"

    @property
    def kv_heads(self) -> int:
        return self.num_key_value_heads or self.num_attention_heads

    @property
    def hdim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @staticmethod
    def from_hf_config(hf) -> "ServeModelConfig":
        """Build from a transformers PretrainedConfig (or plain dict)."""
        get = (lambda k, d=None: getattr(hf, k, d)) if not isinstance(hf, dict) \
            else (lambda k, d=None: hf.get(k, d))
        fields = {f.name for f in dataclasses.fields(ServeModelConfig)}
        kw = {}
        for name in fields:
            v = get(name, None)
            if v is not None:
                kw[name] = v
        # family-specific renames
        if get("layer_norm_epsilon") is not None:  # falcon/gpt_bigcode
            kw["layer_norm_eps"] = get("layer_norm_epsilon")
        if get("n_embd") is not None:      # starcoder/gpt_bigcode, mpt (d_model)
            kw["hidden_size"] = get("n_embd")
        if get("d_model") is not None:
            kw["hidden_size"] = get("d_model")
        if get("n_head") is not None:
            kw["num_attention_heads"] = get("n_head")
        if get("n_heads") is not None:
            kw["num_attention_heads"] = get("n_heads")
        if get("n_layer") is not None:
            kw["num_hidden_layers"] = get("n_layer")
        if get("n_layers") is not None:
            kw["num_hidden_layers"] = get("n_layers")
        if get("ffn_dim") is not None:     # opt
            kw["intermediate_size"] = get("ffn_dim")
        if get("n_inner") is not None and get("n_inner"):
            kw["intermediate_size"] = get("n_inner")
        if get("expansion_ratio") is not None:  # mpt
            kw["intermediate_size"] = get("expansion_ratio") * kw["hidden_size"]
        if get("n_positions") is not None:  # gpt_bigcode
            kw["max_position_embeddings"] = get("n_positions")
        if get("num_kv_heads") is not None and get(
            "new_decoder_architecture", False
        ):  # falcon new-decoder GQA only; old arch ignores num_kv_heads
            kw["num_key_value_heads"] = get("num_kv_heads")
        if get("multi_query", False):      # falcon-7b / starcoder MQA
            kw["num_key_value_heads"] = 1
        if get("alibi", None) is not None:
            kw["use_alibi"] = get("alibi")
        attn_cfg = get("attn_config", None)  # mpt nests attention settings
        if attn_cfg is not None:
            aget = (lambda k, d=None: attn_cfg.get(k, d)) \
                if isinstance(attn_cfg, dict) \
                else (lambda k, d=None: getattr(attn_cfg, k, d))
            if aget("kv_n_heads") is not None:
                kw["num_key_value_heads"] = aget("kv_n_heads")
            if aget("alibi") is not None:
                kw["use_alibi"] = aget("alibi")
        if get("model_type") == "gpt_bigcode" and "intermediate_size" not in kw:
            kw["intermediate_size"] = 4 * kw["hidden_size"]
        td = get("torch_dtype", None)
        if td is not None:
            td = str(td).replace("torch.", "")
            # fp16 has no TPU hardware path; bf16 is the TPU half-precision
            kw["dtype"] = "bfloat16" if td in ("float16", "bfloat16") else td
        return ServeModelConfig(**kw)


def build_model(ff, config: ServeModelConfig, max_tokens: int):
    """Dispatch to the registered family builder; returns the logits Tensor."""
    if config.model_type not in MODEL_REGISTRY:
        raise ValueError(
            f"unknown model_type {config.model_type!r}; "
            f"known: {sorted(MODEL_REGISTRY)}"
        )
    return MODEL_REGISTRY[config.model_type](ff, config, max_tokens)
