"""LLaMA / Llama-2 serve graph builder.

Reference: ``inference/models/llama.cc`` (``LLAMA::create_llama_model``) — the
same stack expressed through the FFModel builder API: token embedding, per
layer [fused residual RMSNorm → KV-cached GQA attention → fused residual
RMSNorm → SwiGLU MLP], final norm, LM head.  Node names follow the HF
state-dict layout so weight import is a direct name map.
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import ServeModelConfig, register_model


@register_model("llama")
def build_llama(ff, cfg: ServeModelConfig, max_tokens: int):
    tokens = ff.create_tensor((max_tokens,), dtype=jnp.int32)
    x = ff.embedding(
        tokens, cfg.vocab_size, cfg.hidden_size, name="model.embed_tokens", dtype=jnp.dtype(cfg.dtype))
    residual, mlp_out = x, None
    for i in range(cfg.num_hidden_layers):
        if i == 0:
            attn_in = ff.rms_norm(
                residual, eps=cfg.rms_norm_eps,
                name=f"model.layers.{i}.input_layernorm",
            )
        else:
            residual, attn_in = ff.residual_rms_norm(
                mlp_out, residual, eps=cfg.rms_norm_eps,
                name=f"model.layers.{i}.input_layernorm",
            )
        attn = ff.inc_multihead_self_attention(
            attn_in,
            cfg.hidden_size,
            cfg.num_attention_heads,
            cfg.kv_heads,
            cfg.hdim,
            rotary_embedding=True,
            rope_theta=cfg.rope_theta,
            use_bias=False,
            name=f"model.layers.{i}.self_attn",
        )
        residual, mlp_in = ff.residual_rms_norm(
            attn, residual, eps=cfg.rms_norm_eps,
            name=f"model.layers.{i}.post_attention_layernorm",
        )
        gate = ff.dense(
            mlp_in, cfg.intermediate_size, use_bias=False,
            name=f"model.layers.{i}.mlp.gate_proj",
        )
        up = ff.dense(
            mlp_in, cfg.intermediate_size, use_bias=False,
            name=f"model.layers.{i}.mlp.up_proj",
        )
        act = ff.sigmoid_silu_multi(gate, up, name=f"model.layers.{i}.mlp.act")
        mlp_out = ff.dense(
            act, cfg.hidden_size, use_bias=False,
            name=f"model.layers.{i}.mlp.down_proj",
        )
    _, normed = ff.residual_rms_norm(
        mlp_out, residual, eps=cfg.rms_norm_eps, name="model.norm"
    )
    logits = ff.dense(normed, cfg.vocab_size, use_bias=False, name="lm_head")
    return logits
