"""StarCoder (gpt_bigcode) serve graph builder.

Reference: ``inference/models/starcoder.cc`` — learned absolute position
embeddings, pre-LN decoder with multi-query attention (biased, no RoPE),
tanh-GELU MLP, tied LM head.
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import ServeModelConfig, register_model


@register_model("gpt_bigcode")
def build_starcoder(ff, cfg: ServeModelConfig, max_tokens: int):
    tokens = ff.create_tensor((max_tokens,), dtype=jnp.int32)
    x = ff.embedding(
        tokens, cfg.vocab_size, cfg.hidden_size, name="transformer.wte", dtype=jnp.dtype(cfg.dtype))
    x = ff.position_embedding(
        x, cfg.max_position_embeddings, offset=0, name="transformer.wpe"
    )
    for i in range(cfg.num_hidden_layers):
        p = f"transformer.h.{i}"
        h = ff.layer_norm(x, eps=cfg.layer_norm_eps, name=f"{p}.ln_1")
        a = ff.inc_multihead_self_attention(
            h, cfg.hidden_size, cfg.num_attention_heads, cfg.kv_heads,
            cfg.hdim, rotary_embedding=False, use_bias=True,
            name=f"{p}.attn",
        )
        x = ff.add(x, a, name=f"{p}.attn_residual")
        h = ff.layer_norm(x, eps=cfg.layer_norm_eps, name=f"{p}.ln_2")
        h = ff.dense(h, cfg.intermediate_size, activation="gelu",
                     use_bias=True, name=f"{p}.mlp.c_fc")
        h = ff.dense(h, cfg.hidden_size, use_bias=True, name=f"{p}.mlp.c_proj")
        x = ff.add(x, h, name=f"{p}.mlp_residual")
    x = ff.layer_norm(x, eps=cfg.layer_norm_eps, name="transformer.ln_f")
    return ff.dense(x, cfg.vocab_size, use_bias=False, name="lm_head")
