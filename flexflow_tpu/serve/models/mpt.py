"""MPT serve graph builder.

Reference: ``inference/models/mpt.cc`` — pre-LN (no-bias LayerNorm) decoder
with ALiBi attention (no position embedding, no RoPE), exact-GELU MLP, no
linear biases, tied LM head.
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import ServeModelConfig, register_model


@register_model("mpt")
def build_mpt(ff, cfg: ServeModelConfig, max_tokens: int):
    tokens = ff.create_tensor((max_tokens,), dtype=jnp.int32)
    x = ff.embedding(
        tokens, cfg.vocab_size, cfg.hidden_size, name="transformer.wte", dtype=jnp.dtype(cfg.dtype))
    for i in range(cfg.num_hidden_layers):
        p = f"transformer.blocks.{i}"
        h = ff.layer_norm(x, eps=cfg.layer_norm_eps, use_bias=False,
                          name=f"{p}.norm_1")
        a = ff.inc_multihead_self_attention(
            h, cfg.hidden_size, cfg.num_attention_heads, cfg.kv_heads,
            cfg.hdim, rotary_embedding=False, use_bias=False, use_alibi=True,
            name=f"{p}.attn",
        )
        x = ff.add(x, a, name=f"{p}.attn_residual")
        h = ff.layer_norm(x, eps=cfg.layer_norm_eps, use_bias=False,
                          name=f"{p}.norm_2")
        h = ff.dense(h, cfg.intermediate_size, activation="gelu_exact",
                     use_bias=False, name=f"{p}.ffn.up_proj")
        h = ff.dense(h, cfg.hidden_size, use_bias=False,
                     name=f"{p}.ffn.down_proj")
        x = ff.add(x, h, name=f"{p}.mlp_residual")
    x = ff.layer_norm(x, eps=cfg.layer_norm_eps, use_bias=False,
                      name="transformer.norm_f")
    return ff.dense(x, cfg.vocab_size, use_bias=False, name="lm_head")
