from . import falcon, llama, mpt, opt, starcoder  # noqa: F401
from .base import MODEL_REGISTRY, ServeModelConfig, build_model

__all__ = ["MODEL_REGISTRY", "ServeModelConfig", "build_model"]
