"""OPT serve graph builder.

Reference: ``inference/models/opt.cc`` (``OPT::create_opt_model``) — token +
learned position embeddings (offset 2), biased attention/MLP (ReLU), tied LM
head.  Handles both norm placements: pre-LN (``do_layer_norm_before=True``,
every size except 350m, with a model-level final layer norm) and post-LN
(opt-350m: LN applied after each residual add, no final norm), plus
opt-350m's ``word_embed_proj_dim != hidden_size`` with its project_in/out
linears.  Node names follow the HF ``facebook/opt-*`` state-dict layout.
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import ServeModelConfig, register_model


@register_model("opt")
def build_opt(ff, cfg: ServeModelConfig, max_tokens: int):
    embed_dim = cfg.word_embed_proj_dim or cfg.hidden_size
    tokens = ff.create_tensor((max_tokens,), dtype=jnp.int32)
    x = ff.embedding(
        tokens, cfg.vocab_size, embed_dim, name="model.decoder.embed_tokens", dtype=jnp.dtype(cfg.dtype))
    if embed_dim != cfg.hidden_size:
        x = ff.dense(x, cfg.hidden_size, use_bias=False,
                     name="model.decoder.project_in")
    x = ff.position_embedding(
        x, cfg.max_position_embeddings, offset=2,
        name="model.decoder.embed_positions",
    )
    for i in range(cfg.num_hidden_layers):
        p = f"model.decoder.layers.{i}"
        pre = cfg.do_layer_norm_before
        h = ff.layer_norm(x, eps=cfg.layer_norm_eps,
                          name=f"{p}.self_attn_layer_norm") if pre else x
        a = ff.inc_multihead_self_attention(
            h, cfg.hidden_size, cfg.num_attention_heads, cfg.kv_heads,
            cfg.hdim, rotary_embedding=False, use_bias=True,
            name=f"{p}.self_attn",
        )
        x = ff.add(x, a, name=f"{p}.attn_residual")
        if not pre:
            x = ff.layer_norm(x, eps=cfg.layer_norm_eps,
                              name=f"{p}.self_attn_layer_norm")
        h = ff.layer_norm(x, eps=cfg.layer_norm_eps,
                          name=f"{p}.final_layer_norm") if pre else x
        h = ff.dense(h, cfg.intermediate_size, activation="relu",
                     use_bias=True, name=f"{p}.fc1")
        h = ff.dense(h, cfg.hidden_size, use_bias=True, name=f"{p}.fc2")
        x = ff.add(x, h, name=f"{p}.mlp_residual")
        if not pre:
            x = ff.layer_norm(x, eps=cfg.layer_norm_eps,
                              name=f"{p}.final_layer_norm")
    if cfg.do_layer_norm_before:
        x = ff.layer_norm(x, eps=cfg.layer_norm_eps,
                          name="model.decoder.final_layer_norm")
    if embed_dim != cfg.hidden_size:
        x = ff.dense(x, embed_dim, use_bias=False,
                     name="model.decoder.project_out")
    return ff.dense(x, cfg.vocab_size, use_bias=False, name="lm_head")
