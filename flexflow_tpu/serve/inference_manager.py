"""InferenceManager: compile a serve PCG and run per-step inference.

Reference: ``src/runtime/inference_manager.cc`` —
``compile_model_and_allocate_buffer`` (placement + activation/KV buffers) and
``inference()`` (per-layer dispatch).  Here compilation is: plan the PCG with
a tensor-parallel strategy, allocate the per-attention-op KV caches as sharded
device arrays, and jit ONE step function per batch-config type (incremental /
tree-search / tree-verify — jax caches the compilation per pytree structure,
the analogue of the reference's three task variants).  Caches are donated so
the update is in-place in HBM.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.interpreter import build_forward
from ..core.pcg import PCG
from ..obs.profiler import NULL_PROFILER
from ..obs.telemetry import NULL_TELEMETRY
from .batch_config import BatchConfig, InferenceResult
from .kv_allocator import (  # noqa: F401 — re-exported for compat
    KVAllocator,
    StageKV,
    allocate_attention_state,
)
from .ops import IncMultiHeadSelfAttention

# Per-slot exit codes a decode scan carries in its state and returns with
# the stretch's single readback (devices decide WHY a row stopped; the
# host only reads the verdict).  Shared by InferenceManager.decode_scan*,
# the pipeline-parallel manager, and SpecDecodeScan.
EXIT_NOT_IN_BATCH = -1  # padding / row frozen before this scan began
EXIT_RUNNING = 0        # budget left and no EOS: resume next segment
EXIT_EOS = 1            # emitted the stop token mid-scan (frozen since)
EXIT_BUDGET = 2         # consumed its max_new_tokens budget in this scan


def tensor_parallel_strategy(
    graph, tp_axes: Tuple[str, ...] = ("tp",), mesh=None
):
    """Megatron-style serve strategy: attention sharded over kv-head groups,
    MLP column→row parallel, LM head vocab-column sharded.

    The analogue of the reference's default TP MachineView assignment for
    serve graphs (``InferenceManager::compile_model_and_allocate_buffer``'s
    tensor-parallel placement).  Unity search can replace this wholesale.
    Dims not divisible by the TP degree are left unsharded (replicated).
    """
    degree = 1
    if mesh is not None:
        for a in tp_axes:
            degree *= dict(mesh.shape)[a]

    strategy: Dict[str, Dict] = {}
    for node in graph.nodes:
        t = node.op.type_name
        op = node.op
        if t in (
            "inc_multihead_self_attention",
            "spec_inc_multihead_self_attention",
            "tree_inc_multihead_self_attention",
        ):
            if op.num_kv_heads % degree == 0:
                strategy[node.name] = {"head": tp_axes}
        elif t == "linear":
            n = node.name
            if "gate_proj" in n or "up_proj" in n or "fc1" in n or "c_fc" in n:
                if op.out_dim % degree == 0:
                    strategy[n] = {"channel_out": tp_axes}
            elif "down_proj" in n or "fc2" in n or "c_proj" in n:
                if op.in_dim and op.in_dim % degree == 0:
                    strategy[n] = {"channel_in": tp_axes}
            elif op.out_dim % degree == 0:
                strategy[n] = {"channel_out": tp_axes}
    return strategy


def _default_calibration(mesh):
    """(machine_model, cost_cache_or_None) from the repo's calibration
    artifacts.

    The training-side bench wires measured constants into its searches
    (bench_search.py); the serve path must not run on bare spec-sheet
    defaults with no memory cap when the same artifacts are sitting on disk
    (VERDICT r4 #5).  Missing artifacts degrade gracefully to spec
    defaults; the measured v5e op-cost cache only applies on a TPU backend
    (its absolute times would mis-scale the cpu test spec).
    """
    import os

    import jax

    from ..search.machine_model import MachineModel
    from ..search.measure import CostCache

    art = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "..", "artifacts",
    )
    on_tpu = jax.default_backend() == "tpu"
    mm = MachineModel.for_mesh(mesh, spec_name="v5e" if on_tpu else "cpu")
    if on_tpu:  # measured v5e constants only apply to the v5e spec
        mm = mm.with_calibration(os.path.join(art, "tpu_calib_v5e.json"))
    costs = None
    cpath = os.path.join(art, "tpu_costs_v5e.json")
    if on_tpu and os.path.exists(cpath):
        try:
            costs = CostCache(cpath)
        except Exception:
            costs = None
    return mm, costs


def searched_serve_strategy(model, budget: int = 300, seed: int = 0,
                            measured=None, memory_limit=None, machine=None):
    """Unity search over a SERVE graph (VERDICT r3 #5).

    The reference searches placements for inference graphs too
    (``InferenceManager::compile_model_and_allocate_buffer`` consults the
    same Unity optimizer as training); here ``graph_optimize`` runs with
    ``training=False`` — no backward factor, no grad all-reduce, inference
    activation accounting — and the memory model counts the KV/spec buffers
    the attention ops registered (``cost_max_requests``/``cost_seq_len``/
    ``cost_max_spec``), sharded by each candidate's own head-axis config.
    Call AFTER the serve capacities are known (InferenceManager does this
    in ``__init__`` via ``strategy="search"``).

    CALIBRATED BY DEFAULT (VERDICT r4 #5): when ``machine``/``measured``/
    ``memory_limit`` are not given, the repo's measured calibration
    artifacts are loaded and the per-chip HBM capacity becomes the memory
    cap, mirroring what bench_search.py wires in on the training side.
    """
    from ..search.search import graph_optimize

    if machine is None:
        machine, costs = _default_calibration(model.mesh)
        if measured is None:
            measured = costs
    if memory_limit is None:
        memory_limit = machine.spec.hbm_capacity
    return graph_optimize(
        model.graph, model.mesh, budget=budget, seed=seed,
        training=False, measured=measured, memory_limit=memory_limit,
        machine=machine,
    )


def register_serve_capacities(graph, max_requests, max_seq_len,
                              max_spec_tokens=0, kv_dtype=None):
    """Record the serving capacities + KV dtype on a serve graph's attention
    ops so planning (``plan_memory_bytes``), the serve search, and the cache
    allocator all see the deployment's real buffer shapes.  Shared by the
    single-plan :class:`InferenceManager` and the stage-split
    :class:`~flexflow_tpu.serve.pp.PipelinedInferenceManager`."""
    for node in graph.nodes:
        if isinstance(node.op, IncMultiHeadSelfAttention):
            node.op.cost_seq_len = max_seq_len
            node.op.cost_max_requests = max_requests
            node.op.cost_max_spec = max_spec_tokens
            node.op.kv_dtype = kv_dtype


def mark_gated_lm_head(graph, out_tids, max_requests) -> bool:
    """Mark the logits-producing Linear for LM-head gating (single-output
    graphs only).  Returns whether a Linear was actually marked — the guard
    the ``gate_lm_head`` property ANDs in (see InferenceManager.__init__)."""
    if len(out_tids) != 1:
        return False
    from ..ops.linear import Linear

    marked = False
    for node in graph.nodes:
        if out_tids[0] in node.outputs and isinstance(node.op, Linear):
            node.op.lm_head_gated = True
            node.op.cost_logit_rows = max_requests
            marked = True
    return marked


def pick_prefill_tile(max_tokens_per_batch: int, max_seq_len: int) -> int:
    """Query-tile width for the Pallas prefill kernel: the largest
    power-of-two divisor of ``max_tokens_per_batch`` capped at 128 that also
    divides ``max_seq_len`` (contract (d) of PrefillBatchConfig — tiled
    segment starts must never clamp against the cache's seq capacity)."""
    tile = 1
    while tile < 128 and max_tokens_per_batch % (tile * 2) == 0:
        tile *= 2
    while tile > 1 and max_seq_len % tile:
        tile //= 2
    return tile


def sample_tokens(logits, sample):
    """Temperature + nucleus (top-p) sampling; exact argmax at T<=0.

    Same math as the ``Sampling`` graph op (ops/reduction.py, reference
    ``src/ops/sampling.cu``) but with DYNAMIC temperature/top_p (traced
    scalars, so one compiled step serves every GenerationConfig) and an
    explicit key threaded from the RequestManager.

    ``sample`` is ``(key, temperature, top_p)`` — one key draws every row —
    or the resilient-serving 4-tuple ``(key, temperature, top_p, folds)``
    with ``folds`` i32[rows, 2]: row ``i`` draws from
    ``fold_in(fold_in(key, folds[i, 0]), folds[i, 1])``, i.e. a PER-REQUEST
    (rid, token-index) key schedule that is invariant to batch composition
    and preemption-and-recompute (see RequestManager._sample_for).
    """
    key, temperature, top_p = sample[:3]
    folds = sample[3] if len(sample) > 3 else None
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def draw(_):
        lg = logits / jnp.maximum(temperature, 1e-6)
        sorted_lg = jnp.sort(lg, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_lg, cutoff_idx, axis=-1)
        lg = jnp.where(lg < cutoff, -jnp.inf, lg)
        if folds is None:
            return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)
        keys = jax.vmap(
            lambda f: jax.random.fold_in(jax.random.fold_in(key, f[0]), f[1])
        )(folds)
        return jax.vmap(jax.random.categorical)(keys, lg).astype(jnp.int32)

    return jax.lax.cond(temperature <= 0.0, lambda _: greedy, draw, None)


class InferenceManager:
    # serving telemetry handle (obs/): host-side dispatch spans only — it
    # is NEVER passed into a jitted program, so attaching a live handle
    # cannot change compiled executables or their outputs.  RequestManager
    # shares its handle here; the class default is the no-op singleton.
    telemetry = NULL_TELEMETRY
    # seeded chaos hook (serve/resilience.py), synced by the RequestManager
    # like the telemetry handle.  Consulted at each dispatch site BEFORE
    # any work reaches the device, so an injected fault leaves no partial
    # device state and a retried dispatch replays identical compute.
    fault_injector = None
    # step-level cost attribution (obs/profiler.py), synced by the
    # RequestManager like the telemetry handle: dispatch-phase timing +
    # the dispatch counter live HERE (the program-launch sites); the
    # deterministic flops/byte accounting lives in the RequestManager
    # (host bookkeeping).  Host-side only — never traced into a program.
    profiler = NULL_PROFILER

    def __init__(
        self,
        model,
        max_requests: int = 8,
        max_tokens_per_batch: int = 64,
        max_seq_len: int = 512,
        max_spec_tokens: int = 0,
        strategy: Optional[Dict[str, Dict]] = None,
        tp_axes: Optional[Tuple[str, ...]] = None,
        topk: int = 0,
        outputs=None,
        use_pallas: str = "auto",
        kv_dtype: Optional[str] = None,
        gate_lm_head: bool = True,
        prefill_overlap: bool = True,
        kv_page_size: Optional[int] = None,
    ):
        """``model`` is an FFModel whose graph was built by a serve builder.

        ``outputs``: the logits Tensor(s); defaults to the last node's last
        output (the LM head) — serve graphs can have dangling intermediate
        tensors (e.g. the unused residual sum of the final fused norm).

        ``kv_dtype``: KV-cache storage dtype.  ``"int8"`` stores the
        committed k/v caches as int8 with per-(row, head, position) f32
        scales (quantize-on-write, dequant fused into the Pallas attention
        kernels) — halving decode KV bandwidth vs bf16 and the capacity
        term that gates full-depth models; None (default) keeps the model's
        compute dtype.  Registered on the attention ops BEFORE planning, so
        ``plan_memory_bytes`` / the serve search see the quantized cache
        footprint.

        ``gate_lm_head``: mark the logits-producing Linear for LM-head
        gating — prefill chunks built by the RequestManager then compute
        logits only at each request's last prompt token (gather-then-GEMM
        over <= max_requests rows) instead of all chunk positions.  The
        flag is read at BATCH-BUILD time (it decides whether
        PrefillBatchConfigs carry ``logit_slots``), so it can be toggled
        between calls for ablation; decode/mixed/hand-built batches are
        never gated.

        ``kv_page_size``: enable the PAGED KV cache (serve/kv_paged.py):
        the same physical buffers are carved into fixed pages of this many
        tokens, managed through a per-request block table with refcounted
        copy-on-write prefix sharing — no fragmentation at high occupancy,
        shared system prompts prefilled once.  Must divide ``max_seq_len``
        AND its 128-lane pad (asserted at allocator construction) and be a
        multiple of the prefill tile (asserted here).  None (default)
        keeps the slot-contiguous allocator; both paths are bit-identical
        (tests/test_kv_paged.py).  Writes require mapped pages: the
        RequestManager prepares them before every dispatch
        (``_kv_prepare``); callers driving ``step``/``decode_scan``
        directly must call ``kv.bind(rid, slot=...)`` +
        ``kv.prepare_write(rid, lo, hi)`` themselves — an unprepared
        write lands in the scratch page (pad-token semantics), not an
        error.

        ``prefill_overlap``: software-pipeline the prefill scan — chunk
        i+1's embedding→norm→layer-0 QKV projection is issued inside chunk
        i's scan step (carried across the ``lax.scan`` boundary), giving
        XLA's scheduler a cross-iteration target to overlap with chunk i's
        attention/MLP tail.  Auto-disabled when the graph's prologue isn't
        the recognized embedding→rms_norm→attention chain (OPT's position
        embedding, falcon's parallel blocks ride the plain scan).  Read
        per prefill_scan call (static jit arg), so it too ablates without
        rebuilding.
        """
        self.model = model
        self.max_requests = max_requests
        self.max_tokens = max_tokens_per_batch
        self.max_seq_len = max_seq_len
        self.max_spec_tokens = max_spec_tokens
        self.topk = topk
        if kv_dtype not in (None, "int8"):
            # no silent fp coercion: the caches follow the model's compute
            # dtype unless quantized, so honoring e.g. a float32 request on
            # a bf16 model would need a real mixed-precision cache path —
            # refuse rather than hand back a dtype the caller didn't ask for
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r} "
                             "(expected None or 'int8'; fp caches always "
                             "use the model's compute dtype)")
        self.kv_dtype = kv_dtype
        mesh = model.mesh
        if tp_axes is None:
            tp_axes = ("tp",) if mesh is not None and "tp" in mesh.shape else ()
        self.tp_axes = tuple(tp_axes)
        # register serve capacities on the attention ops so the search's
        # cost/memory models see the KV + spec buffers (plan_memory_bytes)
        register_serve_capacities(model.graph, max_requests, max_seq_len,
                                  max_spec_tokens, kv_dtype)
        if outputs is None:
            out_tids = [model.graph.nodes[-1].outputs[-1]]
        else:
            outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
            out_tids = [t.tid for t in outputs]
        # LM-head gating: mark the logits producer (the final Linear) so
        # prefill chunks carrying ``logit_slots`` compute logits only at
        # sample points.  cost_logit_rows makes the search's cost model
        # price the gated program (Linear.flops) — marked BEFORE the serve
        # search runs, like the KV capacities above.  ``_lm_head_marked``
        # records whether a Linear was actually marked: the public
        # ``gate_lm_head`` property ANDs it in, so flipping the flag True
        # on a graph whose logits producer was never marked (no single
        # Linear output) cannot make the RequestManager build gated
        # batches an unmarked LM head would ignore — slot-indexed sample
        # points against flat-indexed results would corrupt every request.
        self._lm_head_marked = False
        self._gate_lm_head = bool(gate_lm_head)
        if gate_lm_head:
            self._lm_head_marked = mark_gated_lm_head(
                model.graph, out_tids, max_requests)
        if strategy == "search":
            strategy = searched_serve_strategy(model)
        elif strategy is None:
            strategy = tensor_parallel_strategy(model.graph, self.tp_axes, mesh) \
                if self.tp_axes else {}
        self.strategy = strategy
        self.pcg = PCG(model.graph, mesh, strategy, output_tids=out_tids)
        self.plan = self.pcg.plan()
        self._fwd = build_forward(self.plan, mode="spmd")
        self._token_tid = model.graph.input_tids[0]
        self.params = None
        # KV-cache ownership lives in the allocator (serve/kv_allocator.py)
        # — admission control, preemption pricing, and the memory ledger
        # all consult THIS object; ``self.state`` is a delegating property,
        # so the jitted step's donate/re-bind cycle is unchanged.
        # ``kv_page_size`` swaps in the paged allocator behind the same
        # interface (serve/kv_paged.py).
        stage_kv = [StageKV(model.graph.nodes, strategy, self.plan.mesh,
                            max_requests, max_seq_len, max_spec_tokens)]
        self.kv_page_size = kv_page_size
        if kv_page_size:
            from .kv_paged import PagedKVAllocator

            self.kv = PagedKVAllocator(stage_kv, max_requests, max_seq_len,
                                       page_size=kv_page_size)
        else:
            self.kv = KVAllocator(stage_kv, max_requests, max_seq_len)
        # Pallas decode/tree kernels: replace the cache-row-gather attention.
        # "auto" = on for TPU backends; under TP the attention op wraps the
        # kernel in shard_map over the kv-head axis (IncMultiHeadSelfAttention
        # ._head_shard_map) — shardings it can't express (non-head mesh axes
        # > 1) fall back to the gather path per op.  True forces the flag on
        # (interpret mode off-TPU, for tests); False = pure-JAX path.
        # INIT-ONLY: the flags are baked into the jitted step at first trace;
        # mutating the attributes afterwards has no effect.
        backend = jax.default_backend()
        if use_pallas == "auto":
            self.use_pallas = backend == "tpu"
        else:
            self.use_pallas = bool(use_pallas)
        self.pallas_interpret = backend != "tpu"
        # query-tile width for the Pallas prefill kernel: the largest
        # power-of-two divisor of max_tokens, capped at 128.  64 measured
        # ~17% faster than 32 on v5e; 128 used to fail to compile at the 7B
        # shape (the [KV, tile*gq, block_s] f32 score tile alone is 8 MB) —
        # the KV-HEAD-CHUNKED grid axis in ops/pallas/attention.py now
        # shrinks the per-grid-step working set (scores [kv_chunk, tile*gq,
        # block_s]) until it fits, so the wider tile is admissible: half
        # the grid rows per chunk, half the per-row DMA-wait boundaries.
        # RequestManager builds PrefillBatchConfigs with this tile size for
        # pure-prefill steps.  The tile must also divide max_seq_len
        # (ADVICE r5 medium): the tiled-prefill block DUS assumes
        # tile-aligned starts never clamp against the cache's seq capacity.
        self.prefill_tile = pick_prefill_tile(max_tokens_per_batch,
                                              max_seq_len)
        if kv_page_size:
            from .kv_paged import validate_page_tile

            validate_page_tile(kv_page_size, self.prefill_tile)
        # fixed tree-token layout (rows, slots) registered by SpecDecodeScan
        # (one per InferenceManager); the layout is PASSED per step by the
        # scan, never applied to host-built tree batches
        self.tree_token_layout: Optional[Tuple[int, int]] = None
        # prefill software pipelining: recognize the embedding -> rms_norm
        # -> attention prologue (llama-family serve graphs) whose layer-0
        # QKV projection can be issued one scan step early.  Graphs with a
        # different prologue (OPT's position embedding, falcon's parallel
        # blocks) keep the plain scan.
        self._overlap_steps = None
        steps = self.plan.steps
        if (prefill_overlap and len(steps) >= 3
                and steps[0].node.op.type_name == "embedding"
                and steps[1].node.op.type_name == "rms_norm"
                and steps[2].node.op.type_name
                == "inc_multihead_self_attention"
                and list(steps[1].in_vids) == list(steps[0].out_vids[:1])
                and list(steps[2].in_vids) == list(steps[1].out_vids[:1])):
            self._overlap_steps = tuple(steps[:3])
            steps[2].node.op.qkv0_consumer = True
        self.prefill_overlap = self._overlap_steps is not None
        # CPU virtual-device meshes get a sequential HLO schedule PER
        # PROGRAM (collective rendezvous deadlock class, VERDICT r4 weak
        # #1 / r5 weak #5) instead of the old process-wide XLA_FLAGS
        # override — single-device programs keep the default scheduler.
        from ..utils.platform import collective_safe_compiler_options

        opts = collective_safe_compiler_options(mesh)
        self._step = jax.jit(self._step_impl, donate_argnums=(1,),
                             compiler_options=opts)
        self._scan = jax.jit(
            self._decode_scan_impl,
            donate_argnums=(1,),
            static_argnames=("n_steps", "eos"),
            compiler_options=opts,
        )
        self._pscan = jax.jit(self._prefill_scan_impl, donate_argnums=(1,),
                              static_argnames=("overlap",),
                              compiler_options=opts)
        # mid-stretch slot join (on-device continuous batching): a tiny
        # program that activates one batch row between scan segments
        self._join = jax.jit(self._join_impl, static_argnames=("eos",),
                             compiler_options=opts)

    @property
    def gate_lm_head(self) -> bool:
        """Whether RequestManager-built prefill chunks gate the LM head.

        True only when the flag is on AND a Linear was actually marked at
        construction — the two cannot disagree (see __init__)."""
        return self._gate_lm_head and self._lm_head_marked

    @gate_lm_head.setter
    def gate_lm_head(self, value) -> None:
        self._gate_lm_head = bool(value)

    @property
    def state(self):
        """The KV-cache buffers, owned by the allocator.  The property
        keeps the historical API: the jitted step takes ``self.state``
        (donated) and the result re-binds it, with the allocator as the
        one place the buffers live."""
        return self.kv.state

    @state.setter
    def state(self, value) -> None:
        self.kv.state = value

    @property
    def plan_key(self) -> str:
        """This deployment's coordinates in the serve search's
        ``tp{t}_pp{p}_m{m}`` convention (single-plan: pp=1, m=1)."""
        tp = 1
        mesh = self.plan.mesh
        if mesh is not None:
            shape = dict(mesh.shape)
            for a in self.tp_axes:
                tp *= shape.get(a, 1)
        return f"tp{tp}_pp1_m1"

    # ------------------------------------------------------------------
    def init_operators_inference(self, params=None, rng=None, dtype=None):
        """Initialize params (random if none given) and allocate KV caches.

        Reference: ``InferenceManager::init_operators_inference`` +
        the cache allocation inside each attention op's ``init_task``.
        """
        from ..core.interpreter import init_params

        if params is None:
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            params = init_params(self.model.graph, self.plan, rng, dtype=dtype)
        self.params = params
        self.allocate_kv_cache()
        return self

    def allocate_kv_cache(self):
        state = self.kv.allocate()
        self.kv.reset_attribution()
        return state

    def publish_memory(self, telemetry, key: Optional[str] = None) -> None:
        """Record this deployment's predicted-vs-allocated HBM into the
        handle's memory ledger (obs/memory.py): predicted =
        ``plan_memory_parts`` over the compiled plan (the same arithmetic
        the serve search gates with), allocated = the REAL parameter and
        KV-buffer bytes (int8 values+scales and lane padding included).
        ``key`` overrides the ledger plan key — co-resident deployments
        (the spec draft model) must not collide with the target's record
        when both run the same tp/pp shape.  Host-side accounting only;
        no-op for a disabled handle or before the caches are allocated."""
        if telemetry is None or not getattr(telemetry, "enabled", False):
            return
        from ..obs.memory import publish_predicted_parts
        from ..search.simulator import compose_stage_parts, plan_memory_parts

        key = key or self.plan_key
        # static_gb = the statically-allocatable share (weights + KV) —
        # the component the allocated side can actually be compared to;
        # total_gb keeps the transient and stays one-sided (nothing ever
        # "allocates" a transient, so reconciling it would book the
        # activation share as model error)
        publish_predicted_parts(
            telemetry, key,
            compose_stage_parts([plan_memory_parts(self.plan,
                                                   training=False)]))
        if self.state is None:
            return
        from .kv_allocator import params_nbytes

        w = params_nbytes(self.params)
        kv = self.kv.allocated_bytes(kv_only=False, per_device=True)
        telemetry.memory_plan_allocated(
            key, weights_gb=w / 1e9, kv_gb=kv / 1e9,
            static_gb=(w + kv) / 1e9,
        )

    # ------------------------------------------------------------------
    def _sample_tokens(self, logits, sample):
        """See module-level :func:`sample_tokens` (shared with the
        pipeline-parallel manager)."""
        return sample_tokens(logits, sample)

    def _step_impl(self, params, state, bc, sample=None, tree_layout=None,
                   qkv0=None, pages=None):
        # ``tree_layout`` is passed ONLY by SpecDecodeScan, whose verify
        # batches are guaranteed slot-major [R, P]; host-built tree batches
        # (SpecInferManager) have variable layouts and must not take the
        # batched-kernel path.  ``qkv0`` (prefill software pipelining) is
        # this chunk's precomputed layer-0 q/k/v from the scan carry; only
        # the marked qkv0_consumer attention op reads it.  ``pages`` is the
        # paged-KV block table (kv_paged.PageTable) every attention op
        # translates its cache coordinates through; None = slot-contiguous.
        base = bc if isinstance(bc, BatchConfig) else bc.base
        outs, new_state = self._fwd(
            params,
            {self._token_tid: base.tokens},
            state=state,
            extras={
                "batch_config": bc,
                "pallas_decode": self.use_pallas,
                "pallas_interpret": self.pallas_interpret,
                "tree_layout": tree_layout
                if not isinstance(bc, BatchConfig) else None,
                "qkv0": qkv0,
                "pages": pages,
            },
        )
        logits = outs[0].astype(jnp.float32)  # [T, vocab]
        if sample is not None:
            token_ids = self._sample_tokens(logits, sample)
        else:
            token_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits_max = jnp.max(logits, axis=-1)
        topk_ids = topk_lp = None
        if self.topk:
            lp = jax.nn.log_softmax(logits, axis=-1)
            topk_lp, topk_ids = jax.lax.top_k(lp, self.topk)
            topk_ids = topk_ids.astype(jnp.int32)
        return (
            InferenceResult(token_ids, logits_max, topk_ids, topk_lp),
            new_state,
        )

    def _page_view(self):
        """Current device-side block table (None = slot-contiguous).  Read
        per dispatch — the RequestManager's pre-dispatch ``prepare_write``
        calls may have remapped pages (allocation, COW) since last step."""
        return self.kv.page_view()

    def step(self, bc, sample=None) -> InferenceResult:
        """Run one serving step; caches update in place (donated).

        ``sample``: optional ``(key, temperature, top_p)`` — argmax if None.
        """
        assert self.params is not None, "call init_operators_inference() first"
        if self.fault_injector is not None:
            self.fault_injector.maybe_fail("step")
        # span = host dispatch time (the jit call returns without syncing);
        # device time shows up at the result readback, not here.  Dispatch
        # spans live on their own track: they nest inside the serve loop's
        # spans, and per-track totals assume non-overlapping spans per track
        prof = self.profiler
        if prof.enabled:
            prof.count("dispatches")
        with self.telemetry.span("step_dispatch", cat="dispatch",
                                 track="dispatch"), prof.phase("dispatch"):
            result, self.state = self._step(self.params, self.state, bc,
                                            sample, None, None,
                                            self._page_view())
        return result

    # ------------------------------------------------------------------
    def _decode_scan_impl(self, params, state, bc, sample, pages, allowed,
                          n_steps: int, eos: Optional[int]):
        """n_steps pure-decode steps as ONE on-device ``lax.scan``.

        TPU-first redesign of the reference's serving loop (§3.3): instead of
        a host round trip per token (``prepare_next_batch`` → dispatch →
        sync), the next step's BatchConfig is derived on device from the
        step's output (``BatchConfig.advance``) and the host only syncs once
        per scan.  With dispatch latency L and device step time t, TPOT drops
        from ``max(L, t)`` to ``t + L/n_steps``.

        ``eos`` (static): slots that emit it are FROZEN for the rest of the
        scan — their request_index flips to -1, so later steps write their
        KV to the scratch row and their emissions are masked out of ``live``.

        ``allowed`` (i32[max_tokens] or None): per-flat-row remaining token
        budgets — the device-side ``max_new_tokens`` exit.  A row is frozen
        the same way once it has emitted ``allowed[row]`` tokens, so a
        chained stretch can run rows of UNEQUAL remaining budgets in one
        scan without overshooting any of them.  Per-row exit codes
        (``EXIT_*``) come back with the results: what ended each row —
        still running, EOS, or budget — readable in the stretch's single
        readback, so the host reaps lifecycle outcomes without re-deriving
        them from the token stream.
        """
        present = bc.request_index >= 0
        alive0 = present
        if allowed is not None:
            alive0 = alive0 & (allowed > 0)
            # entry freeze: a row that arrives with no budget must not
            # write KV even on step 0 (its writes go to the scratch row)
            bc = BatchConfig(
                tokens=bc.tokens,
                request_index=jnp.where(alive0, bc.request_index, -1),
                token_position=bc.token_position,
                num_tokens=bc.num_tokens,
                seq_lens=bc.seq_lens,
            )

        def body(carry, i):
            state, bc, alive, eos_hit = carry
            stp = None
            if sample is not None:
                if len(sample) > 3:
                    # per-request key schedule: each row's token index
                    # advances one per scan step
                    key, temperature, top_p, folds = sample
                    stp = (key, temperature, top_p, folds.at[:, 1].add(i))
                else:
                    key, temperature, top_p = sample
                    stp = (jax.random.fold_in(key, i), temperature, top_p)
            # the block table is CONSTANT across the scan: the manager's
            # prepare_write pre-mapped (and COW-resolved) every page the
            # n_steps positions can reach before dispatch
            result, state = self._step_impl(params, state, bc, stp,
                                            pages=pages)
            toks = result.token_ids
            live = alive  # emission validity for THIS step
            if eos is not None:
                hit = live & (toks == eos)
                eos_hit = eos_hit | hit
                alive = alive & ~hit
            if allowed is not None:
                alive = alive & (i + 1 < allowed)
            nxt = bc.advance(toks)
            if eos is not None or allowed is not None:
                nxt = BatchConfig(
                    tokens=nxt.tokens,
                    request_index=jnp.where(alive, nxt.request_index, -1),
                    token_position=nxt.token_position,
                    num_tokens=nxt.num_tokens,
                    seq_lens=nxt.seq_lens,
                )
            return (state, nxt, alive, eos_hit), (toks, live)

        eos_hit0 = jnp.zeros_like(alive0)
        (state, bc, alive_end, eos_hit), (tokens, live) = jax.lax.scan(
            body, (state, bc, alive0, eos_hit0), jnp.arange(n_steps)
        )
        ecode = jnp.where(
            ~present, EXIT_NOT_IN_BATCH,
            jnp.where(eos_hit, EXIT_EOS,
                      jnp.where(alive_end, EXIT_RUNNING, EXIT_BUDGET)),
        ).astype(jnp.int32)
        return tokens, live, ecode, state, bc

    def _decode_scan_guards(self, n_steps: int, max_position=None,
                            bc=None) -> None:
        """Shared pre-dispatch validation for the scan paths.

        ``max_position``: the highest ``token_position`` in the batch as
        HOST bookkeeping (the chained path always knows it — reading it
        off a device-resident ``bc`` would force the mid-stretch sync the
        whole design removes).  Falls back to reading ``bc`` when the
        caller has no host-side count (external hand-built batches)."""
        import numpy as np

        from .ops import DUS_MAX_TOKENS

        if self.max_tokens > DUS_MAX_TOKENS:
            # the scan's KV writes are padded to max_tokens; past the DUS
            # threshold they become an XLA scatter whose layout choice
            # forces a per-step full-cache relayout (see ops.DUS_MAX_TOKENS)
            import warnings

            warnings.warn(
                f"decode_scan with max_tokens_per_batch {self.max_tokens} > "
                f"{DUS_MAX_TOKENS}: KV writes take the scatter path and "
                "re-lay out the full cache every step; use a smaller "
                "max_tokens_per_batch for scanned decoding",
                stacklevel=2,
            )
        if max_position is None:
            max_position = int(np.max(np.asarray(bc.token_position)))
        last = int(max_position) + n_steps
        if last > self.max_seq_len:
            raise ValueError(
                f"decode_scan would reach position {last} > max_seq_len "
                f"{self.max_seq_len}; cache writes past the end clamp to the "
                "last slot and silently corrupt it"
            )

    def decode_scan(self, bc, n_steps: int, eos: Optional[int] = None,
                    sample=None):
        """Run ``n_steps`` decode steps on device.

        Returns ``(tokens, live, bc)``: i32[n_steps, T] token ids,
        bool[n_steps, T] emission validity (False once a slot passed its
        ``eos``), and the advanced BatchConfig to resume from.
        """
        assert self.params is not None, "call init_operators_inference() first"
        self._decode_scan_guards(n_steps, bc=bc)
        if self.fault_injector is not None:
            self.fault_injector.maybe_fail("decode_scan")
        prof = self.profiler
        if prof.enabled:
            prof.count("dispatches")
        with self.telemetry.span("decode_scan_dispatch", cat="dispatch",
                                 track="dispatch",
                                 n_steps=n_steps), prof.phase("dispatch"):
            tokens, live, _, self.state, bc = self._scan(
                self.params, self.state, bc, sample, self._page_view(),
                None, n_steps=n_steps, eos=eos
            )
        if self.telemetry.enabled:
            self.telemetry.metrics.counter("decode_scan_steps").inc(n_steps)
        return tokens, live, bc

    def decode_scan_async(self, bc, n_steps: int, eos: Optional[int] = None,
                          sample=None, allowed=None,
                          max_position: Optional[int] = None):
        """One chained-stretch segment: ``n_steps`` decode steps with NO
        readback and NO host-side read of ``bc``.

        The on-device continuous-batching path (request_manager's chained
        ``_decode_stretch``): segments dispatch back-to-back, joins splice
        arrivals in between them (``join_slot``), and the host materializes
        everything in ONE sync at stretch end.  ``allowed`` is the
        per-flat-row remaining-token budget (i32[max_tokens]); rows freeze
        on device when it runs out, so heterogeneous budgets share one
        scan.  ``max_position`` is the caller's host bookkeeping of the
        batch's highest token position (required: this path must not sync
        to validate).  Returns LAZY device values
        ``(tokens, live, exit_codes, bc)``.
        """
        assert self.params is not None, "call init_operators_inference() first"
        assert max_position is not None, \
            "decode_scan_async requires host-tracked max_position"
        self._decode_scan_guards(n_steps, max_position=max_position)
        if self.fault_injector is not None:
            self.fault_injector.maybe_fail("decode_scan")
        prof = self.profiler
        if prof.enabled:
            prof.count("dispatches")
        with self.telemetry.span("decode_scan_dispatch", cat="dispatch",
                                 track="dispatch",
                                 n_steps=n_steps), prof.phase("dispatch"):
            tokens, live, ecode, self.state, bc = self._scan(
                self.params, self.state, bc, sample, self._page_view(),
                allowed, n_steps=n_steps, eos=eos
            )
        if self.telemetry.enabled:
            self.telemetry.metrics.counter("decode_scan_steps").inc(n_steps)
        return tokens, live, ecode, bc

    def _join_impl(self, bc, tok_src, src_idx, dst, slot, pos, seq_len,
                   num_tokens, eos: Optional[int]):
        tok = tok_src[src_idx]
        active = True if eos is None else tok != eos
        return bc.join_row(dst, tok, slot, pos, seq_len, num_tokens,
                           active=active)

    def join_slot(self, bc, tok_src, src_idx, dst, slot, pos, seq_len,
                  num_tokens, eos: Optional[int] = None):
        """Splice one staged arrival into a running stretch's batch.

        ``tok_src``: the arrival's final prefill-chunk result tokens (a
        DEVICE array — reading it would sync); ``src_idx``: where its next
        token sits in that array; ``dst``: the flat batch row the request
        occupies from now on; ``slot``/``pos``/``seq_len``/``num_tokens``:
        host bookkeeping of the joined batch.  One tiny jitted program
        (fixed avals — compiles once, polled by the recompile guard); the
        dispatched chain stays fully async.
        """
        prof = self.profiler
        if prof.enabled:
            prof.count("dispatches")
        with prof.phase("dispatch"):
            return self._join(
                bc, tok_src, jnp.int32(src_idx), jnp.int32(dst),
                jnp.int32(slot), jnp.int32(pos), jnp.int32(seq_len),
                jnp.int32(num_tokens), eos=eos)

    # ------------------------------------------------------------------
    def _project_chunk0(self, params, bc):
        """Embedding → layer-0 norm → layer-0 QKV projection for one chunk.

        The prologue the prefill pipelining issues one scan step EARLY
        (``_prefill_scan_impl``).  Runs the exact op ``lower``s of the
        recognized plan steps (with the interpreter's sharding constraints
        and the same extras the in-graph lowering would see), so the
        carried q/k/v are bit-identical to what the in-graph path would
        compute — an invariant pinned end-to-end by
        tests/test_prefill_gating.py::test_prefill_overlap_scan_bit_identical,
        which is the guard if a future op lower or interpreter convention
        change makes the two paths diverge.
        """
        from ..core.interpreter import _constrain_spmd, _mesh_is_trivial
        from ..core.op import OpContext

        e_step, n_step, a_step = self._overlap_steps
        mesh = self.plan.mesh
        trivial = _mesh_is_trivial(mesh)
        x = bc.base.tokens
        for step in (e_step, n_step):
            ctx = OpContext(
                mode="spmd", mesh=None if trivial else mesh,
                training=False, rng=None, config=step.config,
                extras={
                    # mirror _step_impl's extras so an embedding/norm lower
                    # that consults any of them behaves identically here
                    # (pages stays None: the prologue never touches caches)
                    "batch_config": bc,
                    "pallas_decode": self.use_pallas,
                    "pallas_interpret": self.pallas_interpret,
                    "tree_layout": None,
                    "qkv0": None,
                    "pages": None,
                },
            )
            [x] = step.node.op.lower(ctx, [x],
                                     params.get(step.node.name, {}))
            if not trivial:
                x = _constrain_spmd(x, step.out_shardings[0], mesh)
        return a_step.node.op.project_qkv(
            x, params.get(a_step.node.name, {}), bc)

    def _prefill_scan_impl(self, params, state, bcs, sample=None,
                           pages=None, overlap=False):
        """A stack of prefill chunks as ONE on-device ``lax.scan``.

        The decode loop already scans (``decode_scan``); prefill was the one
        serve phase still paying a host dispatch (+ ~100ms tunnel sync at
        request boundaries) per chunk.  ``bcs`` is a PrefillBatchConfig whose
        leaves carry a leading chunk axis; each scan step runs the normal
        step program (Q-tiled Pallas prefill kernel included) and emits its
        token ids — the host reads only the sample points it needs, once,
        after the whole scan.  With LM-head gating (``bcs.logit_slots``)
        the emitted ids are [n_chunks, max_requests], indexed by slot.

        ``overlap`` (static): software-pipeline the scan — step i ALSO
        computes chunk i+1's embedding→norm→layer-0 QKV (``_project_chunk0``)
        and carries it, so the projection (and its weight fetch) is visible
        to XLA's scheduler alongside chunk i's attention/MLP tail instead
        of sitting behind the while-loop iteration boundary.  Costs one
        redundant prologue per scan segment (the last step precomputes a
        dummy); measured on device via the bench's overlap ablation — if
        XLA's scheduler refuses the overlap the ablation delta is ~0 and
        the artifact records it as scheduler-bound.
        """
        # per-request (rid, token-index) sample keys ride the scan xs with
        # a leading chunk axis (the 4-tuple schedule — see sample_tokens);
        # the legacy 3-tuple folds the shared key by chunk index instead
        per_row = sample is not None and len(sample) > 3
        folds_all = sample[3] if per_row else None

        def run_step(state, bc, i, fold=None, qkv0=None):
            stp = None
            if per_row:
                stp = (sample[0], sample[1], sample[2], fold)
            elif sample is not None:
                key, temperature, top_p = sample
                stp = (jax.random.fold_in(key, i), temperature, top_p)
            return self._step_impl(params, state, bc, stp, qkv0=qkv0,
                                   pages=pages)

        n = bcs.base.tokens.shape[0]
        idx = jnp.arange(n)
        if not overlap:
            def body(state, xs):
                bc, i = xs[0], xs[1]
                result, state = run_step(state, bc, i,
                                         xs[2] if per_row else None)
                return state, result.token_ids

            state, tokens = jax.lax.scan(
                body, state,
                (bcs, idx, folds_all) if per_row else (bcs, idx))
            return tokens, state  # tokens: i32[n_chunks, T or R]

        # chunk i+1's batch config rides step i's xs; the final step
        # re-projects its own chunk (uniform program; output unused)
        bcs_next = jax.tree_util.tree_map(
            lambda x: jnp.concatenate([x[1:], x[-1:]], axis=0), bcs)
        pre0 = self._project_chunk0(
            params, jax.tree_util.tree_map(lambda x: x[0], bcs))

        def body(carry, xs):
            state, pre = carry
            bc, bc_next, i = xs[0], xs[1], xs[2]
            result, state = run_step(state, bc, i,
                                     xs[3] if per_row else None, qkv0=pre)
            pre_next = self._project_chunk0(params, bc_next)
            return (state, pre_next), result.token_ids

        (state, _), tokens = jax.lax.scan(
            body, (state, pre0),
            (bcs, bcs_next, idx, folds_all) if per_row
            else (bcs, bcs_next, idx))
        return tokens, state

    def prefill_scan(self, bcs, sample=None):
        """Run a stacked PrefillBatchConfig (leading chunk axis) on device.

        ``sample``: optional ``(key, temperature, top_p)`` so the chunks
        carrying a prompt's final position emit a SAMPLED first token.
        """
        assert self.params is not None, "call init_operators_inference() first"
        if self.fault_injector is not None:
            self.fault_injector.maybe_fail("prefill_scan")
        prof = self.profiler
        if prof.enabled:
            prof.count("dispatches")
        with self.telemetry.span("prefill_scan_dispatch", cat="dispatch",
                                 track="dispatch",
                                 n_chunks=int(bcs.base.tokens.shape[0])), \
                prof.phase("dispatch"):
            tokens, self.state = self._pscan(
                self.params, self.state, bcs, sample, self._page_view(),
                overlap=bool(self.prefill_overlap
                             and self._overlap_steps is not None),
            )
        return tokens

    def reset(self):
        """Clear all cache contents (new serving session)."""
        self.state = self.allocate_kv_cache()
