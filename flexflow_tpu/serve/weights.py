"""HuggingFace weight import: torch state dict → flexflow_tpu param tree.

Reference: ``inference/file_loader.cc`` (``FileDataLoader::load_weights``) and
``python/flexflow/serve/serve.py``'s download-and-convert path.  The reference
exports HF checkpoints to raw binary per-tensor files and loads them into
Legion regions with manual TP slicing; here the conversion is a pure name/
layout map into the param pytree and sharding is applied by ``device_put``
with the plan's NamedShardings — GSPMD handles the slicing.

Layout notes (torch ``nn.Linear.weight`` is ``[out, in]``; our Linear kernel
is ``[in, out]``, so every projection transposes):

* ``q/k/v_proj`` fuse into the kv-head-major ``qkv [E, KV, q_per_kv+2, D]``
  used by :class:`~flexflow_tpu.serve.ops.IncMultiHeadSelfAttention` (one MXU
  GEMM, TP = shard dim 1).
* ``o_proj.weight [E, QH*D]`` → ``[QH*D, E]``.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .models.base import ServeModelConfig


def _t(x) -> np.ndarray:
    """torch tensor (any dtype/device) -> float32 numpy."""
    import torch

    if isinstance(x, torch.Tensor):
        return x.detach().to(torch.float32).cpu().numpy()
    return np.asarray(x, np.float32)


def fuse_qkv(qw, kw, vw, cfg: ServeModelConfig) -> np.ndarray:
    """[QH*D,E],[KV*D,E],[KV*D,E] (torch layout) -> [E, KV, q_per_kv+2, D]."""
    e = cfg.hidden_size
    kv, d = cfg.kv_heads, cfg.hdim
    gq = cfg.num_attention_heads // kv
    q = _t(qw).T.reshape(e, kv, gq, d)
    k = _t(kw).T.reshape(e, kv, 1, d)
    v = _t(vw).T.reshape(e, kv, 1, d)
    return np.concatenate([q, k, v], axis=2)


def convert_llama_state_dict(
    sd: Dict, cfg: ServeModelConfig, dtype=jnp.float32
) -> Dict[str, Dict[str, jax.Array]]:
    """HF LLaMA ``state_dict()`` → ``{node_name: {param_name: array}}``.

    Node names in the serve graph intentionally equal HF module prefixes
    (see ``models/llama.py``), so this is mostly a suffix map.
    """
    params: Dict[str, Dict[str, jax.Array]] = {}

    def put(node, pname, arr):
        params.setdefault(node, {})[pname] = jnp.asarray(arr, dtype)

    put("model.embed_tokens", "weight", _t(sd["model.embed_tokens.weight"]))
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}"
        put(f"{p}.input_layernorm", "gamma", _t(sd[f"{p}.input_layernorm.weight"]))
        put(
            f"{p}.post_attention_layernorm", "gamma",
            _t(sd[f"{p}.post_attention_layernorm.weight"]),
        )
        put(
            f"{p}.self_attn", "qkv",
            fuse_qkv(
                sd[f"{p}.self_attn.q_proj.weight"],
                sd[f"{p}.self_attn.k_proj.weight"],
                sd[f"{p}.self_attn.v_proj.weight"],
                cfg,
            ),
        )
        put(f"{p}.self_attn", "o_proj", _t(sd[f"{p}.self_attn.o_proj.weight"]).T)
        for proj in ("gate_proj", "up_proj", "down_proj"):
            put(f"{p}.mlp.{proj}", "kernel", _t(sd[f"{p}.mlp.{proj}.weight"]).T)
    put("model.norm", "gamma", _t(sd["model.norm.weight"]))
    if "lm_head.weight" in sd:
        put("lm_head", "kernel", _t(sd["lm_head.weight"]).T)
    else:  # tied embeddings
        put("lm_head", "kernel", _t(sd["model.embed_tokens.weight"]).T)
    return params


def fuse_qkv_rows(w, cfg: ServeModelConfig) -> np.ndarray:
    """Pre-fused row-stacked ``[QH*D + 2*KV*D, E]`` (q|k|v, q heads in
    kv-major order — HF falcon/mpt/gpt_bigcode layouts) → our
    ``[E, KV, q_per_kv+2, D]``."""
    e = cfg.hidden_size
    kv, d = cfg.kv_heads, cfg.hdim
    qh = cfg.num_attention_heads
    w = _t(w)
    q, k, v = np.split(w, [qh * d, qh * d + kv * d], axis=0)
    return np.concatenate(
        [
            q.T.reshape(e, kv, qh // kv, d),
            k.T.reshape(e, kv, 1, d),
            v.T.reshape(e, kv, 1, d),
        ],
        axis=2,
    )


def fuse_qkv_bias(qb, kb, vb, cfg: ServeModelConfig) -> np.ndarray:
    kv, d = cfg.kv_heads, cfg.hdim
    gq = cfg.num_attention_heads // kv
    return np.concatenate(
        [
            _t(qb).reshape(kv, gq, d),
            _t(kb).reshape(kv, 1, d),
            _t(vb).reshape(kv, 1, d),
        ],
        axis=1,
    )


def fuse_qkv_rows_bias(b, cfg: ServeModelConfig) -> np.ndarray:
    kv, d = cfg.kv_heads, cfg.hdim
    qh = cfg.num_attention_heads
    qb, kb, vb = np.split(_t(b), [qh * d, qh * d + kv * d])
    return fuse_qkv_bias(qb, kb, vb, cfg)


def convert_opt_state_dict(sd, cfg: ServeModelConfig, dtype=jnp.float32):
    params: Dict[str, Dict[str, jax.Array]] = {}

    def put(node, pname, arr):
        params.setdefault(node, {})[pname] = jnp.asarray(arr, dtype)

    def ln(node, key):
        put(node, "gamma", _t(sd[f"{key}.weight"]))
        put(node, "beta", _t(sd[f"{key}.bias"]))

    put("model.decoder.embed_tokens", "weight",
        _t(sd["model.decoder.embed_tokens.weight"]))
    put("model.decoder.embed_positions", "weight",
        _t(sd["model.decoder.embed_positions.weight"]))
    for i in range(cfg.num_hidden_layers):
        p = f"model.decoder.layers.{i}"
        ln(f"{p}.self_attn_layer_norm", f"{p}.self_attn_layer_norm")
        put(
            f"{p}.self_attn", "qkv",
            fuse_qkv(
                sd[f"{p}.self_attn.q_proj.weight"],
                sd[f"{p}.self_attn.k_proj.weight"],
                sd[f"{p}.self_attn.v_proj.weight"],
                cfg,
            ),
        )
        put(
            f"{p}.self_attn", "qkv_bias",
            fuse_qkv_bias(
                sd[f"{p}.self_attn.q_proj.bias"],
                sd[f"{p}.self_attn.k_proj.bias"],
                sd[f"{p}.self_attn.v_proj.bias"],
                cfg,
            ),
        )
        put(f"{p}.self_attn", "o_proj", _t(sd[f"{p}.self_attn.out_proj.weight"]).T)
        put(f"{p}.self_attn", "o_bias", _t(sd[f"{p}.self_attn.out_proj.bias"]))
        ln(f"{p}.final_layer_norm", f"{p}.final_layer_norm")
        for fc in ("fc1", "fc2"):
            put(f"{p}.{fc}", "kernel", _t(sd[f"{p}.{fc}.weight"]).T)
            put(f"{p}.{fc}", "bias", _t(sd[f"{p}.{fc}.bias"]))
    if "model.decoder.final_layer_norm.weight" in sd:  # pre-LN variants only
        ln("model.decoder.final_layer_norm", "model.decoder.final_layer_norm")
    for proj in ("project_in", "project_out"):  # opt-350m embed projection
        key = f"model.decoder.{proj}.weight"
        if key in sd:
            put(f"model.decoder.{proj}", "kernel", _t(sd[key]).T)
    lm = sd.get("lm_head.weight", sd["model.decoder.embed_tokens.weight"])
    put("lm_head", "kernel", _t(lm).T)
    return params


def convert_falcon_state_dict(sd, cfg: ServeModelConfig, dtype=jnp.float32):
    if cfg.new_decoder_architecture:
        raise NotImplementedError(
            "falcon new_decoder_architecture weight layout is not supported"
        )
    params: Dict[str, Dict[str, jax.Array]] = {}

    def put(node, pname, arr):
        params.setdefault(node, {})[pname] = jnp.asarray(arr, dtype)

    def ln(node, key):
        put(node, "gamma", _t(sd[f"{key}.weight"]))
        put(node, "beta", _t(sd[f"{key}.bias"]))

    put("transformer.word_embeddings", "weight",
        _t(sd["transformer.word_embeddings.weight"]))
    for i in range(cfg.num_hidden_layers):
        p = f"transformer.h.{i}"
        ln(f"{p}.input_layernorm", f"{p}.input_layernorm")
        if not cfg.parallel_attn:  # falcon-rw sequential layout
            ln(f"{p}.post_attention_layernorm", f"{p}.post_attention_layernorm")
        # falcon's fused weight is already kv-head-major interleaved
        # (HF _split_heads: view(heads, 3, D) / view(heads+2, D) for MQA),
        # which IS our [E, KV, q_per_kv+2, D] layout — a straight reshape
        put(f"{p}.self_attention", "qkv",
            _t(sd[f"{p}.self_attention.query_key_value.weight"]).T.reshape(
                cfg.hidden_size, cfg.kv_heads,
                cfg.num_attention_heads // cfg.kv_heads + 2, cfg.hdim))
        put(f"{p}.self_attention", "o_proj",
            _t(sd[f"{p}.self_attention.dense.weight"]).T)
        put(f"{p}.mlp.dense_h_to_4h", "kernel",
            _t(sd[f"{p}.mlp.dense_h_to_4h.weight"]).T)
        put(f"{p}.mlp.dense_4h_to_h", "kernel",
            _t(sd[f"{p}.mlp.dense_4h_to_h.weight"]).T)
        if cfg.bias:
            put(f"{p}.self_attention", "qkv_bias",
                _t(sd[f"{p}.self_attention.query_key_value.bias"]).reshape(
                    cfg.kv_heads,
                    cfg.num_attention_heads // cfg.kv_heads + 2, cfg.hdim))
            put(f"{p}.self_attention", "o_bias",
                _t(sd[f"{p}.self_attention.dense.bias"]))
            put(f"{p}.mlp.dense_h_to_4h", "bias",
                _t(sd[f"{p}.mlp.dense_h_to_4h.bias"]))
            put(f"{p}.mlp.dense_4h_to_h", "bias",
                _t(sd[f"{p}.mlp.dense_4h_to_h.bias"]))
    ln("transformer.ln_f", "transformer.ln_f")
    lm = sd.get("lm_head.weight", sd["transformer.word_embeddings.weight"])
    put("lm_head", "kernel", _t(lm).T)
    return params


def convert_mpt_state_dict(sd, cfg: ServeModelConfig, dtype=jnp.float32):
    params: Dict[str, Dict[str, jax.Array]] = {}

    def put(node, pname, arr):
        params.setdefault(node, {})[pname] = jnp.asarray(arr, dtype)

    put("transformer.wte", "weight", _t(sd["transformer.wte.weight"]))
    for i in range(cfg.num_hidden_layers):
        p = f"transformer.blocks.{i}"
        put(f"{p}.norm_1", "gamma", _t(sd[f"{p}.norm_1.weight"]))
        put(f"{p}.norm_2", "gamma", _t(sd[f"{p}.norm_2.weight"]))
        put(f"{p}.attn", "qkv", fuse_qkv_rows(sd[f"{p}.attn.Wqkv.weight"], cfg))
        put(f"{p}.attn", "o_proj", _t(sd[f"{p}.attn.out_proj.weight"]).T)
        put(f"{p}.ffn.up_proj", "kernel", _t(sd[f"{p}.ffn.up_proj.weight"]).T)
        put(f"{p}.ffn.down_proj", "kernel",
            _t(sd[f"{p}.ffn.down_proj.weight"]).T)
    put("transformer.norm_f", "gamma", _t(sd["transformer.norm_f.weight"]))
    lm = sd.get("lm_head.weight", sd["transformer.wte.weight"])
    put("lm_head", "kernel", _t(lm).T)
    return params


def convert_starcoder_state_dict(sd, cfg: ServeModelConfig, dtype=jnp.float32):
    params: Dict[str, Dict[str, jax.Array]] = {}

    def put(node, pname, arr):
        params.setdefault(node, {})[pname] = jnp.asarray(arr, dtype)

    def ln(node, key):
        put(node, "gamma", _t(sd[f"{key}.weight"]))
        put(node, "beta", _t(sd[f"{key}.bias"]))

    put("transformer.wte", "weight", _t(sd["transformer.wte.weight"]))
    put("transformer.wpe", "weight", _t(sd["transformer.wpe.weight"]))
    for i in range(cfg.num_hidden_layers):
        p = f"transformer.h.{i}"
        ln(f"{p}.ln_1", f"{p}.ln_1")
        ln(f"{p}.ln_2", f"{p}.ln_2")
        put(f"{p}.attn", "qkv", fuse_qkv_rows(sd[f"{p}.attn.c_attn.weight"], cfg))
        put(f"{p}.attn", "qkv_bias",
            fuse_qkv_rows_bias(sd[f"{p}.attn.c_attn.bias"], cfg))
        put(f"{p}.attn", "o_proj", _t(sd[f"{p}.attn.c_proj.weight"]).T)
        put(f"{p}.attn", "o_bias", _t(sd[f"{p}.attn.c_proj.bias"]))
        for fc in ("c_fc", "c_proj"):
            put(f"{p}.mlp.{fc}", "kernel", _t(sd[f"{p}.mlp.{fc}.weight"]).T)
            put(f"{p}.mlp.{fc}", "bias", _t(sd[f"{p}.mlp.{fc}.bias"]))
    ln("transformer.ln_f", "transformer.ln_f")
    lm = sd.get("lm_head.weight", sd["transformer.wte.weight"])
    put("lm_head", "kernel", _t(lm).T)
    return params


CONVERTERS = {
    "llama": convert_llama_state_dict,
    "opt": convert_opt_state_dict,
    "falcon": convert_falcon_state_dict,
    "mpt": convert_mpt_state_dict,
    "gpt_bigcode": convert_starcoder_state_dict,
}


def convert_state_dict(sd, cfg: ServeModelConfig, dtype=jnp.float32):
    if cfg.model_type not in CONVERTERS:
        raise ValueError(
            f"no weight converter for {cfg.model_type!r}; "
            f"known: {sorted(CONVERTERS)}"
        )
    return CONVERTERS[cfg.model_type](sd, cfg, dtype)


def load_hf_model(name_or_path: str):
    """Load a local HF checkpoint (config + weights + tokenizer if present).

    Returns (state_dict, ServeModelConfig, tokenizer_or_None).  Network
    download is NOT attempted (``local_files_only=True``) — ship checkpoints
    to disk first, as the reference's weight-export flow does.
    """
    import transformers

    hf_cfg = transformers.AutoConfig.from_pretrained(
        name_or_path, local_files_only=True
    )
    model = transformers.AutoModelForCausalLM.from_pretrained(
        name_or_path, local_files_only=True, torch_dtype="float32"
    )
    tok = None
    try:
        tok = transformers.AutoTokenizer.from_pretrained(
            name_or_path, local_files_only=True
        )
    except Exception:
        pass
    return model.state_dict(), ServeModelConfig.from_hf_config(hf_cfg), tok


def place_params(params, plan):
    """device_put converted params according to the plan's shardings."""
    mesh = plan.mesh
    if mesh.size == 1:
        return params
    out = {}
    for node, sub in params.items():
        shs = plan.param_shardings.get(node, {})
        out[node] = {
            k: jax.device_put(v, shs[k].named_sharding(mesh))
            if k in shs
            else v
            for k, v in sub.items()
        }
    return out
