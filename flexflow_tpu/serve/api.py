"""User-facing serving API: the ``LLM`` / ``SSM`` classes.

Reference: ``python/flexflow/serve/__init__.py`` + ``serve/serve.py`` — the
``LLM(model_name).compile(...); llm.generate(prompts)`` flow, with an optional
list of SSMs enabling SpecInfer.  Here weights come from a local HF checkpoint
(or an in-memory transformers model / raw state dict); the tokenizer is the HF
tokenizer when available, otherwise prompts are token-id lists.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax

from ..config import FFConfig
from ..model import FFModel
from ..parallel.mesh import make_mesh
from .inference_manager import InferenceManager
from .models.base import ServeModelConfig, build_model
from .request_manager import GenerationConfig, RequestManager
from .spec_infer import SpecInferManager
from .weights import convert_state_dict, load_hf_model, place_params


class LLM:
    def __init__(
        self,
        model: Any,
        tokenizer: Any = None,
        config: Optional[ServeModelConfig] = None,
    ):
        """``model``: local HF checkpoint path, a transformers model instance,
        a raw HF state dict (requires ``config``), or a ServeModelConfig for
        random-weight serving."""
        self.tokenizer = tokenizer
        self._sd = None
        if isinstance(model, str):
            self._sd, self.config, tok = load_hf_model(model)
            self.tokenizer = tokenizer or tok
        elif isinstance(model, ServeModelConfig):
            self.config = model
        elif isinstance(model, dict):
            if config is None:
                raise ValueError("raw state dict needs an explicit config")
            self._sd, self.config = model, config
        else:  # transformers PreTrainedModel
            self._sd = model.state_dict()
            self.config = config or ServeModelConfig.from_hf_config(model.config)
        self.im: Optional[InferenceManager] = None
        self.rm = None

    # ------------------------------------------------------------------
    def compile(
        self,
        max_requests: int = 8,
        max_tokens_per_batch: int = 64,
        max_seq_len: int = 512,
        tp: int = 1,
        max_spec_tokens: int = 0,
        topk: int = 0,
        generation_config: Optional[GenerationConfig] = None,
        ssms: Sequence["LLM"] = (),
        spec_width: int = 2,
        spec_depth: int = 3,
        dtype=None,
        devices=None,
        kv_dtype=None,
        kv_page_size=None,
        telemetry=None,
        resilience=None,
        fault_injector=None,
        plan_health=None,
        profiler=None,
        slo=None,
        brownout=None,
    ) -> "LLM":
        """``kv_dtype="int8"`` stores the KV caches int8 with fused
        in-kernel dequant (see ``InferenceManager``) — halves decode KV
        bandwidth and doubles context/batch capacity per HBM byte, which is
        what makes the full-depth Llama-2-7B shape (int8 weights via
        ``quantize_int8`` + int8 KV) admissible on one 16 GB chip.

        ``kv_page_size`` enables the paged KV cache with copy-on-write
        prefix sharing (``serve/kv_paged.py``; None = slot-contiguous).

        ``telemetry`` / ``resilience`` / ``fault_injector`` thread the
        observability handle and the resilient-serving policy layer
        (admission control, deadlines/cancellation, preemption-and-
        recompute, dispatch retry — see ``serve/resilience.py``) into the
        RequestManager.  ``plan_health`` attaches a
        :class:`~flexflow_tpu.obs.PlanHealthMonitor` the serve loops poll
        (SLO / prediction-error / workload-drift checks emitting
        ``replan_recommended``; pair it with :meth:`attach_migration` to
        ACT on the recommendation via a live plan switch — see
        :meth:`health`).  ``profiler`` attaches a
        :class:`~flexflow_tpu.obs.StepProfiler` (step-level cost
        attribution: per-phase time budgets + deterministic work
        counters; bit-identical outputs with it on or off).
        ``slo`` attaches an :class:`~flexflow_tpu.serve.slo.SLOPolicy`
        (per-request ``slo_class`` lanes: priority bands, per-class
        bounded queues and TTFT/TPOT targets, reserved KV headroom);
        ``brownout`` a :class:`~flexflow_tpu.serve.slo.
        BrownoutController` walking the graceful-degradation ladder
        under overload (defer -> degrade -> shed batch-class work, with
        hysteresis) — see ``serve/slo.py``."""
        devices = devices if devices is not None else jax.devices()[:tp]
        mesh = make_mesh({"tp": tp}, devices)
        ff = FFModel(FFConfig(), mesh=mesh)
        logits = build_model(ff, self.config, max_tokens_per_batch)
        if ssms and not max_spec_tokens:
            max_spec_tokens = 1 + spec_width * spec_depth
        self.im = InferenceManager(
            ff,
            max_requests=max_requests,
            max_tokens_per_batch=max_tokens_per_batch,
            max_seq_len=max_seq_len,
            max_spec_tokens=max_spec_tokens,
            topk=topk,
            outputs=logits,
            kv_dtype=kv_dtype,
            kv_page_size=kv_page_size,
        )
        if self._sd is not None:
            params = convert_state_dict(self._sd, self.config, dtype or "float32")
            params = place_params(params, self.im.plan)
            self.im.init_operators_inference(params=params)
        else:
            self.im.init_operators_inference(dtype=dtype)

        gen = generation_config or GenerationConfig()
        if gen.eos_token_id is None and self.config.eos_token_id is not None:
            gen = dataclass_replace(gen, eos_token_id=self.config.eos_token_id)
        if ssms:
            ssm = ssms[0]
            if ssm.im is None:
                ssm.compile(
                    max_requests=max_requests,
                    max_tokens_per_batch=max_tokens_per_batch,
                    max_seq_len=max_seq_len,
                    max_spec_tokens=max_spec_tokens,
                    topk=max(spec_width, 1),
                    devices=devices[:1],
                    tp=1,
                    kv_dtype=kv_dtype,
                    kv_page_size=kv_page_size,
                )
            self.rm = SpecInferManager(
                self.im, ssm.im, gen, width=spec_width, depth=spec_depth,
                telemetry=telemetry, resilience=resilience,
                fault_injector=fault_injector, plan_health=plan_health,
                profiler=profiler, slo=slo, brownout=brownout,
            )
        else:
            self.rm = RequestManager(self.im, gen, telemetry=telemetry,
                                     resilience=resilience,
                                     fault_injector=fault_injector,
                                     plan_health=plan_health,
                                     profiler=profiler, slo=slo,
                                     brownout=brownout)
        return self

    def health(self):
        """Run (and return) one plan-health check NOW: live TTFT/TPOT vs
        the executing plan's predictions and SLO targets, plus workload
        drift vs the planned-for profile.  None when no monitor was
        attached at :meth:`compile` time.  A ``replan_recommended``
        report names a candidate plan; with a
        :class:`~flexflow_tpu.serve.migration.MigrationController`
        attached (:meth:`attach_migration`) the recommendation is ACTED
        on — a live drain/rebuild/readmit plan switch over the r9
        preemption-and-recompute path, with rollback — otherwise it is
        report-only."""
        if self.rm is None or self.rm.plan_health is None:
            return None
        return self.rm.plan_health.check()

    def attach_migration(self, build_manager, config=None, plan=None):
        """Attach a live-migration controller to the serving session
        (``serve/migration.py``): it consumes the plan-health monitor's
        ``replan_recommended`` (and operator
        :meth:`~flexflow_tpu.serve.migration.MigrationController.
        request_migration` calls) and executes the plan switch at a serve
        tick boundary — drain (admission closed + r9 preemption), rebuild
        (``build_manager(candidate)`` constructs the new deployment),
        readmit (rids preserved, token streams bit-identical), with
        rollback to the incumbent on failure.  ``self.rm``/``self.im``
        follow the active deployment across switches.  Returns the
        controller."""
        assert self.rm is not None, "call compile() first"
        from .migration import MigrationController

        def on_switch(new_rm):
            self.rm = new_rm
            self.im = new_rm.im

        return MigrationController(self.rm, build_manager, plan=plan,
                                   config=config, on_switch=on_switch)

    @staticmethod
    def fleet(llms, **kwargs):
        """Build a fault-tolerant :class:`~flexflow_tpu.serve.fleet.
        FleetRouter` over compiled ``LLM`` instances (each one replica
        deployment — for bit-identity with a single-replica run they
        must share weights and GenerationConfig).  Keyword args forward
        to the router (``gen``/``telemetry``/``resilience``/
        ``fault_injector``/``clock``/``profiler``/``config``); the
        router then owns the shared admission queue, telemetry-driven
        least-load dispatch, the per-replica health state machine with
        bit-identical failover, and rolling plan migration — see
        ``serve/fleet.py``."""
        from .fleet import FleetRouter

        rms = []
        for llm in llms:
            assert llm.rm is not None, "compile() every fleet member first"
            rms.append(llm.rm)
        return FleetRouter(rms, **kwargs)

    def memory_report(self):
        """The deployment's byte-side view NOW: the
        :class:`~flexflow_tpu.serve.kv_allocator.KVAllocator`'s live
        occupancy/headroom/fragmentation snapshot plus, when a telemetry
        handle was attached at :meth:`compile` time, the memory ledger's
        predicted-vs-allocated HBM reconciliation (see ``obs/memory.py``).
        None before :meth:`compile`."""
        if self.im is None:
            return None
        # through the manager's view, not the target allocator directly —
        # a spec deployment's manager combines target + draft, matching
        # the exported gauges
        report = {"kv": (self.rm.kv_snapshot() if self.rm is not None
                         else self.im.kv.snapshot())}
        tel = getattr(self.rm, "telemetry", None) if self.rm else None
        if tel is not None and getattr(tel, "enabled", False):
            report["ledger"] = tel.memory.report()
        return report

    # ------------------------------------------------------------------
    def generate(
        self,
        prompts: Union[str, Sequence],
        max_new_tokens: Optional[int] = None,
    ):
        """Strings in → strings out (needs a tokenizer); id lists in → id
        lists out."""
        assert self.rm is not None, "call compile() first"
        if not isinstance(prompts, str) and not len(prompts):
            return []
        single = isinstance(prompts, str) or isinstance(prompts[0], int)
        if single:
            prompts = [prompts]
        texty = isinstance(prompts[0], str)
        if texty:
            if self.tokenizer is None:
                raise ValueError("string prompts require a tokenizer")
            ids = [self.tokenizer.encode(p) for p in prompts]
        else:
            ids = [list(p) for p in prompts]
        outs = self.rm.generate(ids, max_new_tokens)
        if texty:
            outs = [self.tokenizer.decode(o) for o in outs]
        return outs[0] if single else outs


class SSM(LLM):
    """Parity alias for the reference's draft-model class."""


def dataclass_replace(obj, **kw):
    import dataclasses

    return dataclasses.replace(obj, **kw)
