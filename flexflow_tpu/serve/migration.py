"""Live plan migration: ACT on ``replan_recommended``.

Through r14 the observe→calibrate→re-plan loop ended at a recommendation:
:class:`~flexflow_tpu.obs.plan_health.PlanHealthMonitor` re-searches on
the drifted workload profile and emits ``replan_recommended`` with a
candidate plan, and the acceptance-drift check recommends spec flips —
but nothing ever migrated.  This module closes that gap: a
:class:`MigrationController` attached to the serving
:class:`~flexflow_tpu.serve.request_manager.RequestManager` consumes the
recommendation (or an operator's explicit :meth:`request_migration`) and
executes a FULL live plan switch without losing a single request:

* **drain** — admission to engine slots closes (requests keep enqueueing;
  nothing new takes a slot), a bounded GRACE window lets near-finished
  requests complete, a speculative manager's pending commits flush, and
  every still-running request is preempted through the r9
  recompute path (``RequestManager.preempt``: slot + KV release
  immediately, the request re-enters the pending queue carrying its
  ``prompt + generated`` recompute feed);
* **rebuild** — the candidate deployment is constructed via the caller's
  ``build_manager`` hook, reusing the ordinary
  :class:`~.inference_manager.InferenceManager` /
  :class:`~.pp.PipelinedInferenceManager` /
  :class:`~.spec_infer.SpecInferManager` constructors — any change of
  tp×pp×m×kv_dtype×paged×spec is just a different constructor call — with
  KV reacquired through a fresh
  :class:`~.kv_allocator.KVAllocator`/:class:`~.kv_paged.PagedKVAllocator`;
* **readmit** — the drained requests re-register on the candidate manager
  with their ORIGINAL rids and sample-key state.  Token streams are
  bit-identical across the switch for greedy AND seeded sampling because
  recovery is the same recompute path preemption already uses: KV is
  recomputed from ``prompt + generated`` and every sample keys on the r9
  ``(rid, token_index)`` fold, which the preserved rid carries across
  managers (pinned by tests/test_migration.py for tp1→pp2,
  contiguous→paged, and spec-on→spec-off);
* **commit / teardown** — the incumbent releases its cache ownership
  (:meth:`KVAllocator.teardown`, refcount no-leak asserted by the chaos
  tests) and the successor manager takes over the serve loop in place
  (the loops hand off mid-run — see ``RequestManager._maybe_migrate``).

**Robustness is the headline.**  Every phase consults the deployment's
seeded :class:`~.resilience.FaultInjector` (sites ``migration_drain`` /
``migration_rebuild`` / ``migration_readmit``) and retries transient
faults with the same exponential-backoff policy dispatches use.  A
rebuild or readmit that fails past the retry budget — or any
non-transient constructor/validation error — ROLLS BACK: the candidate's
buffers (if any) are torn down, admission reopens on the incumbent, and
the drained requests readmit THERE instead, so every rid still reaches a
terminal outcome (``migration_rolled_back`` is emitted, schema-validated).
A cooldown window plus the monitor-side ``replan_cooldown_ticks`` knob
prevent plan flapping when two candidates oscillate.

**Spec flip fast path.**  When the candidate differs from the incumbent
ONLY in the ``_spec_w{w}d{d}`` suffix (the r14 acceptance-drift
recommendation) and the incumbent is a SpecInferManager with the same
tree shape, no rebuild is needed: the controller flips ``set_spec_mode``
on every live request and the manager's ``default_spec_mode`` for future
admissions — the automatic fleet-wide flip the ROADMAP's spec item named
as an operator action until now.

Everything here is host-side orchestration over existing manager
primitives; no migration decision is ever traced into a jitted program.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional, Tuple

from ..obs.telemetry import telemetry_or_null
from .request_manager import (
    RequestManager,
    RequestStatus,
    TERMINAL_STATUSES,
)
from .resilience import RetryPolicy, TransientServeError

_SPEC_SUFFIX = re.compile(r"_spec_w(\d+)d(\d+)$")

# requests currently occupying an engine slot (the drain's preempt set)
_RUNNING = (RequestStatus.PREFILLING, RequestStatus.DECODING)


def base_plan_key(key: str) -> str:
    """A plan key with its ``_spec_w{w}d{d}`` suffix stripped — two keys
    with equal bases name the same tp×pp×m shape and differ only in the
    speculation mode."""
    return _SPEC_SUFFIX.sub("", key or "")


def spec_shape(key: str) -> Optional[Tuple[int, int]]:
    """(width, depth) of a ``_spec_w{w}d{d}`` plan key, None if non-spec."""
    m = _SPEC_SUFFIX.search(key or "")
    return (int(m.group(1)), int(m.group(2))) if m else None


class MigrationRollback(Exception):
    """A non-transient migration failure: roll back to the incumbent
    (never retried — retry is for :class:`TransientServeError` only)."""


def build_deployment(built, gen, telemetry=None, resilience=None,
                     fault_injector=None, clock=None, profiler=None,
                     spec: Optional[Dict] = None, plan_key: str = "",
                     default_shape: Optional[Tuple[int, int]] = None,
                     slo=None, brownout=None):
    """Wrap a ``build_manager``-style result into a serving manager.

    THE one wrapping contract shared by :class:`MigrationController`'s
    rebuild phase and the fleet router's replica construction
    (``serve/fleet.py``) — a deployment is a ready
    :class:`~.request_manager.RequestManager` (returned as-is), a single
    InferenceManager-like object (wrapped in a ``RequestManager``), or an
    ``(llm_im, ssm_im)`` pair (wrapped in a
    :class:`~.spec_infer.SpecInferManager`, tree shape resolved PER FIELD
    from the ``spec`` dict, then the ``plan_key``'s ``_spec_w{w}d{d}``
    suffix, then ``default_shape``).  Sharing gen/telemetry/resilience/
    injector/clock/profiler here is what makes seeded bit-identity hold
    by construction across managers — every wrapped deployment samples
    through the same (seed, rid, token_index) schedule.
    """
    if isinstance(built, RequestManager):
        return built
    if isinstance(built, (tuple, list)):
        from .spec_infer import SpecInferManager

        llm_im, ssm_im = built
        shape = dict(spec or {})
        key_wd = spec_shape(plan_key)
        base_wd = key_wd or default_shape or (2, 3)
        width = shape.get("width") or base_wd[0]
        depth = shape.get("depth") or base_wd[1]
        return SpecInferManager(
            llm_im, ssm_im, gen, width=width, depth=depth,
            telemetry=telemetry, resilience=resilience,
            fault_injector=fault_injector, clock=clock, profiler=profiler,
            slo=slo, brownout=brownout)
    return RequestManager(built, gen, telemetry=telemetry,
                          resilience=resilience,
                          fault_injector=fault_injector, clock=clock,
                          profiler=profiler, slo=slo, brownout=brownout)


@dataclasses.dataclass
class MigrationConfig:
    """Policy knobs for the live-migration controller.

    * ``auto``: consume the attached
      :class:`~flexflow_tpu.obs.plan_health.PlanHealthMonitor`'s
      ``replan_recommended`` automatically (False = operator-driven
      :meth:`MigrationController.request_migration` only).
    * ``cooldown_ticks``: serve ticks after a completed OR rolled-back
      migration during which new auto-recommendations are ignored — the
      controller-side hysteresis against plan flapping (the monitor has
      its own emission-side ``replan_cooldown_ticks``; both guards
      compose).  Manual ``request_migration`` bypasses it.
    * ``defer_ticks``: ticks a staged migration waits (admission still
      OPEN) before the drain begins — lets an operator schedule "migrate
      in ~N ticks" and gives tests a deterministic mid-flight window.
    * ``drain_grace_ticks``: admission-closed ticks the incumbent keeps
      serving before the survivors are force-preempted — a request one
      token from finishing completes instead of paying a full recompute.
      Each grace tick counts toward the ``migration_downtime_ticks``
      gauge ("ticks with admission closed").
    * ``spec_flip_fast_path``: recognize candidates differing only in the
      spec suffix and flip ``set_spec_mode`` instead of rebuilding.
    * ``retry``: backoff policy for transient faults inside the migration
      phases; None uses the manager's own ``res.retry``.
    """

    auto: bool = True
    cooldown_ticks: int = 64
    defer_ticks: int = 0
    drain_grace_ticks: int = 2
    spec_flip_fast_path: bool = True
    retry: Optional[RetryPolicy] = None


class MigrationController:
    """Executes live plan switches for one serving session.

    ``manager``: the incumbent (attaches as ``manager.migration``, the
    hook the serve loops poll at every tick boundary).
    ``build_manager``: ``candidate_plan_dict -> deployment`` — the rebuild
    hook.  It may return a ready :class:`RequestManager` (the builder
    then owns gen/telemetry wiring — the controller still transplants
    requests and syncs the clock), a single InferenceManager-like object
    (wrapped in a ``RequestManager`` sharing the incumbent's
    GenerationConfig/telemetry/resilience/injector/clock, so seeded
    bit-identity holds by construction), or an ``(llm_im, ssm_im)`` pair
    (wrapped in a :class:`~.spec_infer.SpecInferManager`; tree
    width/depth from the candidate's ``spec`` dict / plan-key suffix,
    falling back to the incumbent's).  It must build AROUND fresh
    InferenceManagers — reusing the incumbent's ``im`` is invalid (its
    buffers are torn down on commit).
    ``plan``: the incumbent's plan dict (default: the attached
    plan-health monitor's, else inferred from the manager).
    ``on_switch``: optional callback ``new_manager -> None`` fired after
    a successful commit — the hook ``LLM.attach_migration`` uses to keep
    ``llm.rm``/``llm.im`` pointing at the active deployment.

    ``controller.rm`` is always the ACTIVE manager; ``history`` records
    every completed/rolled-back migration.
    """

    def __init__(self, manager: RequestManager,
                 build_manager: Callable[[Dict], object],
                 plan: Optional[Dict] = None,
                 config: Optional[MigrationConfig] = None,
                 on_switch: Optional[Callable] = None):
        self.rm = manager
        self.build_manager = build_manager
        self.config = config or MigrationConfig()
        self.on_switch = on_switch
        self.plan = dict(plan) if plan is not None else self._infer_plan(manager)
        self.history: List[Dict] = []
        self._staged: Optional[Dict] = None
        self._ticks = 0
        self._cooldown_until = 0
        if getattr(manager, "migration", None) is not None:
            # silently replacing an attached controller would orphan it:
            # its staged migrations would never execute (the manager polls
            # exactly one controller per tick boundary)
            raise ValueError(
                "manager already has a MigrationController attached")
        manager.migration = self

    # ------------------------------------------------------------------
    @staticmethod
    def _infer_plan(rm: RequestManager) -> Dict:
        mon = getattr(rm, "plan_health", None)
        if mon is not None and getattr(mon, "plan", None):
            return dict(mon.plan)
        key = getattr(rm.im, "plan_key", "?")
        if hasattr(rm, "ssm") and getattr(rm, "default_spec_mode", False):
            key += f"_spec_w{rm.width}d{rm.depth}"
        return {"plan_key": key}

    @property
    def telemetry(self):
        return telemetry_or_null(getattr(self.rm, "telemetry", None))

    def _has_running(self, rm: RequestManager) -> bool:
        return any(r.status in _RUNNING for r in rm._active())

    def _live_rids(self, rm: RequestManager) -> List[int]:
        """Non-terminal rids, pending-queue order first then slotted —
        after a full drain this is exactly the pending queue."""
        slotted = [r.rid for r in rm._active()
                   if r.status not in TERMINAL_STATUSES]
        return list(rm.pending) + [r for r in slotted if r not in rm.pending]

    # ------------------------------------------------------------------
    # staging
    # ------------------------------------------------------------------
    def request_migration(self, candidate, reasons=(), *,
                          defer_ticks: Optional[int] = None,
                          drain_grace_ticks: Optional[int] = None) -> None:
        """Stage a migration to ``candidate`` (a plan dict from
        ``search_serve_plan``, or a bare plan-key string).  Executes at a
        serve-tick boundary: ``defer_ticks`` of normal serving, then the
        admission-closed drain window, then the switch.  Manual staging
        bypasses the auto-path cooldown; one migration at a time."""
        if self._staged is not None:
            raise ValueError("a migration is already staged/in progress")
        if isinstance(candidate, str):
            candidate = {"plan_key": candidate}
        cfg = self.config
        grace = cfg.drain_grace_ticks if drain_grace_ticks is None \
            else int(drain_grace_ticks)
        if cfg.spec_flip_fast_path and self._spec_flip_applicable(
                self.rm, self.plan.get("plan_key", "?"),
                candidate.get("plan_key", "?")):
            # a flip preempts nothing: paying an admission-closed grace
            # window for it would be pure downtime
            grace = 0
        self._staged = {
            "candidate": dict(candidate),
            "reasons": list(reasons),
            "defer_left": cfg.defer_ticks if defer_ticks is None
            else int(defer_ticks),
            "grace_left": grace,
            "downtime_ticks": 0,
            "t_closed": None,
        }

    def _poll(self, rm: RequestManager) -> None:
        """Consume a fresh plan-health recommendation (auto path)."""
        if not self.config.auto:
            return
        mon = getattr(rm, "plan_health", None)
        rec = getattr(mon, "recommendation", None) if mon is not None else None
        if not rec:
            return
        if self._ticks < self._cooldown_until:
            return
        cand = rec.get("candidate_plan") or {"plan_key": rec.get("candidate")}
        if cand.get("plan_key") == self.plan.get("plan_key"):
            mon.recommendation = None  # incumbent reaffirmed: nothing to do
            return
        self.request_migration(cand, reasons=rec.get("reasons", ()))
        # consumed: the monitor may re-recommend later excursions fresh
        mon.recommendation = None

    # ------------------------------------------------------------------
    # the tick-boundary hook (RequestManager._maybe_migrate drives this)
    # ------------------------------------------------------------------
    def tick(self, rm: RequestManager, idle: bool = False):
        """One tick-boundary slot.  Returns the manager the serve loop
        should continue on — the successor after a completed switch, or
        ``rm`` itself (staging / grace / rollback / nothing to do)."""
        if rm is not self.rm:
            return rm  # a retired manager's loop unwinding; ignore
        if not idle:
            self._ticks += 1
        st = self._staged
        if st is None:
            if idle:
                return rm
            self._poll(rm)
            st = self._staged
            if st is None:
                return rm
        if idle:
            # the loop drained: execute now — the zero-preemption window
            # (defer/grace exist to bound in-flight disruption; idle has
            # none).  Close admission for the switch itself.
            if st["t_closed"] is None:
                rm.admission_closed = True
                st["t_closed"] = rm.clock()
            return self._execute(rm)
        if st["defer_left"] > 0:
            st["defer_left"] -= 1
            return rm
        if st["t_closed"] is None:
            rm.admission_closed = True
            st["t_closed"] = rm.clock()
        else:
            st["downtime_ticks"] += 1  # a serve tick ran admission-closed
        if st["grace_left"] > 0 and self._has_running(rm):
            st["grace_left"] -= 1
            return rm
        return self._execute(rm)

    # ------------------------------------------------------------------
    # guarded phases
    # ------------------------------------------------------------------
    def _phase(self, rm: RequestManager, site: str, fn):
        """Run one migration phase under the seeded fault injector and the
        retry policy.  Returns ``(True, value)`` or ``(False, reason)`` —
        transient faults retry with backoff; :class:`MigrationRollback`
        (and any other non-transient error) fails the phase immediately."""
        pol = self.config.retry or rm.res.retry
        tel = self.telemetry
        attempt = 0
        while True:
            try:
                if rm.injector is not None:
                    rm.injector.maybe_fail(site)
                return True, fn()
            except TransientServeError as e:
                if tel.enabled:
                    tel.fault_observed(site, detail=str(e))
                if attempt >= pol.max_retries:
                    return False, f"{site}: retries exhausted ({e})"
                attempt += 1
                delay = pol.backoff(attempt)
                if tel.enabled:
                    tel.dispatch_retry(site, attempt=attempt, backoff_s=delay)
                if delay > 0:
                    rm._sleep(delay)
            except MigrationRollback as e:
                return False, f"{site}: {e}"
            except Exception as e:  # constructor/validation failures
                return False, f"{site}: {type(e).__name__}: {e}"

    # ------------------------------------------------------------------
    # the switch
    # ------------------------------------------------------------------
    def _execute(self, rm: RequestManager):
        st, self._staged = self._staged, None
        cfg = self.config
        tel = self.telemetry
        candidate = st["candidate"]
        cand_key = candidate.get("plan_key", "?")
        inc_key = self.plan.get("plan_key", "?")
        reasons = ",".join(st["reasons"])
        if tel.enabled:
            tel.migration_started(inc_key, cand_key, reasons=reasons)

        # ---- spec flip fast path (no drain, no rebuild) ----------------
        if (cfg.spec_flip_fast_path
                and self._spec_flip_applicable(rm, inc_key, cand_key)):
            spec_on = spec_shape(cand_key) is not None
            flipped = 0
            for rid in self._live_rids(rm):
                if rm.set_spec_mode(rid, spec_on):
                    flipped += 1
            rm.default_spec_mode = spec_on
            return self._commit(rm, rm, st, candidate, mode="spec_flip",
                                preempted=0, flipped=flipped)

        # ---- drain -----------------------------------------------------
        ok, drained = self._phase(rm, "migration_drain",
                                  lambda: self._drain(rm))
        if not ok:
            return self._rollback(rm, st, candidate, "drain", drained)
        # ---- rebuild ---------------------------------------------------
        ok, new_rm = self._phase(rm, "migration_rebuild",
                                 lambda: self._build(rm, candidate))
        if not ok:
            return self._rollback(rm, st, candidate, "rebuild", new_rm)
        # ---- readmit ---------------------------------------------------
        ok, moved = self._phase(
            rm, "migration_readmit",
            lambda: self._readmit(rm, new_rm, candidate))
        if not ok:
            return self._rollback(rm, st, candidate, "readmit", moved,
                                  new_rm=new_rm)
        # ---- commit: tear down the incumbent, swap the active manager --
        return self._commit(rm, new_rm, st, candidate, mode="rebuild",
                            preempted=drained)

    def _spec_flip_applicable(self, rm, inc_key: str, cand_key: str) -> bool:
        if cand_key == inc_key or base_plan_key(cand_key) \
                != base_plan_key(inc_key):
            return False
        if not hasattr(rm, "ssm"):  # needs a live draft model to flip onto
            return False
        shape = spec_shape(cand_key)
        # flipping OFF works for any shape; flipping ON must match the
        # manager's compiled tree capacity
        return shape is None or shape == (rm.width, rm.depth)

    def _drain(self, rm: RequestManager) -> int:
        """Flush pending spec commits, then preempt every still-running
        request through the r9 recompute path.  Idempotent — a retried
        drain re-preempts only what is still slotted."""
        flush = getattr(rm, "flush_pending_commits", None)
        if flush is not None:
            # a flush failure already requeued/failed its affected rows
            # via the manager's own retry guard; the drain proceeds
            flush()
        count = 0
        for req in list(rm._active()):
            if req.status in _RUNNING:
                rm.preempt(req.rid)
                count += 1
        return count

    def _build(self, rm: RequestManager, candidate: Dict):
        """Construct the candidate deployment (see class docstring for
        the ``build_manager`` contract)."""
        built = self.build_manager(candidate)
        if built is None:
            raise MigrationRollback("build_manager returned None")
        # the freshness check runs BEFORE any manager wraps the result:
        # wrapping the incumbent's own InferenceManager would reset its
        # attribution, and tearing the "candidate" down on rollback would
        # destroy the buffers the incumbent still serves from
        incumbent_ims = {id(x) for x in (rm.im, getattr(rm, "ssm", None))
                         if x is not None}
        parts = (built,) if not isinstance(built, (tuple, list)) else built
        for part in parts:
            for x in (part, getattr(part, "im", None),
                      getattr(part, "ssm", None)):
                if x is not None and id(x) in incumbent_ims:
                    raise MigrationRollback(
                        "build_manager must construct a FRESH deployment "
                        "(the incumbent's buffers are torn down on commit)")
        tel = rm.telemetry if rm.telemetry.enabled else None
        # the StepProfiler handle crosses the switch like telemetry: rids
        # are preserved, so the per-request work attribution keeps
        # accumulating in ONE table across managers (and the successor's
        # jitted programs join the recompile poll via install()).  Tree
        # shape for a spec pair: candidate's spec dict, then the plan-key
        # suffix, then the incumbent's shape (build_deployment resolves
        # PER FIELD so a partial spec dict still fills in sanely).
        prof = rm.profiler if getattr(rm, "profiler", None) is not None \
            and rm.profiler.enabled else None
        return build_deployment(
            built, rm.gen, telemetry=tel, resilience=rm.res,
            fault_injector=rm.injector, clock=rm.clock, profiler=prof,
            spec=candidate.get("spec"),
            plan_key=candidate.get("plan_key", ""),
            default_shape=((rm.width, rm.depth) if hasattr(rm, "width")
                           else None),
            # the lane policy + ladder cross the switch like the
            # telemetry handle — a migration must not silently
            # deactivate SLO lanes on the successor
            slo=getattr(rm, "slo", None),
            brownout=getattr(rm, "brownout", None))

    def _readmit(self, rm: RequestManager, new_rm: RequestManager,
                 candidate: Dict) -> int:
        """Transplant every request onto the candidate manager, preserving
        rids (the sample-key fold) and recompute feeds.  Non-destructive
        for the incumbent until :meth:`_commit` — a readmit failure rolls
        back with the incumbent's queue intact."""
        new_rm.admission_closed = True  # until commit reopens it
        new_rm.clock = rm.clock  # deadlines stay on one time base
        # decode pacing crosses the switch: an operator who pinned
        # tick-paced decode (chain_segments off) or a custom stretch
        # bound must not silently revert to the defaults mid-session
        new_rm.chain_segments = rm.chain_segments
        new_rm.scan_chunk = rm.scan_chunk
        new_rm.lifecycle_quantum = rm.lifecycle_quantum
        spec_on = (spec_shape(candidate.get("plan_key", "")) is not None
                   or bool(candidate.get("spec")))
        is_spec_mgr = hasattr(new_rm, "ssm")
        live = self._live_rids(rm)
        converted = {}
        for rid in live:
            old = rm.requests[rid]
            req = new_rm.request_cls(rid, list(old.prompt),
                                     old.max_new_tokens)
            req.trace_id = old.trace_id
            req.priority = old.priority
            req.deadline_s = old.deadline_s
            req.cancel_requested = old.cancel_requested
            req.preemptions = old.preemptions
            req.requeues = old.requeues
            req.kv_bytes = old.kv_bytes
            # SLO-lane identity crosses the switch: losing the class
            # would resolve a latency_critical request to the DEFAULT
            # (degradable) lane on the successor and let a brownout
            # shed it — violating its shed_policy="never" contract
            req.slo_class = old.slo_class
            req.deferred_ticks = old.deferred_ticks
            req.generated = list(old.generated)
            req.prefill_src = (list(old.prefill_src)
                               if old.prefill_src is not None else None)
            req.n_prefed = old.n_prefed
            req.status = old.status  # PENDING or PREEMPTED post-drain
            req.spec = bool(spec_on) if is_spec_mgr else False
            err = new_rm._validate_request(req)
            if err is not None:
                # the candidate cannot hold this request (e.g. a smaller
                # max_seq_len): losing it is not an option — roll back
                raise MigrationRollback(
                    f"request {rid} does not fit the candidate: {err}")
            converted[rid] = req
        # terminal/history records carry over as-is (result lookup joins
        # pre- and post-migration outcomes under one rid space)
        for rid, old in rm.requests.items():
            if rid not in converted:
                new_rm.requests[rid] = old
        new_rm.requests.update(converted)
        new_rm.pending = list(live)
        new_rm._next_rid = max(new_rm._next_rid, rm._next_rid)
        new_rm._tstamps.update(rm._tstamps)  # admission fired once per rid
        if is_spec_mgr:
            new_rm.default_spec_mode = bool(spec_on)
        # host-tier KV crosses the switch: the drain's preempts spilled
        # every running request's pages into the incumbent's host tier —
        # adopt them onto the successor's allocators so readmission
        # restores instead of re-prefilling.  adopt_spills() moves
        # entries ONLY when the swap signatures (page geometry + buffer
        # shapes/dtypes) match; a reshaped candidate silently falls back
        # to the r9 recompute feed, which the transplant above preserved.
        for old_kv, new_kv in zip(self._allocators(rm),
                                  self._allocators(new_rm)):
            new_kv.adopt_spills(old_kv, live)
        return len(live)

    @staticmethod
    def _allocators(rm: RequestManager) -> List:
        kvs = [getattr(rm.im, "kv", None)]
        ssm = getattr(rm, "ssm", None)
        if ssm is not None:
            kvs.append(getattr(ssm, "kv", None))
        return [kv for kv in kvs if kv is not None]

    def _teardown(self, rm: RequestManager) -> List[int]:
        """Release a manager's cache ownership: every allocator tears
        down (attribution released, buffers dropped, page pools reset).
        Returns rids that still held attribution — the refcount no-leak
        contract says this is empty after a full drain."""
        leaked: List[int] = []
        for kv in self._allocators(rm):
            leaked.extend(kv.teardown())
        return sorted(set(leaked))

    def _rollback(self, rm: RequestManager, st: Dict, candidate: Dict,
                  phase: str, reason, new_rm=None):
        """The switch failed: discard the candidate (tearing down any
        buffers it allocated), reopen admission on the incumbent, and let
        the drained requests readmit there — zero lost requests."""
        if new_rm is not None:
            # never tear down an allocator the incumbent still serves
            # from (defense in depth; _build already rejects shared ims)
            inc = {id(kv) for kv in self._allocators(rm)}
            for kv in self._allocators(new_rm):
                if id(kv) not in inc:
                    kv.teardown()
        rm.admission_closed = False
        tel = self.telemetry
        cand_key = candidate.get("plan_key", "?")
        inc_key = self.plan.get("plan_key", "?")
        if tel.enabled:
            tel.migration_rolled_back(inc_key, cand_key, phase=phase,
                                      reason=str(reason)[:200])
        mon = getattr(rm, "plan_health", None)
        if mon is not None:
            mon.recommendation = None  # consumed; a fresh excursion re-emits
        self._cooldown_until = self._ticks + self.config.cooldown_ticks
        self.history.append({
            "outcome": "rolled_back", "incumbent": inc_key,
            "candidate": cand_key, "phase": phase, "reason": str(reason),
            "downtime_ticks": st["downtime_ticks"], "tick": self._ticks,
        })
        return rm

    def _commit(self, rm: RequestManager, new_rm: RequestManager, st: Dict,
                candidate: Dict, mode: str, preempted: int,
                flipped: Optional[int] = None):
        tel = self.telemetry
        cand_key = candidate.get("plan_key", "?")
        inc_key = self.plan.get("plan_key", "?")
        leaked: List[int] = []
        if new_rm is not rm:
            # the incumbent's queue moved wholesale; retire it so a stray
            # loop reference drains immediately instead of double-serving
            rm.pending = []
            rm.admission_closed = True
            rm.migration = None
            leaked = self._teardown(rm)
            # release the retired deployment from the profiler's
            # recompile/page polls (compiles-so-far fold into the
            # counter) — without this, every migration would pin the
            # incumbent's jitted programs alive through the poll list
            prof = getattr(rm, "profiler", None)
            if prof is not None and prof.enabled:
                prof.uninstall(rm.im)
                ssm = getattr(rm, "ssm", None)
                if ssm is not None:
                    prof.uninstall(ssm)
            new_rm.migration = self
            self.rm = new_rm
        new_rm.admission_closed = False
        downtime_s = (new_rm.clock() - st["t_closed"]
                      if st["t_closed"] is not None else 0.0)
        # re-point the plan-health monitor at the NEW executing plan
        mon = getattr(rm, "plan_health", None)
        if mon is not None and getattr(new_rm, "plan_health", None) is None:
            new_rm.plan_health = mon
        mon = getattr(new_rm, "plan_health", None)
        if mon is not None and hasattr(mon, "rebase"):
            kvs = self._allocators(new_rm)
            mon.rebase(candidate,
                       kv_allocator=(kvs[0] if len(kvs) == 1 else kvs)
                       if kvs else None)
        self.plan = dict(candidate)
        self._cooldown_until = self._ticks + self.config.cooldown_ticks
        record = {
            "outcome": "completed", "mode": mode, "incumbent": inc_key,
            "candidate": cand_key, "preempted_requests": preempted,
            "downtime_ticks": st["downtime_ticks"],
            "downtime_s": downtime_s, "kv_leaked_rids": leaked,
            "tick": self._ticks,
        }
        if flipped is not None:
            record["flipped_requests"] = flipped
        self.history.append(record)
        if tel.enabled:
            tel.migration_completed(
                inc_key, cand_key, mode=mode, preempted_requests=preempted,
                downtime_ticks=st["downtime_ticks"], downtime_s=downtime_s)
        if self.on_switch is not None and new_rm is not rm:
            self.on_switch(new_rm)
        return new_rm
