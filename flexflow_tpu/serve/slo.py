"""SLO-class serving lanes + brownout: graceful degradation under overload.

Production traffic is not one class: a latency-critical interactive lane
and a throughput-bound batch lane have different SLO targets, different
shed policies, and different claims on the KV cache — the same
DistServe-style separation of latency-bound and throughput-bound work the
ROADMAP cites for prefill/decode disaggregation, applied at the ADMISSION
layer first.  Through r19 every request shared one admission gate and one
shed policy, so a burst of batch traffic could crowd out interactive
requests and overload ended in undifferentiated ``REJECTED`` or priority
preemption.  This module is the policy plane that fixes both:

* :class:`SLOClass` — one traffic class: a priority band, per-class
  TTFT/TPOT p95 targets (fed to the plan-health checks), a shed policy,
  a KV reservation fraction, and a bounded per-class pending queue.
* :class:`SLOPolicy` — the class registry requests resolve against (the
  ``slo_class`` arrival option / ``register_new_request(slo_class=)``
  keyword; one vocabulary via ``parse_arrival_options``).
* :func:`reservation_reason` — the reserved-KV-headroom gate: each
  class's committed cache need charges its OWN reservation first and only
  the overflow competes for the shared pool, so batch traffic can NEVER
  dip into the latency-critical lane's reservation (whatever the arrival
  order).
* :class:`BrownoutController` — watches per-class SLO attainment, queue
  depth, and KV pressure on the injectable clock and walks a
  deterministic degradation ladder::

      NORMAL -> DEFER_BATCH -> DEGRADE_BATCH -> SHED_BATCH -> CRITICAL_ONLY

  one level per breached evaluation window, with hysteresis
  (``deescalate_after`` consecutive clean windows to step back down — an
  oscillating signal cannot flap the ladder).  The controller only
  DECIDES; the RequestManager / FleetRouter apply the level's actions at
  tick boundaries: DEFER holds degradable-class queue admissions,
  DEGRADE flips speculation off (the r14 ``set_spec_mode`` path) and
  caps ``max_new_tokens`` for degradable classes, SHED turns their
  queued + new work into explicit ``REJECTED``, CRITICAL_ONLY also
  evicts their live requests.  Every outcome stays terminal and explicit
  (deferred requests eventually serve, time out, or shed as
  ``REJECTED`` — never ``FAILED``), and every ADMITTED request's tokens
  stay bit-identical to an unloaded run (degradation only truncates or
  re-schedules work; the (rid, token_index) sample fold is untouched).

Everything here is host-side policy — no decision is ever traced into a
jitted program, so attaching a policy or controller cannot change what
any compiled step computes.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Tuple

from ..obs.telemetry import telemetry_or_null

# shed_policy vocabulary (the per-class knob ISSUE 15 names):
#   "brownout" — the full ladder: deferred first, then degraded, then shed
#   "reject"   — impatient batch: skip deferral, reject new arrivals at
#                any brownout level >= DEFER_BATCH (callers that would
#                rather fail fast than wait out a brownout)
#   "never"    — latency-critical: the ladder never touches this class
SHED_POLICIES = ("brownout", "reject", "never")


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One traffic class (a serving lane).

    * ``priority_band``: added to the caller's per-request priority at
      registration — bands should be spaced wider than any per-request
      priority spread so classes strictly dominate (the default policy
      spaces them 1000 apart).
    * ``ttft_p95_s`` / ``tpot_p95_s``: per-class SLO targets.  The
      plan-health monitor checks the class's OWN p95s against them: a
      breach on a non-degradable class recommends replan, a breach on a
      degradable class escalates the brownout ladder first.
    * ``shed_policy``: see :data:`SHED_POLICIES`.
    * ``kv_reservation_frac``: fraction of the admission KV budget
      reserved for this class — other classes' committed need can never
      enter it (:func:`reservation_reason`).
    * ``max_pending``: bounded PER-CLASS pending queue (None =
      unbounded); registrations beyond it shed as explicit ``REJECTED``.
    * ``degraded_max_new_tokens``: the ``max_new_tokens`` cap applied to
      this class's requests while the ladder is at DEGRADE_BATCH or
      above (None = no cap).  Truncation only: committed tokens are a
      PREFIX of the unloaded run's stream, so bit-identity per position
      is preserved.
    """

    name: str
    priority_band: int = 0
    ttft_p95_s: Optional[float] = None
    tpot_p95_s: Optional[float] = None
    shed_policy: str = "brownout"
    kv_reservation_frac: float = 0.0
    max_pending: Optional[int] = None
    degraded_max_new_tokens: Optional[int] = None

    def __post_init__(self):
        if self.shed_policy not in SHED_POLICIES:
            raise ValueError(f"shed_policy {self.shed_policy!r} "
                             f"(expected one of {SHED_POLICIES})")
        if not 0.0 <= self.kv_reservation_frac <= 1.0:
            raise ValueError("kv_reservation_frac must be in [0, 1]")
        if (self.degraded_max_new_tokens is not None
                and self.degraded_max_new_tokens < 1):
            raise ValueError("degraded_max_new_tokens must be >= 1")

    @property
    def degradable(self) -> bool:
        """Whether the brownout ladder may touch this class."""
        return self.shed_policy != "never"


class SLOPolicy:
    """The class registry one serving deployment (or fleet) resolves
    requests against.  ``default_class`` names the lane unclassified
    requests ride — in the default policy that is ``batch``, so only
    explicitly-marked traffic claims the latency-critical lane."""

    def __init__(self, classes: List[SLOClass], default_class: str):
        if not classes:
            raise ValueError("an SLOPolicy needs at least one class")
        self.classes: Dict[str, SLOClass] = {}
        for cls in classes:
            if cls.name in self.classes:
                raise ValueError(f"duplicate SLO class {cls.name!r}")
            self.classes[cls.name] = cls
        if default_class not in self.classes:
            raise ValueError(f"default_class {default_class!r} is not a "
                             f"registered class ({sorted(self.classes)})")
        self.default_class = default_class
        total = sum(c.kv_reservation_frac for c in classes)
        if total > 1.0 + 1e-9:
            raise ValueError(
                f"KV reservations sum to {total:.3f} > 1.0 — the shared "
                "pool would be negative")

    def resolve(self, name: Optional[str]) -> Optional[SLOClass]:
        """The class for a request's ``slo_class`` option (None / "" ->
        the default class); None for an UNKNOWN name — the caller turns
        that into a reject reason (one bad arrival must not kill a serve
        loop)."""
        if not name:
            return self.classes[self.default_class]
        return self.classes.get(name)

    def get(self, name: str) -> Optional[SLOClass]:
        return self.classes.get(name)

    def snapshot(self) -> Dict:
        """Serializable policy provenance for a traffic-trace header
        (obs/replay.py): every class's full knob set + the default lane.
        Replay does not rebuild a policy from this — the caller wires
        its own — but a what-if report keys per-class deltas on it and
        a fidelity check can assert the replayed policy matches."""
        return {
            "default_class": self.default_class,
            "classes": {name: dataclasses.asdict(cls)
                        for name, cls in sorted(self.classes.items())},
        }

    @staticmethod
    def default(lc_reservation_frac: float = 0.25,
                lc_ttft_p95_s: Optional[float] = None,
                lc_tpot_p95_s: Optional[float] = None,
                batch_max_pending: Optional[int] = None,
                degraded_max_new_tokens: Optional[int] = None
                ) -> "SLOPolicy":
        """The two-lane policy ISSUE 15 describes: ``latency_critical``
        (band 1000, reserved KV, never degraded) over ``batch`` (band 0,
        full brownout ladder, the default lane)."""
        return SLOPolicy([
            SLOClass("latency_critical", priority_band=1000,
                     ttft_p95_s=lc_ttft_p95_s, tpot_p95_s=lc_tpot_p95_s,
                     shed_policy="never",
                     kv_reservation_frac=lc_reservation_frac),
            SLOClass("batch", priority_band=0, shed_policy="brownout",
                     max_pending=batch_max_pending,
                     degraded_max_new_tokens=degraded_max_new_tokens),
        ], default_class="batch")


def reservation_reason(policy: SLOPolicy,
                       committed_by_class: Dict[str, float],
                       cls: SLOClass, need: float,
                       budget: float) -> Optional[str]:
    """The reserved-headroom gate: the rejection reason, or None to admit.

    Arithmetic (all in the same units — bytes or token-slots — as
    ``budget``): each class's reservation is ``r_k * budget``; a class's
    committed need charges its own reservation FIRST and only the
    overflow competes for the shared pool ``budget * (1 - sum(r_k))``.
    Admit the new request iff every class's overflow (with the new
    request added to ``cls``) still fits the shared pool.  Consequences:

    * a class with no reservation (batch) can use at most
      ``budget - sum(other reservations)`` — it can NEVER dip into the
      latency-critical reservation, whatever arrives first;
    * a reserved class can always use its own reservation even when the
      shared pool is saturated by others;
    * total committed never exceeds ``budget`` (each class's usage is
      ``min(committed, r*budget) + overflow`` and the overflows fit the
      shared pool) — the per-policy gate composes with, and is never
      looser than, the r9 total-headroom gate.
    """
    reserved = {k: c.kv_reservation_frac * budget
                for k, c in policy.classes.items()}
    shared = budget - sum(reserved.values())
    overflow = 0.0
    for k, c in policy.classes.items():
        committed = committed_by_class.get(k, 0.0) \
            + (need if k == cls.name else 0.0)
        overflow += max(committed - reserved.get(k, 0.0), 0.0)
    if overflow > shared + 1e-9:
        return (f"KV lane reservation: class {cls.name!r} overflow would "
                f"need {overflow:.0f} of {shared:.0f} shared units "
                f"(reservations withhold "
                f"{sum(reserved.values()):.0f}/{budget:.0f})")
    return None


class BrownoutLevel(enum.IntEnum):
    """The degradation ladder — ordered so comparisons read naturally
    (``level >= BrownoutLevel.SHED_BATCH``)."""

    NORMAL = 0
    DEFER_BATCH = 1
    DEGRADE_BATCH = 2
    SHED_BATCH = 3
    CRITICAL_ONLY = 4


MAX_LEVEL = BrownoutLevel.CRITICAL_ONLY


@dataclasses.dataclass
class BrownoutConfig:
    """Ladder thresholds + hysteresis.

    * ``check_every``: serve/fleet ticks between evaluations (each
      evaluation is one hysteresis window).
    * ``queue_depth_high``: pending depth of the NON-degradable
      (latency-critical) lanes above which the window counts as
      pressured — interactive work queueing is exactly the signal the
      ladder exists to relieve.
    * ``kv_pressure_frac``: live-KV occupancy fraction above which the
      window is pressured.
    * ``escalate_after``: consecutive pressured windows before the
      ladder steps UP one level.
    * ``deescalate_after``: consecutive clean windows before it steps
      DOWN one level — the hysteresis knob; a level change resets both
      streaks, so the ladder moves at most one level per
      ``min(escalate_after, deescalate_after)`` windows and an
      oscillating signal cannot flap it.
    * ``slo_min_samples``: FRESH per-class latency observations (since
      the previous evaluation) required before the class-SLO signal can
      count as pressure — attainment is judged on recent evidence only
      (``Histogram.tail``), so one old breach can never pin a recovered
      ladder at its peak.
    """

    check_every: int = 4
    queue_depth_high: int = 4
    kv_pressure_frac: float = 0.9
    escalate_after: int = 2
    deescalate_after: int = 4
    slo_min_samples: int = 2


class BrownoutController:
    """Walks the degradation ladder from observed pressure signals.

    The controller DECIDES the level; the serving layer (RequestManager
    or FleetRouter) calls :meth:`evaluate` on its tick cadence with the
    live signals and applies the level's actions at its own tick
    boundary (see the module docstring for the action table).  Per-class
    SLO attainment arrives either through the bound telemetry handle's
    per-class histograms (read here) or through
    :meth:`note_slo_breach` (the plan-health monitor's escalation path
    for degradable-class breaches).

    Host-side only and deterministic: given the same signal sequence the
    level walk is identical, which is what lets the hermetic
    ``slo_overload`` bench pin "up the ladder and back down, zero
    flapping" on a virtual clock.
    """

    def __init__(self, policy: SLOPolicy,
                 config: Optional[BrownoutConfig] = None,
                 telemetry=None, clock=None):
        import time as _time

        self.policy = policy
        self.config = config or BrownoutConfig()
        self.telemetry = telemetry_or_null(telemetry)
        self.clock = clock or _time.perf_counter
        self.level = BrownoutLevel.NORMAL
        self._pressured_windows = 0
        self._clean_windows = 0
        self._breach_noted: Optional[str] = None
        self._slo_seen: Dict[str, int] = {}  # hist name -> count consumed
        self.evaluations = 0
        # (evaluation index, new level, reason) per transition — the
        # hermetic bench reads this to pin the monotone up-then-down walk
        self.history: List[Tuple[int, BrownoutLevel, str]] = []

    # ------------------------------------------------------------------
    # level queries the serving layers gate on
    # ------------------------------------------------------------------
    def _cls(self, name: str) -> Optional[SLOClass]:
        return self.policy.resolve(name)

    def holds(self, cls_name: str) -> bool:
        """DEFER semantics: should this class's queued requests be held
        out of engine slots this tick?  ("reject"-policy classes never
        wait — they shed via :meth:`admits` instead.)"""
        cls = self._cls(cls_name)
        return (cls is not None and cls.shed_policy == "brownout"
                and self.level >= BrownoutLevel.DEFER_BATCH)

    def spills(self, cls_name: str) -> bool:
        """SPILL semantics — the rung between DEFER and DEGRADE: may this
        class's decoding requests have their KV pages pushed to the host
        tier (preempt-with-spill) to relieve page pressure?  Carried by
        DEFER_BATCH and above as an ACTION, not a new ladder level: the
        level walk, its hysteresis pins, and fleet.py's hardcoded level
        comparisons stay untouched, and readmission restores the pages
        (bit-identical-prefix contract — preemption already carries it).
        Only degradable classes spill; latency-critical work keeps its
        pages hot."""
        cls = self._cls(cls_name)
        return (cls is not None and cls.degradable
                and self.level >= BrownoutLevel.DEFER_BATCH)

    def degrades(self, cls_name: str) -> bool:
        """DEGRADE semantics: spec off + output cap for this class?"""
        cls = self._cls(cls_name)
        return (cls is not None and cls.degradable
                and self.level >= BrownoutLevel.DEGRADE_BATCH)

    def sheds_queued(self, cls_name: str) -> bool:
        """SHED semantics: queued requests of this class go REJECTED."""
        cls = self._cls(cls_name)
        return (cls is not None and cls.degradable
                and self.level >= BrownoutLevel.SHED_BATCH)

    def sheds_live(self, cls_name: str) -> bool:
        """CRITICAL_ONLY semantics: even slotted requests evict."""
        cls = self._cls(cls_name)
        return (cls is not None and cls.degradable
                and self.level >= BrownoutLevel.CRITICAL_ONLY)

    def admits(self, cls_name: str) -> bool:
        """Admission gate for NEW arrivals of this class at the current
        level (False -> explicit REJECTED)."""
        cls = self._cls(cls_name)
        if cls is None or not cls.degradable:
            return True
        if cls.shed_policy == "reject":
            return self.level < BrownoutLevel.DEFER_BATCH
        return self.level < BrownoutLevel.SHED_BATCH

    def output_cap(self, cls_name: str) -> Optional[int]:
        """The ``max_new_tokens`` cap in force for this class (None = no
        cap at the current level)."""
        cls = self._cls(cls_name)
        if cls is None or not self.degrades(cls_name):
            return None
        return cls.degraded_max_new_tokens

    # ------------------------------------------------------------------
    # signal intake
    # ------------------------------------------------------------------
    def note_slo_breach(self, cls_name: str) -> None:
        """A degradable class breached its own SLO targets (the
        plan-health monitor's per-class check routes here FIRST; only a
        non-degradable breach recommends replan).  Counts as pressure in
        the next evaluation window."""
        self._breach_noted = cls_name

    def _class_slo_pressure(self) -> Optional[str]:
        """Latency-critical attainment from the per-class histograms the
        telemetry handle maintains: a NON-degradable class missing its
        own p95 targets is the clearest 'sacrifice batch work' signal.

        Judged on FRESH observations only (those since the previous
        evaluation, ``Histogram.tail``) — a brownout controller must see
        current attainment, and a single old breach pinning the ladder
        at its peak after the lane recovered would defeat the
        de-escalation contract."""
        from ..obs.metrics import percentile

        tel = self.telemetry
        if not tel.enabled:
            return None
        breach = None
        for name, cls in self.policy.classes.items():
            if cls.degradable:
                continue
            for metric, target in (("ttft_s", cls.ttft_p95_s),
                                   ("tpot_s", cls.tpot_p95_s)):
                if target is None:
                    continue
                key = f"{metric}_cls_{name}"
                hist = tel.metrics.histogram(key)
                fresh = hist.tail(self._slo_seen.get(key, 0))
                self._slo_seen[key] = hist.count
                if len(fresh) < self.config.slo_min_samples:
                    continue
                p95 = percentile(sorted(fresh), 0.95)
                if breach is None and p95 is not None and p95 > target:
                    breach = f"slo:{name}:{metric}"
        return breach

    def evaluate(self, lc_queue_depth: int = 0,
                 kv_occupancy_frac: float = 0.0) -> BrownoutLevel:
        """One hysteresis window: classify it pressured or clean, update
        the streaks, and walk the ladder at most ONE level.  Returns the
        (possibly new) level.  Callers supply the queue/KV signals they
        own; SLO attainment is read from telemetry + breach notes."""
        cfg = self.config
        self.evaluations += 1
        # the per-class tails are consumed EVERY window (whatever other
        # pressure fired), so "fresh" always means "since the previous
        # evaluation" and burst-era breaches cannot resurface later
        slo_pressure = self._class_slo_pressure()
        reason = None
        if lc_queue_depth > cfg.queue_depth_high:
            reason = f"lc_queue_depth:{lc_queue_depth}"
        elif kv_occupancy_frac > cfg.kv_pressure_frac:
            reason = f"kv_pressure:{kv_occupancy_frac:.2f}"
        elif self._breach_noted is not None:
            reason = f"slo_breach:{self._breach_noted}"
        elif slo_pressure is not None:
            reason = slo_pressure
        self._breach_noted = None
        if reason is not None:
            self._pressured_windows += 1
            self._clean_windows = 0
            if (self._pressured_windows >= cfg.escalate_after
                    and self.level < MAX_LEVEL):
                self._transition(BrownoutLevel(self.level + 1), reason)
        else:
            self._clean_windows += 1
            self._pressured_windows = 0
            if (self._clean_windows >= cfg.deescalate_after
                    and self.level > BrownoutLevel.NORMAL):
                self._transition(BrownoutLevel(self.level - 1),
                                 "clean_windows")
        tel = self.telemetry
        if tel.enabled:
            tel.metrics.gauge("brownout_level").set(int(self.level))
        return self.level

    def _transition(self, new: BrownoutLevel, reason: str) -> None:
        old = self.level
        self.level = new
        # a level change opens a fresh window in BOTH directions — K
        # clean windows are needed from HERE to step down (hysteresis),
        # K pressured ones to step further up
        self._pressured_windows = 0
        self._clean_windows = 0
        self.history.append((self.evaluations, new, reason))
        if self.telemetry.enabled:
            self.telemetry.brownout_level_changed(
                int(new), int(old), level_name=new.name, reason=reason)
