"""Fixed-capacity batch descriptors shipped to the device each serving step.

The TPU-native analogue of FlexFlow's ``BatchConfig`` family (reference:
``include/flexflow/batch_config.h``, ``src/runtime/batch_config.cc`` and the
beam/tree variants): a POD struct of fixed-size arrays describing which
requests and tokens are in flight.  The reference ships it to every GPU as a
Legion future each step; here it is a JAX pytree of small arrays passed into
the jitted decode step.  Fixed capacities are a *feature* on TPU: every step
has identical shapes, so XLA compiles the decode program exactly once.

Layout follows the reference's flat-token design: a step processes up to
``max_tokens`` tokens belonging to up to ``max_requests`` request slots;
per-token arrays say which slot each token belongs to and at which absolute
sequence position it sits.  Prefill (many tokens of one request) and decode
(one token per request) ride the same struct — the continuous-batching mix
FlexFlow's RequestManager produces.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# Capacity defaults (analogous to the reference's BatchConfig constants).
MAX_NUM_REQUESTS = 8
MAX_NUM_TOKENS = 64
MAX_SPEC_TREE_TOKENS = 64


def _field(**meta):
    return dataclasses.field(metadata=meta)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BatchConfig:
    """One incremental-decoding step's worth of work.

    All arrays are capacity-padded; ``num_tokens`` marks the valid prefix.
    Padding token slots carry ``request_index == -1`` so their writes land in
    a scratch cache row and their logits are ignored.
    """

    tokens: jax.Array           # i32[max_tokens] input token ids
    request_index: jax.Array    # i32[max_tokens] slot per token (-1 = pad)
    token_position: jax.Array   # i32[max_tokens] absolute seq position
    num_tokens: jax.Array       # i32[] valid token count
    seq_lens: jax.Array         # i32[max_requests] cache depth AFTER this step

    @property
    def max_tokens(self) -> int:
        return self.tokens.shape[0]

    @property
    def max_requests(self) -> int:
        return self.seq_lens.shape[0]

    def advance(self, token_ids: jax.Array) -> "BatchConfig":
        """Next pure-decode step's config, computed ON DEVICE.

        For a batch where every valid slot is a decode token (one token per
        active request), the next step feeds each slot the token just
        produced for it, one position further.  This is what lets the decode
        loop run as a ``lax.scan`` entirely on device — the TPU-native
        answer to the reference's per-step host round trip through
        ``RequestManager::prepare_next_batch`` (the host only syncs every
        N steps).  Prefill/mixed batches must go through ``build``.
        """
        active = self.request_index >= 0
        req = jnp.clip(self.request_index, 0, self.max_requests - 1)
        seq_lens = self.seq_lens + jnp.zeros_like(self.seq_lens).at[req].add(
            active.astype(self.seq_lens.dtype)
        )
        return BatchConfig(
            tokens=jnp.where(active, token_ids, self.tokens),
            request_index=self.request_index,
            token_position=self.token_position + active.astype(jnp.int32),
            num_tokens=self.num_tokens,
            seq_lens=seq_lens,
        )

    def join_row(self, dst, tok, slot, pos, seq_len, num_tokens,
                 active=True) -> "BatchConfig":
        """Masked slot activation: merge ONE staged arrival into a running
        scan's batch, on device.

        A multi-step decode scan advances its BatchConfig entirely on
        device, so an arrival admitted mid-stretch cannot be spliced in by
        rebuilding the batch on host (that would force a sync).  Instead
        the host prefills the prompt asynchronously, then activates flat
        row ``dst`` for slot ``slot`` with the prefill's produced token
        ``tok`` at position ``pos`` (= prompt length): the next scan
        segment picks the row up exactly as if it had been in the batch
        from the start.  ``active=False`` installs the row pre-frozen
        (``request_index=-1``) — used when the prefill token already
        terminated the request (EOS), so the scan never decodes past it.
        All operands may be traced scalars; shapes are unchanged, so the
        consuming scan's compiled program is reused as-is.
        """
        slot_i = jnp.asarray(slot, jnp.int32)
        return BatchConfig(
            tokens=self.tokens.at[dst].set(jnp.asarray(tok, jnp.int32)),
            request_index=self.request_index.at[dst].set(
                jnp.where(jnp.asarray(active), slot_i,
                          jnp.int32(-1))),
            token_position=self.token_position.at[dst].set(
                jnp.asarray(pos, jnp.int32)),
            num_tokens=jnp.asarray(num_tokens, jnp.int32),
            seq_lens=self.seq_lens.at[slot_i].set(
                jnp.asarray(seq_len, jnp.int32)),
        )

    def split_microbatches(self, n_micro: int) -> list:
        """Split the flat token batch into ``n_micro`` contiguous ranges —
        the decode-time micro-batches pipeline-parallel serving interleaves
        across stages (Orca-style).

        Exact by construction: the builders lay a request's tokens out
        contiguously in ascending position order, so a contiguous range
        split preserves in-request ordering; a token's causal frontier only
        ever reaches KV written by earlier flat slots (same micro-batch:
        written before attending, as in the flat step) or by earlier
        micro-batches (committed before that micro-batch runs).  Each
        micro-batch keeps the full ``seq_lens`` (attention masks use
        ``token_position`` only) and clips ``num_tokens`` to its range.
        """
        if n_micro <= 1 or self.max_tokens % n_micro:
            return [self]
        k = self.max_tokens // n_micro
        out = []
        for j in range(n_micro):
            lo = j * k
            out.append(BatchConfig(
                tokens=self.tokens[lo: lo + k],
                request_index=self.request_index[lo: lo + k],
                token_position=self.token_position[lo: lo + k],
                num_tokens=jnp.clip(self.num_tokens - lo, 0, k),
                seq_lens=self.seq_lens,
            ))
        return out

    @staticmethod
    def build(
        token_ids,
        request_indices,
        positions,
        seq_lens,
        max_tokens: int = MAX_NUM_TOKENS,
        max_requests: int = MAX_NUM_REQUESTS,
    ) -> "BatchConfig":
        """Host-side constructor from variable-length lists (pads to capacity)."""
        n = len(token_ids)
        if n > max_tokens:
            raise ValueError(f"{n} tokens > capacity {max_tokens}")
        tokens = np.zeros(max_tokens, np.int32)
        req = np.full(max_tokens, -1, np.int32)
        pos = np.zeros(max_tokens, np.int32)
        tokens[:n] = token_ids
        req[:n] = request_indices
        pos[:n] = positions
        sl = np.zeros(max_requests, np.int32)
        sl[: len(seq_lens)] = seq_lens
        return BatchConfig(
            tokens=jnp.asarray(tokens),
            request_index=jnp.asarray(req),
            token_position=jnp.asarray(pos),
            num_tokens=jnp.asarray(n, jnp.int32),
            seq_lens=jnp.asarray(sl),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PrefillBatchConfig:
    """A prompt-prefill step whose flat tokens are grouped into request-
    homogeneous tiles, unlocking the Q-tiled Pallas prefill kernel.

    The reference's IncMHA CUDA kernel serves prompt and decode phases with
    one code path (``inc_multihead_self_attention.cu``); on TPU the two
    phases want different grids — decode is one query per cache row
    (bandwidth-bound), prefill is a *block* of queries per cache row
    (MXU-bound) — so prefill ships this wrapper type and the attention op
    mode-dispatches on it like the tree variants.

    Contract (enforced by :meth:`build`): with ``Bq = tile_size`` and
    ``G = base.max_tokens // Bq``, flat slot ``g*Bq + b`` belongs to tile
    ``g``; each tile's real tokens (a) belong to ONE request, (b) sit at the
    tile's head with pad slots only at the tail, (c) have contiguous
    ascending positions, and (d) start at a TILE-ALIGNED position
    (``start_pos % Bq == 0``) — the attention op writes each tile's KV as
    one block dynamic-update-slice, and alignment (with the cache's seq
    capacity a multiple of the tile) guarantees the DUS start is never
    clamp-shifted.  The kernel then reconstructs every per-token causal
    mask from the tile's first position alone.

    **LM-head gating** (``logit_slots``): a prefill chunk only needs logits
    at each request's LAST prompt token (the first-generated-token sample
    point); every other position's logits are computed and thrown away —
    at the 7B bench shape the LM head is ~9% of a 512-token chunk's GEMM
    flops.  When ``logit_slots`` is set (i32[max_requests]; the flat token
    index of slot r's prompt-final token in THIS chunk, -1 = this chunk
    carries no sample point for r), the LM head gathers those <=
    max_requests hidden rows and computes a [max_requests, vocab] GEMM
    instead of [max_tokens, vocab]; mid-prompt chunks (all -1) pay only
    that negligible gathered GEMM.  The step's InferenceResult arrays are
    then indexed BY SLOT, not by flat token.  ``None`` keeps the full
    per-position logits (the oracle path gating is tested against).
    """

    base: BatchConfig
    tile_size: int = dataclasses.field(metadata=dict(static=True))
    logit_slots: Optional[jax.Array] = None  # i32[max_requests] or None

    @property
    def num_tiles(self) -> int:
        return self.base.max_tokens // self.tile_size

    @staticmethod
    def build(
        segments,
        seq_lens,
        tile_size: int,
        max_tokens: int = MAX_NUM_TOKENS,
        max_requests: int = MAX_NUM_REQUESTS,
        gate_slots=None,
    ):
        """Tile-aligned constructor.

        ``segments``: iterable of ``(slot, token_ids, start_pos)`` — one
        contiguous prompt chunk per request.  Returns ``(pbc, last_flat)``
        where ``last_flat[slot]`` is the flat index of that segment's final
        token (where its first-generated-token logits appear).

        ``gate_slots``: iterable of slots whose segment ENDS its prompt in
        this chunk — enables LM-head gating (``logit_slots`` built from
        ``last_flat``; the caller knows which segments complete, the
        builder only knows where each segment ends).  None = full logits.
        """
        fields, last_flat = PrefillBatchConfig.np_fields(
            segments, seq_lens, tile_size, max_tokens, max_requests
        )
        base = BatchConfig(*(jnp.asarray(f) for f in fields))
        ls = None
        if gate_slots is not None:
            ls = PrefillBatchConfig.np_logit_slots(
                gate_slots, last_flat, max_requests)
            ls = jnp.asarray(ls)
        return (
            PrefillBatchConfig(base=base, tile_size=tile_size,
                               logit_slots=ls),
            last_flat,
        )

    @staticmethod
    def np_logit_slots(gate_slots, last_flat, max_requests):
        """i32[max_requests] logit_slots array from the completing slots
        (host-side half, stackable like :meth:`np_fields`)."""
        ls = np.full(max_requests, -1, np.int32)
        for slot in gate_slots:
            ls[slot] = last_flat[slot]
        return ls

    @staticmethod
    def np_fields(segments, seq_lens, tile_size, max_tokens, max_requests):
        """:meth:`build`'s host-side half: the five BatchConfig fields as
        numpy arrays (field order) — callers that stack many chunks (the
        RequestManager's prefill stretch) stack these and transfer once,
        instead of shipping five tiny arrays to the device per chunk."""
        if max_tokens % tile_size:
            raise ValueError(
                f"tile_size {tile_size} must divide max_tokens {max_tokens}"
            )
        tokens = np.zeros(max_tokens, np.int32)
        req = np.full(max_tokens, -1, np.int32)
        pos = np.zeros(max_tokens, np.int32)
        last_flat = {}
        at = 0
        n = 0
        for slot, toks, start in segments:
            if start % tile_size:
                raise ValueError(
                    f"segment start {start} not aligned to tile_size "
                    f"{tile_size} (contract (d): the block KV write needs "
                    "tile-aligned positions)"
                )
            need = -(-len(toks) // tile_size) * tile_size  # round up to tiles
            if at + need > max_tokens:
                raise ValueError(
                    f"segments need {at + need} padded slots > capacity "
                    f"{max_tokens}"
                )
            tokens[at: at + len(toks)] = toks
            req[at: at + len(toks)] = slot
            pos[at: at + len(toks)] = np.arange(start, start + len(toks))
            last_flat[slot] = at + len(toks) - 1
            n = at + len(toks)
            at += need
        sl = np.zeros(max_requests, np.int32)
        sl[: len(seq_lens)] = seq_lens
        fields = (tokens, req, pos, np.asarray(n, np.int32), sl)
        return fields, last_flat


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TreeSearchBatchConfig:
    """Draft-model (SSM) tree-expansion step.

    Reference: ``BeamSearchBatchConfig``.  The step's tokens are nodes being
    added to each request's speculation tree; ``spec_index`` is the node's
    index within the per-request tree buffer, ``ancestor_mask[r, i, j]`` says
    tree node ``i`` of request ``r`` may attend tree node ``j`` (its root-path
    ancestors and itself).  Committed-cache attention stays causal on
    ``token_position``.
    """

    base: BatchConfig
    spec_index: jax.Array     # i32[max_tokens] tree-node slot per step token
    ancestor_mask: jax.Array  # bool[max_requests, max_spec, max_spec]
    committed_lens: jax.Array  # i32[max_requests] committed cache depth

    @property
    def max_spec_tokens(self) -> int:
        return self.ancestor_mask.shape[-1]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TreeVerifyBatchConfig:
    """LLM verification step over flattened speculation trees.

    Reference: ``TreeVerifyBatchConfig``.  Same tree-attention layout as
    :class:`TreeSearchBatchConfig` — the whole tree arrives in ONE step and is
    verified with the tree-topology causal mask — plus the commit descriptor:
    tokens accepted in the *previous* macro-step whose KV (saved in the spec
    buffer) must be copied into the committed cache before attending.

    **Mixed spec/non-spec batches** (ISSUE 11): a request in plain decode
    mode rides the same verify step as a DEGENERATE root-only tree — one
    node (its decode token) whose ancestor mask is just the self bit.
    The tree attention of a single root node reduces exactly to ordinary
    decode attention over the committed prefix, so spec rows verify
    multi-token while plain rows decode one token in one batched step;
    the accept walk trivially emits the plain row's sampled/argmax token
    (no children to match).  Builders: ``SpecInferManager._draft_phase``
    (host) and ``SpecDecodeScan`` with ``spec_mask`` (on-device).
    """

    base: BatchConfig
    spec_index: jax.Array      # i32[max_tokens]
    ancestor_mask: jax.Array   # bool[max_requests, max_spec, max_spec]
    committed_lens: jax.Array  # i32[max_requests]
    # commit descriptor (flat, capacity-padded, request_index -1 = pad):
    commit_request_index: jax.Array  # i32[max_commit]
    commit_src_spec_index: jax.Array  # i32[max_commit] slot in spec buffer
    commit_dst_position: jax.Array   # i32[max_commit] cache position to fill

    @property
    def max_spec_tokens(self) -> int:
        return self.ancestor_mask.shape[-1]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class InferenceResult:
    """Per-step device output consumed by the RequestManager.

    Reference: ``InferenceResult`` (token ids produced for each flat token
    slot).  ``logprobs``/``topk`` are optional extensions used by sampling and
    speculation.
    """

    token_ids: jax.Array   # i32[max_tokens] next-token id per flat slot
    logits_max: jax.Array  # f32[max_tokens] (argmax logit, diagnostics)
    topk_ids: Optional[jax.Array] = None     # i32[max_tokens, k]
    topk_logprobs: Optional[jax.Array] = None  # f32[max_tokens, k]
