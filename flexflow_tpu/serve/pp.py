"""Pipeline-parallel serving: stage-split decode with micro-batch interleaving.

SURVEY §4's inference matrix is "model x precision x TP/PP configs"; the serve
stack so far covered TP only.  This module adds the PP column: the serve graph
is split into ``pp`` contiguous STAGES at small live-set boundaries (the same
live-cut machinery the GPipe training executor carves SESE segments with —
``core.graph.live_cuts``), each stage compiles to its own program over its own
device slice (weights + that stage's KV caches resident per slice — the
capacity lever that lets shapes exceeding one chip's HBM serve across the pp
axis), and activations hop stage to stage.  Decode-time micro-batch
interleaving (Orca OSDI'22) keeps every stage busy: ``m`` micro-batches cycle
through the stage chain continuously, shrinking the steady-state pipeline
bubble from ``(pp-1)/pp`` (one batch, ``m=1``) to ``(pp-m)/pp`` — zero once
``m >= pp`` fills the pipeline (``m = pp`` is the decode optimum: beyond it
stage weights re-stream per micro-batch for no bubble win).

Execution model — MULTI-PROGRAM, host-interleaved: one jitted step per stage
per batch-config type, dispatched asynchronously.  Stage programs occupy
disjoint devices, so dispatching micro-batch j+1's stage-0 right after
micro-batch j's (whose stage-1 is still running) overlaps them for real; the
host never blocks inside a macro-step (the one sync is the caller reading
results).  Inter-stage transfer is a ``jax.device_put`` of the boundary
activations onto the next stage's mesh — on TPU this lowers to an ICI
device-to-device copy, the point-to-point analogue of the training pipeline's
``ppermute`` (which needs every stage inside ONE program; serve stages are
deliberately separate programs so each keeps its own donated KV state and its
own TP sharding through the existing GSPMD path).

Bit-identity: each micro-batch runs the exact op ``lower``s of the plan steps
the single-stage InferenceManager would run, in the same order, on the same
values — stage boundaries only name where activations change devices, and
contiguous-range micro-batch splits preserve the flat batch's causal layout
(see ``BatchConfig.split_microbatches``).  Pinned by tests/test_pp_serve.py
for decode, prefill (tiled + gated), and mixed steps, incl. the int8-weights +
int8-KV configuration.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.graph import live_cuts
from ..core.interpreter import build_forward, init_params
from ..core.pcg import PCG
from ..obs.profiler import NULL_PROFILER
from ..obs.telemetry import NULL_TELEMETRY
from .batch_config import BatchConfig, InferenceResult
from .inference_manager import (
    EXIT_BUDGET,
    EXIT_EOS,
    EXIT_NOT_IN_BATCH,
    EXIT_RUNNING,
    mark_gated_lm_head,
    pick_prefill_tile,
    register_serve_capacities,
    sample_tokens,
    tensor_parallel_strategy,
)
from .kv_allocator import KVAllocator, StageKV, params_nbytes
from .ops import IncMultiHeadSelfAttention


def serve_stage_split(graph, pp: int, out_tid: Optional[int] = None,
                      max_live: int = 2):
    """Split a serve graph's node chain into ``pp`` contiguous stages.

    Cuts are placed at boundaries whose live tensor set is at most
    ``max_live`` wide (llama-family graphs carry ``{residual, hidden}``
    between decoder layers, so 2 covers them; a pure op chain cuts at
    SESE single-tensor boundaries), balanced so each stage owns an equal
    share of the attention layers — the weight- and KV-heavy units.  Ties
    prefer the narrowest cut, then the latest boundary (so norms feeding a
    layer stay with the upstream stage and the next stage starts at its
    attention).

    Returns ``[(nodes, entry_tids, exit_tids)]`` with
    ``exit_tids[s] == entry_tids[s+1]`` (sorted tid order),
    ``entry_tids[0] == graph.input_tids`` and ``exit_tids[-1] == [out_tid]``.
    """
    nodes = graph.nodes
    if not nodes:
        raise ValueError("empty graph")
    if out_tid is None:
        out_tid = nodes[-1].outputs[-1]
    if pp <= 1:
        return [(list(nodes), list(graph.input_tids), [out_tid])]
    lives = live_cuts(graph, [out_tid])
    is_attn = [isinstance(n.op, IncMultiHeadSelfAttention) for n in nodes]
    total = sum(is_attn)
    if pp > total:
        raise ValueError(
            f"pp={pp} stages need at least that many attention layers "
            f"(graph has {total})"
        )
    cum = np.cumsum(is_attn)
    candidates = [i for i in range(len(nodes) - 1)
                  if len(lives[i]) <= max_live]
    cuts: List[int] = []
    lo_attn = 0
    for s in range(1, pp):
        target = total * s / pp
        pool = [i for i in candidates
                if lo_attn < cum[i] < total
                and (not cuts or i > cuts[-1])]
        if not pool:
            raise ValueError(
                f"no admissible cut for stage boundary {s} "
                f"(live sets wider than {max_live}?)"
            )
        best = min(pool, key=lambda i: (abs(cum[i] - target),
                                        len(lives[i]), -i))
        cuts.append(best)
        lo_attn = cum[best]
    bounds = [-1] + cuts + [len(nodes) - 1]
    stages = []
    for s in range(pp):
        seg = nodes[bounds[s] + 1: bounds[s + 1] + 1]
        entry = (list(graph.input_tids) if s == 0
                 else sorted(lives[bounds[s]]))
        exit_ = ([out_tid] if s == pp - 1 else sorted(lives[bounds[s + 1]]))
        stages.append((seg, entry, exit_))
    return stages


class _StageView:
    """Graph-protocol view of a contiguous node range, plannable by PCG.

    Tensor ids (and ``tensor_specs``) are shared with the parent graph, so
    stage entry tids are exactly the parent's boundary tensors; the view
    only narrows ``nodes`` and redeclares the boundary as graph inputs.
    """

    def __init__(self, parent, nodes, input_tids):
        self.nodes = list(nodes)
        self.input_tids = list(input_tids)
        self.tensor_specs = parent.tensor_specs
        self._parent = parent

    def topo_order(self):
        return self.nodes

    def spec(self, tid):
        return self.tensor_specs[tid]

    def unique_name(self, base):
        return self._parent.unique_name(base)


def build_stage_plans(graph, split, strategy, meshes):
    """One PCG plan per stage: the stage's nodes over its own mesh, with the
    (TP) strategy restricted to them and the boundary tensors as plan
    inputs/outputs.  Used by the executor below AND by the serve search's
    TP x PP pricing (``search.serve_search``) — per-stage
    ``plan_memory_bytes`` is what gates pp admissibility under the HBM cap.
    """
    plans = []
    for (nodes, entry, exit_), mesh in zip(split, meshes):
        names = {n.name for n in nodes}
        cfg = {k: v for k, v in (strategy or {}).items() if k in names}
        view = _StageView(graph, nodes, entry)
        plans.append(PCG(view, mesh, cfg, output_tids=list(exit_)).plan())
    return plans


class _Stage:
    """One pipeline stage: plan + params + KV state + jitted step."""

    def __init__(self, nodes, entry_tids, exit_tids, mesh, plan):
        self.nodes = nodes
        self.entry_tids = list(entry_tids)
        self.exit_tids = list(exit_tids)
        self.mesh = mesh
        self.plan = plan
        self.fwd = build_forward(plan, mode="spmd")
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.replicated = NamedSharding(mesh, P())
        self.params: Optional[Dict] = None
        # per-stage KV ownership (serve/kv_allocator.py): the manager binds
        # a StageKV per stage; ``state`` delegates so the async dispatch
        # loop's donate/re-bind cycle is unchanged
        self.kv: Optional[StageKV] = None
        self.step = None  # bound by the manager (closes over its flags)

    @property
    def state(self) -> Optional[Dict]:
        return self.kv.state if self.kv is not None else None

    @state.setter
    def state(self, value) -> None:
        self.kv.state = value


class PipelinedInferenceManager:
    """Stage-split serving over a ``pp`` (x ``tp``) mesh.

    ``model.mesh`` must carry a ``pp`` axis (and optionally ``tp``); each of
    the ``pp`` device slices runs one stage, tensor-parallel over its own
    ``tp`` sub-axis through the unchanged GSPMD serve path (Megatron head
    sharding, Pallas kernels via the per-op shard_map).  API-compatible with
    :class:`InferenceManager` for the RequestManager: ``step`` /
    ``decode_scan`` / ``reset`` / capacity attributes all behave the same,
    so continuous batching, chunked prefill (tiled + LM-head-gated) and the
    serving loops run unmodified.

    ``n_micro``: decode-time micro-batches per macro-step (default = pp).
    Flat BatchConfigs split into ``n_micro`` contiguous token ranges that
    pipeline through the stages; prefill chunks ride whole (successive
    chunks already interleave across stages via async dispatch).

    **Speculative serving composes** (``max_spec_tokens > 0``): each stage
    allocates its layers' spec-tree buffers alongside the committed KV,
    and the host-built ``TreeSearchBatchConfig``/``TreeVerifyBatchConfig``
    batches ride the stage chain WHOLE (like prefill chunks) — the
    tree-verify step is just another batch shape hopping the live-cut
    boundary, so :class:`~.spec_infer.SpecInferManager` drives a
    pipelined target with the draft model co-resident on its own devices
    (the dual-allocator accounting the spec manager already does).  The
    on-device ``SpecDecodeScan`` stays single-program (it calls
    ``_step_impl`` directly); spec × pp serves through the host manager.

    Not yet supported here: the on-device prefill scan — it needs the
    single-program pipelining this multi-program design trades away;
    chunked prefill covers the prompt phase instead.
    """

    # shared with RequestManager like InferenceManager.telemetry; stage
    # dispatches land on per-stage trace tracks ("stage0", "stage1", ...)
    # so a Perfetto export shows the micro-batch interleave per stage
    telemetry = NULL_TELEMETRY
    # seeded chaos hook (serve/resilience.py), synced by the RequestManager.
    # Consulted before every stage dispatch AND every inter-stage hop —
    # faults raise before device work, and retrying a whole macro-step is
    # safe because stage KV writes are positional and value-deterministic
    # (a replayed micro-batch rewrites identical values; see _dispatch).
    fault_injector = None
    # step-level cost attribution (obs/profiler.py), synced by the
    # RequestManager: per-stage dispatch phases (``stage{i}``) time the
    # host-interleaved stage compute, ``hop`` times the inter-stage
    # activation transfer, and every stage program launch counts into the
    # deterministic ``dispatches`` counter.  Host-side only.
    profiler = NULL_PROFILER

    def __init__(
        self,
        model,
        max_requests: int = 8,
        max_tokens_per_batch: int = 64,
        max_seq_len: int = 512,
        n_micro: Optional[int] = None,
        strategy: Optional[Dict[str, Dict]] = None,
        outputs=None,
        use_pallas: str = "auto",
        kv_dtype: Optional[str] = None,
        gate_lm_head: bool = True,
        topk: int = 0,
        kv_page_size: Optional[int] = None,
        max_spec_tokens: int = 0,
    ):
        from ..parallel.mesh import make_mesh

        self.model = model
        self.max_requests = max_requests
        self.max_tokens = max_tokens_per_batch
        self.max_seq_len = max_seq_len
        self.max_spec_tokens = max_spec_tokens
        self.topk = topk
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r} "
                             "(expected None or 'int8')")
        self.kv_dtype = kv_dtype
        mesh = model.mesh
        if mesh is None or "pp" not in mesh.shape:
            raise ValueError("PipelinedInferenceManager needs a mesh with a "
                             "'pp' axis (use InferenceManager for pure TP)")
        shape = dict(mesh.shape)
        pp = shape["pp"]
        tp = shape.get("tp", 1)
        for a, n in shape.items():
            if a not in ("pp", "tp") and n > 1:
                raise ValueError(f"unsupported serve mesh axis {a!r}")
        self.pp = pp
        self.tp = tp
        self.n_micro = int(n_micro) if n_micro else pp
        if self.max_tokens % self.n_micro:
            # micro-batches are contiguous EQUAL token ranges of the
            # compiled capacity, so the count must divide it; fall back to
            # the largest divisor and say so rather than silently running
            # the bubble-dominated schedule the caller asked to avoid
            import warnings

            fixed = max(d for d in range(1, self.n_micro + 1)
                        if self.max_tokens % d == 0)
            warnings.warn(
                f"n_micro={self.n_micro} does not divide "
                f"max_tokens_per_batch={self.max_tokens}; using "
                f"n_micro={fixed}", stacklevel=2)
            self.n_micro = fixed

        register_serve_capacities(model.graph, max_requests, max_seq_len,
                                  max_spec_tokens, kv_dtype)
        if outputs is None:
            out_tids = [model.graph.nodes[-1].outputs[-1]]
        else:
            outputs = outputs if isinstance(outputs, (list, tuple)) \
                else [outputs]
            out_tids = [t.tid for t in outputs]
        self._gate_lm_head = bool(gate_lm_head)
        self._lm_head_marked = (mark_gated_lm_head(
            model.graph, out_tids, max_requests) if gate_lm_head else False)

        # ---- stage meshes: pp-major device slices, tp within a slice ----
        names = list(mesh.axis_names)
        arr = np.asarray(mesh.devices)
        perm = [names.index("pp")] + [i for i, n in enumerate(names)
                                     if n != "pp"]
        arr = arr.transpose(perm).reshape(pp, -1)
        self.stage_meshes = [make_mesh({"tp": tp}, list(arr[s]))
                             for s in range(pp)]
        if strategy is None:
            strategy = tensor_parallel_strategy(
                model.graph, ("tp",), self.stage_meshes[0]) if tp > 1 else {}
        self.strategy = strategy

        split = serve_stage_split(model.graph, pp, out_tids[0])
        plans = build_stage_plans(model.graph, split, strategy,
                                  self.stage_meshes)
        self.stages = [
            _Stage(nodes, entry, exit_, m, plan)
            for (nodes, entry, exit_), m, plan
            in zip(split, self.stage_meshes, plans)
        ]
        self.stage_plans = plans
        self._token_tid = model.graph.input_tids[0]
        # per-stage KVAllocator instances under one deployment-level front:
        # each stage owns ITS caches (always_place — per-stage KV residency
        # is the capacity contract), while admission/preemption/the memory
        # ledger consult the composed allocator exactly like the
        # single-plan manager's.
        stage_kvs = [
            StageKV(stage.nodes, strategy, stage.mesh, max_requests,
                    max_seq_len, max_spec_tokens, always_place=True,
                    label=f"stage{s}")
            for s, stage in enumerate(self.stages)
        ]
        for stage, skv in zip(self.stages, stage_kvs):
            stage.kv = skv
        # paged KV under pp: every stage's buffers share one ROW x SEQ
        # geometry, so ONE logical block table addresses all the per-stage
        # page pools simultaneously — a page id names the same (row,
        # seq-range) in every stage's k/v (+ scale) planes, and a COW copy
        # runs across all of them (kv_paged._copy_page iterates stages).
        self.kv_page_size = kv_page_size
        if kv_page_size:
            from .kv_paged import PagedKVAllocator

            self.kv = PagedKVAllocator(stage_kvs, max_requests, max_seq_len,
                                       page_size=kv_page_size)
        else:
            self.kv = KVAllocator(stage_kvs, max_requests, max_seq_len)

        backend = jax.default_backend()
        self.use_pallas = (backend == "tpu") if use_pallas == "auto" \
            else bool(use_pallas)
        self.pallas_interpret = backend != "tpu"
        self.prefill_tile = pick_prefill_tile(max_tokens_per_batch,
                                              max_seq_len)
        if kv_page_size:
            from .kv_paged import validate_page_tile

            validate_page_tile(kv_page_size, self.prefill_tile)
        self.tree_token_layout = None
        self.prefill_overlap = False  # single-program lever; N/A here

        from ..utils.platform import collective_safe_compiler_options

        n_stages = len(self.stages)
        for s, stage in enumerate(self.stages):
            stage.step = jax.jit(
                self._make_stage_impl(stage, last=(s == n_stages - 1)),
                donate_argnums=(1,),
                compiler_options=collective_safe_compiler_options(stage.mesh),
            )
        last_mesh = self.stages[-1].mesh
        self._advance = jax.jit(
            self._advance_impl, static_argnames=("eos",),
            compiler_options=collective_safe_compiler_options(last_mesh),
        )
        # mid-stretch slot join (on-device continuous batching): a tiny
        # program on the last stage's mesh that activates one batch row
        # between chained scan segments
        self._join = jax.jit(
            self._join_impl, static_argnames=("eos",),
            compiler_options=collective_safe_compiler_options(last_mesh),
        )

    # ------------------------------------------------------------------
    @property
    def gate_lm_head(self) -> bool:
        return self._gate_lm_head and self._lm_head_marked

    @gate_lm_head.setter
    def gate_lm_head(self, value) -> None:
        self._gate_lm_head = bool(value)

    @property
    def params(self):
        """Merged per-node param dict across stages (shared sub-dicts, so
        in-place updates — e.g. ``quantize_int8`` — reach the stages)."""
        if self.stages[0].params is None:
            return None
        merged: Dict[str, Dict] = {}
        for stage in self.stages:
            merged.update(stage.params)
        return merged

    @property
    def state(self):
        """Merged per-node KV state across stages (read-only convenience for
        tests/diagnostics; the live buffers are per stage)."""
        if self.stages[0].state is None:
            return None
        merged: Dict[str, Dict] = {}
        for stage in self.stages:
            merged.update(stage.state)
        return merged

    # ------------------------------------------------------------------
    def _make_stage_impl(self, stage, last: bool):
        fwd = stage.fwd
        entry = tuple(stage.entry_tids)
        token_tid = self._token_tid

        def impl(params, state, bc, xs, sample=None, pages=None):
            base = bc if isinstance(bc, BatchConfig) else bc.base
            if entry == (token_tid,):
                inputs = {token_tid: base.tokens}
            else:
                inputs = dict(zip(entry, xs))
            outs, new_state = fwd(
                params, inputs, state=state,
                extras={
                    "batch_config": bc,
                    "pallas_decode": self.use_pallas,
                    "pallas_interpret": self.pallas_interpret,
                    "tree_layout": None,
                    "qkv0": None,
                    "pages": pages,
                },
            )
            if not last:
                return tuple(outs), new_state
            logits = outs[0].astype(jnp.float32)
            if sample is not None:
                token_ids = sample_tokens(logits, sample)
            else:
                token_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits_max = jnp.max(logits, axis=-1)
            topk_ids = topk_lp = None
            if self.topk:
                lp = jax.nn.log_softmax(logits, axis=-1)
                topk_lp, topk_ids = jax.lax.top_k(lp, self.topk)
                topk_ids = topk_ids.astype(jnp.int32)
            return (
                InferenceResult(token_ids, logits_max, topk_ids, topk_lp),
                new_state,
            )

        return impl

    # ------------------------------------------------------------------
    def init_operators_inference(self, params=None, rng=None, dtype=None):
        graph = self.model.graph
        if params is None:
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            for stage in self.stages:
                only = {n.name for n in stage.nodes}
                # same global key indices as the single-plan init: weights
                # are bit-identical to the non-pp manager with this seed
                stage.params = init_params(graph, stage.plan, rng,
                                           dtype=dtype, only=only)
        else:
            for stage in self.stages:
                sub = {}
                for node in stage.nodes:
                    g = params.get(node.name)
                    if g is None:
                        continue
                    shs = stage.plan.param_shardings.get(node.name, {})
                    placed = {}
                    for pname, arr in g.items():
                        sh = shs.get(pname)
                        tgt = (sh.named_sharding(stage.mesh) if sh is not None
                               else stage.replicated)
                        placed[pname] = jax.device_put(arr, tgt)
                    sub[node.name] = placed
                stage.params = sub
        self.allocate_kv_cache()
        return self

    def allocate_kv_cache(self):
        # the allocator owns every stage's buffers (always_place was baked
        # into each StageKV at construction — per-stage KV residency is
        # the capacity contract of pp serving)
        self.kv.allocate()
        self.kv.reset_attribution()
        return self.state

    def reset(self):
        self.allocate_kv_cache()

    @property
    def plan_key(self) -> str:
        """Deployment coordinates in the serve search's convention."""
        return f"tp{self.tp}_pp{self.pp}_m{self.n_micro}"

    def publish_memory(self, telemetry, key=None) -> None:
        """Predicted-vs-allocated HBM per component into the handle's
        memory ledger — per-DEVICE basis, the SAME composition on both
        sides: per-component max across stages (each component's worst
        chip; components may bind on different stages, so the per-pair
        ratios stay meaningful even when no single chip holds every max).
        ``static_gb`` (weights + KV, the allocatable share) is composed
        per STAGE first, so it is a real binding chip's number.  See
        :meth:`InferenceManager.publish_memory` (also for ``key``)."""
        if telemetry is None or not getattr(telemetry, "enabled", False):
            return
        from ..obs.memory import publish_predicted_parts
        from ..search.simulator import compose_stage_parts, plan_memory_parts

        key = key or self.plan_key
        publish_predicted_parts(
            telemetry, key,
            compose_stage_parts([plan_memory_parts(p, training=False)
                                 for p in self.stage_plans]))
        if self.stages[0].state is None:
            return
        per_stage = [
            (params_nbytes(stage.params),
             stage.kv.allocated_bytes(kv_only=False, per_device=True))
            for stage in self.stages
        ]
        telemetry.memory_plan_allocated(
            key,
            weights_gb=max(w for w, _ in per_stage) / 1e9,
            kv_gb=max(kv for _, kv in per_stage) / 1e9,
            static_gb=max(w + kv for w, kv in per_stage) / 1e9,
        )

    # ------------------------------------------------------------------
    def _microbatches(self, bc):
        if isinstance(bc, BatchConfig):
            return bc.split_microbatches(self.n_micro)
        return [bc]  # prefill chunks / tree batches ride whole

    def _page_view(self):
        """Device-side block table (None = slot-contiguous); ONE logical
        table shared by every stage's page pool."""
        return self.kv.page_view()

    def _dispatch(self, bc, sample=None, mb: int = 0, pages=None):
        """One micro-batch through the stage chain; returns the last
        stage's InferenceResult (device arrays, not synced).

        Telemetry spans cover the HOST dispatch of each stage (async — the
        jit calls return without syncing; device occupancy needs XProf) on
        per-stage tracks; the inter-stage ``device_put`` hop is an instant
        on the receiving stage's track.
        """
        tel = self.telemetry
        prof = self.profiler
        fi = self.fault_injector
        xs: Tuple = ()
        res = None
        n = len(self.stages)
        for s, stage in enumerate(self.stages):
            with tel.span("stage_dispatch", cat="pp", track=f"stage{s}",
                          stage=s, mb=mb), prof.phase(f"stage{s}"):
                if fi is not None:
                    fi.maybe_fail(f"stage{s}_dispatch")
                if s > 0:
                    if fi is not None:
                        fi.maybe_fail(f"stage{s}_hop")
                    tel.instant("stage_hop", cat="pp", track=f"stage{s}",
                                stage=s, mb=mb)
                    if tel.enabled:
                        tel.metrics.counter("pp_hops").inc()
                    with prof.phase("hop"):
                        # the whole hop ships as ONE batched transfer —
                        # batch descriptor, page table and boundary
                        # activations in a single pytree device_put (one
                        # async transfer launch) instead of a host call
                        # per operand
                        bc_s, pg_s, xs = jax.device_put(
                            (bc, pages, xs), stage.replicated)
                else:
                    bc_s, pg_s = jax.device_put((bc, pages),
                                                stage.replicated)
                if prof.enabled:
                    prof.count("dispatches")
                if s < n - 1:
                    xs, stage.state = stage.step(stage.params, stage.state,
                                                 bc_s, xs, None, pg_s)
                else:
                    smp = (jax.device_put(sample, stage.replicated)
                           if sample is not None else None)
                    res, stage.state = stage.step(stage.params, stage.state,
                                                  bc_s, xs, smp, pg_s)
        return res

    @staticmethod
    def _merge_results(results: Sequence[InferenceResult]) -> InferenceResult:
        if len(results) == 1:
            return results[0]
        cat = lambda xs: (None if xs[0] is None
                          else jnp.concatenate(list(xs), axis=0))
        return InferenceResult(
            cat([r.token_ids for r in results]),
            cat([r.logits_max for r in results]),
            cat([r.topk_ids for r in results]),
            cat([r.topk_logprobs for r in results]),
        )

    def step(self, bc, sample=None) -> InferenceResult:
        """Run one serving macro-step: ``n_micro`` interleaved micro-batches
        through the stage chain (async dispatch; stage s runs micro-batch j
        while stage s-1 runs j+1).  Caches update in place per stage."""
        assert self.stages[0].params is not None, \
            "call init_operators_inference() first"
        mbs = self._microbatches(bc)
        tel = self.telemetry
        if tel.enabled:
            # steady-state decode bubble of this macro-step's schedule —
            # the model-side fraction the calibration loop compares against
            # measured stage occupancy (XProf) on device runs
            tel.metrics.gauge("pp_bubble_frac").set(
                max(0, self.pp - len(mbs)) / self.pp)
        pv = self._page_view()
        with tel.span("pp_macro_step", cat="pp", track="pp",
                      n_micro=len(mbs)):
            results = []
            k = self.max_tokens // max(len(mbs), 1)
            for j, mbc in enumerate(mbs):
                smp = sample
                if sample is not None and len(mbs) > 1:
                    if len(sample) > 3:
                        # per-request (rid, token-index) keys: slice the
                        # fold rows to this micro-batch's contiguous token
                        # range — sampled output is then bit-identical to
                        # the single-program step (rows and keys align)
                        key, t, p, folds = sample
                        smp = (key, t, p, folds[j * k: (j + 1) * k])
                    else:
                        # per-micro-batch key: same sampling distribution
                        # as the single-program step, different bitstream
                        key, t, p = sample
                        smp = (jax.random.fold_in(key, j), t, p)
                results.append(self._dispatch(mbc, smp, mb=j, pages=pv))
        return self._merge_results(results)

    # ------------------------------------------------------------------
    @staticmethod
    def _advance_impl(bc, toks, alive, eos_hit, step_i, allowed, eos):
        """The decode-scan body's advance/lifecycle logic (see
        InferenceManager._decode_scan_impl), jitted on the last stage's
        mesh so multi-step decode never syncs the host.

        ``eos_hit`` carries which rows exited via EOS (vs exhausting
        their ``allowed`` budget) for the per-row exit codes; ``allowed``
        (i32 per flat row, or None) freezes each row after ITS budget —
        rows of unequal remaining budgets ride one chained stretch.
        ``step_i`` is the current step's index within the segment (device
        scalar, so one compiled program serves every step)."""
        live = alive
        if eos is not None:
            hit = alive & (toks == eos)
            eos_hit = eos_hit | hit
            alive = alive & ~hit
        if allowed is not None:
            alive = alive & (step_i + 1 < allowed)
        nxt = bc.advance(toks)
        if eos is not None or allowed is not None:
            nxt = BatchConfig(
                tokens=nxt.tokens,
                request_index=jnp.where(alive, nxt.request_index, -1),
                token_position=nxt.token_position,
                num_tokens=nxt.num_tokens,
                seq_lens=nxt.seq_lens,
            )
        return nxt, alive, eos_hit, live

    @staticmethod
    def _join_impl(bc, tok_src, src_idx, dst, slot, pos, seq_len,
                   num_tokens, eos):
        """Activate one batch row from a staged arrival's held prefill
        result (see InferenceManager._join_impl): the row joins pre-frozen
        when the held token already IS the terminator."""
        tok = tok_src[src_idx]
        active = True if eos is None else tok != eos
        return bc.join_row(dst, tok, slot, pos, seq_len, num_tokens,
                           active=active)

    def join_slot(self, bc, tok_src, src_idx, dst, slot, pos, seq_len,
                  num_tokens, eos=None):
        """Splice a mid-stretch arrival into the running (device-resident)
        batch — same contract as InferenceManager.join_slot; the join
        program runs on the last stage's mesh, where the chained scan's
        BatchConfig lives."""
        prof = self.profiler
        if prof.enabled:
            prof.count("dispatches")
        with prof.phase("dispatch"):
            return self._join(
                bc, tok_src, jnp.int32(src_idx), jnp.int32(dst),
                jnp.int32(slot), jnp.int32(pos), jnp.int32(seq_len),
                jnp.int32(num_tokens), eos=eos)

    def decode_scan(self, bc, n_steps: int, eos: Optional[int] = None,
                    sample=None):
        """``n_steps`` pure-decode macro-steps, host-dispatched but never
        host-synced: each micro-batch's next BatchConfig derives on device
        (``_advance_impl``) and flows back to stage 0, so the host only
        reads tokens once at the end.  Micro-batches interleave across
        stages step by step (i-major dispatch order).
        """
        assert self.stages[0].params is not None, \
            "call init_operators_inference() first"
        last = int(np.max(np.asarray(bc.token_position))) + n_steps
        if last > self.max_seq_len:
            raise ValueError(
                f"decode_scan would reach position {last} > max_seq_len "
                f"{self.max_seq_len}")
        mbs = self._microbatches(bc)
        m = len(mbs)
        rep = self.stages[-1].replicated
        mbs = [jax.device_put(mb, rep) for mb in mbs]
        alive = [mb.request_index >= 0 for mb in mbs]
        eos_hit = [jnp.zeros_like(a) for a in alive]
        toks = [[None] * m for _ in range(n_steps)]
        lives = [[None] * m for _ in range(n_steps)]
        tel = self.telemetry
        if tel.enabled:
            tel.metrics.gauge("pp_bubble_frac").set(
                max(0, self.pp - m) / self.pp)
        # one table fetch for the whole scan: the manager pre-mapped every
        # page the n_steps positions can reach (no mid-scan mutation)
        pv = self._page_view()
        for i in range(n_steps):
            with tel.span("pp_decode_macro_step", cat="pp", track="pp",
                          step=i, n_micro=m):
                for j in range(m):
                    smp = None
                    if sample is not None:
                        if len(sample) > 3:
                            key, t, p, folds = sample
                            k = folds.shape[0] // m
                            f = folds[j * k: (j + 1) * k]
                            smp = (key, t, p,
                                   f + jnp.array([0, i], jnp.int32))
                        else:
                            key, t, p = sample
                            smp = (jax.random.fold_in(key, i * m + j), t, p)
                    res = self._dispatch(mbs[j], smp, mb=j, pages=pv)
                    mbs[j], alive[j], eos_hit[j], live = self._advance(
                        mbs[j], res.token_ids, alive[j], eos_hit[j],
                        jnp.int32(i), None, eos=eos)
                    toks[i][j] = res.token_ids
                    lives[i][j] = live
        tokens = np.stack([
            np.concatenate([np.asarray(t) for t in row]) for row in toks
        ])
        live_np = np.stack([
            np.concatenate([np.asarray(v) for v in row]) for row in lives
        ])
        bc_out = self._merge_bcs(mbs)
        return tokens, live_np, bc_out

    def decode_scan_async(self, bc, n_steps: int, eos: Optional[int] = None,
                          sample=None, allowed=None, max_position=None):
        """``n_steps`` pure-decode macro-steps with NOTHING materialized:
        returns LAZY device values — ``(tokens [n, max_tokens], live
        masks, per-row exit codes, advanced BatchConfig)`` — so a chained
        stretch dispatches segment after segment (pp hops included,
        device-to-device) and reads everything back once at stretch end.

        ``allowed`` (i32 per flat row, or None) is each row's step budget
        for THIS segment: the advance freezes a row after its budget, and
        the exit codes report EXIT_EOS vs EXIT_BUDGET vs EXIT_RUNNING per
        row (EXIT_NOT_IN_BATCH for pad/frozen-at-entry rows).

        ``max_position`` is REQUIRED: the host-known largest starting
        token position across rows.  The legacy ``decode_scan`` reads it
        from the batch with ``np.max`` — a host sync the chained path
        cannot afford on a device-resident mid-stretch BatchConfig.
        """
        assert self.stages[0].params is not None, \
            "call init_operators_inference() first"
        assert max_position is not None, \
            "decode_scan_async needs the host-tracked max_position"
        last = max_position + n_steps
        if last > self.max_seq_len:
            raise ValueError(
                f"decode_scan would reach position {last} > max_seq_len "
                f"{self.max_seq_len}")
        fi = self.fault_injector
        if fi is not None:
            fi.maybe_fail("decode_scan")
        mbs = self._microbatches(bc)
        m = len(mbs)
        rep = self.stages[-1].replicated
        mbs = [jax.device_put(mb, rep) for mb in mbs]
        k = self.max_tokens // m
        alw = [None] * m
        if allowed is not None:
            alw_full = jax.device_put(jnp.asarray(allowed, jnp.int32), rep)
            alw = [alw_full[j * k: (j + 1) * k] for j in range(m)]
        # present BEFORE the entry freeze: a present row whose budget is
        # already 0 exits as EXIT_BUDGET, not EXIT_NOT_IN_BATCH
        present0 = [mb.request_index >= 0 for mb in mbs]
        if allowed is not None:
            # entry freeze: a present row with no budget must not write
            # its step-0 KV (the frozen row's writes land in scratch)
            mbs = [BatchConfig(
                tokens=mb.tokens,
                request_index=jnp.where(a > 0, mb.request_index, -1),
                token_position=mb.token_position,
                num_tokens=mb.num_tokens,
                seq_lens=mb.seq_lens,
            ) for mb, a in zip(mbs, alw)]
        alive = [mb.request_index >= 0 for mb in mbs]
        eos_hit = [jnp.zeros_like(a) for a in alive]
        toks = [[None] * m for _ in range(n_steps)]
        lives = [[None] * m for _ in range(n_steps)]
        tel = self.telemetry
        if tel.enabled:
            tel.metrics.gauge("pp_bubble_frac").set(
                max(0, self.pp - m) / self.pp)
        pv = self._page_view()
        for i in range(n_steps):
            with tel.span("pp_decode_macro_step", cat="pp", track="pp",
                          step=i, n_micro=m):
                for j in range(m):
                    smp = None
                    if sample is not None:
                        if len(sample) > 3:
                            key, t, p, folds = sample
                            f = folds[j * k: (j + 1) * k]
                            smp = (key, t, p,
                                   f + jnp.array([0, i], jnp.int32))
                        else:
                            key, t, p = sample
                            smp = (jax.random.fold_in(key, i * m + j), t, p)
                    res = self._dispatch(mbs[j], smp, mb=j, pages=pv)
                    mbs[j], alive[j], eos_hit[j], live = self._advance(
                        mbs[j], res.token_ids, alive[j], eos_hit[j],
                        jnp.int32(i), alw[j], eos=eos)
                    toks[i][j] = res.token_ids
                    lives[i][j] = live
        cat = (lambda xs: xs[0]) if m == 1 else jnp.concatenate
        tokens = jnp.stack([cat(row) for row in toks])
        live_out = jnp.stack([cat(row) for row in lives])
        ecode = cat([
            jnp.where(~present0[j], EXIT_NOT_IN_BATCH,
                      jnp.where(eos_hit[j], EXIT_EOS,
                                jnp.where(alive[j], EXIT_RUNNING,
                                          EXIT_BUDGET))).astype(jnp.int32)
            for j in range(m)])
        return tokens, live_out, ecode, self._merge_bcs(mbs)

    @staticmethod
    def _merge_bcs(mbs: Sequence[BatchConfig]) -> BatchConfig:
        if len(mbs) == 1:
            return mbs[0]
        seq = mbs[0].seq_lens
        for mb in mbs[1:]:
            # each micro-batch advanced only its own slots' depths
            seq = jnp.maximum(seq, mb.seq_lens)
        return BatchConfig(
            tokens=jnp.concatenate([mb.tokens for mb in mbs]),
            request_index=jnp.concatenate([mb.request_index for mb in mbs]),
            token_position=jnp.concatenate(
                [mb.token_position for mb in mbs]),
            num_tokens=sum(mb.num_tokens for mb in mbs),
            seq_lens=seq,
        )

    # ------------------------------------------------------------------
    def stage_memory_bytes(self, training: bool = False) -> List[float]:
        """Per-stage ``plan_memory_bytes`` — the capacity arithmetic the
        serve search gates pp admissibility with (weights + KV + largest
        transient, per device of each stage)."""
        from ..search.simulator import plan_memory_bytes

        return [plan_memory_bytes(p, training=training)
                for p in self.stage_plans]
