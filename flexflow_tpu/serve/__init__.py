"""FlexFlow-TPU Serve: LLM serving runtime.

Reference stack: ``src/runtime/{request_manager,inference_manager,
batch_config}.cc`` + ``inference/models/*`` + ``python/flexflow/serve``.
"""

from .batch_config import (
    BatchConfig,
    InferenceResult,
    PrefillBatchConfig,
    TreeSearchBatchConfig,
    TreeVerifyBatchConfig,
    MAX_NUM_REQUESTS,
    MAX_NUM_TOKENS,
    MAX_SPEC_TREE_TOKENS,
)
from .inference_manager import (
    InferenceManager,
    searched_serve_strategy,
    tensor_parallel_strategy,
)
from .kv_paged import PagedKVAllocator, PagePoolExhausted, PageTable
from .models.base import MODEL_REGISTRY, ServeModelConfig, build_model
from .ops import (
    IncMultiHeadSelfAttention,
    SpecIncMultiHeadSelfAttention,
    TreeIncMultiHeadSelfAttention,
)
from .pp import (
    PipelinedInferenceManager,
    build_stage_plans,
    serve_stage_split,
)
from .request_manager import (
    GenerationConfig,
    Request,
    RequestManager,
    RequestStatus,
    TERMINAL_STATUSES,
)
from .resilience import (
    FaultInjector,
    InjectedFault,
    ResilienceConfig,
    RetryPolicy,
    TransientServeError,
)
from .migration import (
    MigrationConfig,
    MigrationController,
    MigrationRollback,
    build_deployment,
)
from .fleet import FleetConfig, FleetRouter, ReplicaState
from .slo import (
    BrownoutConfig,
    BrownoutController,
    BrownoutLevel,
    SLOClass,
    SLOPolicy,
)
from .spec_infer import SpecInferManager
from .api import LLM, SSM
from .weights import convert_state_dict, load_hf_model, place_params
from .quant import annotate_int8, quantize_int8

from . import models  # noqa: F401  (registers model builders)

__all__ = [
    "BatchConfig",
    "PrefillBatchConfig",
    "TreeSearchBatchConfig",
    "TreeVerifyBatchConfig",
    "InferenceResult",
    "InferenceManager",
    "PipelinedInferenceManager",
    "serve_stage_split",
    "build_stage_plans",
    "tensor_parallel_strategy",
    "searched_serve_strategy",
    "RequestManager",
    "Request",
    "RequestStatus",
    "TERMINAL_STATUSES",
    "GenerationConfig",
    "ResilienceConfig",
    "RetryPolicy",
    "FaultInjector",
    "InjectedFault",
    "TransientServeError",
    "SpecInferManager",
    "MigrationController",
    "MigrationConfig",
    "MigrationRollback",
    "build_deployment",
    "FleetRouter",
    "FleetConfig",
    "ReplicaState",
    "SLOClass",
    "SLOPolicy",
    "BrownoutLevel",
    "BrownoutConfig",
    "BrownoutController",
    "LLM",
    "SSM",
    "convert_state_dict",
    "load_hf_model",
    "place_params",
    "quantize_int8",
    "annotate_int8",
    "ServeModelConfig",
    "build_model",
    "MODEL_REGISTRY",
    "IncMultiHeadSelfAttention",
    "SpecIncMultiHeadSelfAttention",
    "TreeIncMultiHeadSelfAttention",
]
