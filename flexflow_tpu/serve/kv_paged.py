"""Paged KV cache with copy-on-write prefix sharing.

The capacity multiplier the ROADMAP names: through r12 KV is
slot-contiguous — every bound slot reserves ``max_seq_len`` positions of
which only the live prefix is occupied, so high-occupancy serving
fragments HBM and every request re-prefills its own copy of a fleet-wide
system prompt.  This module brings the vLLM/PagedAttention block-table
design (Kwon et al., SOSP'23) and SGLang/RadixAttention-style prefix
reuse (Zheng et al.) to the TPU serve stack, **behind the exact r12
KVAllocator interface** (``bind``/``observe``/``release``/
``bytes_per_token``/``capacity_bytes``), so admission control, preemption
pricing, the serve search, and the memory ledger keep consulting one
arithmetic:

* **Physical layout is unchanged.**  The cache buffers stay the
  ``[max_requests+1, KV, S_pad, D]`` arrays the jitted step donates; the
  allocator reinterprets each row's seq axis as ``S_pad / page_size``
  fixed pages, so the pool holds ``(R+1) * S_pad / page_size`` pages and
  a page id ``pid`` addresses ``(row, slot) = divmod(pid, pages_per_row)``
  in EVERY buffer of every stage simultaneously (one logical table; the
  per-stage pools are the per-stage physical planes, exactly the pp
  capacity contract).  The int8 scale planes ``[rows, KV, S]`` page
  alongside K/V — same (row, seq-range) coordinates, no separate table.
* **Block-table indirection, not data movement.**  A per-cache-row table
  ``i32[R+1, pages_per_row]`` maps logical page -> physical page.  The
  Pallas decode/prefill/tree kernels gather the page base per kv-chunk
  through a scalar-prefetched copy of the table
  (``ops/pallas/attention.py``); the KV write paths and the gather
  fallback translate (row, position) through the same table on device
  (``serve/ops.py``).  Masks and positions stay logical, the fetched
  values are identical, so the paged path is BIT-IDENTICAL to the
  slot-contiguous path — the correctness contract tests/test_kv_paged.py
  pins across decode/prefill/mixed/pp2/int8/spec.
* **On-demand pages.**  ``prepare_write(rid, lo, hi)`` (called by the
  RequestManager before every dispatch that writes) maps missing pages
  from the free pool, so a request holds ``ceil(live/page)`` pages
  instead of a ``max_seq_len`` span — ``kv_fragmentation_frac`` collapses
  from the slot-reservation waste to intra-page tail waste (~0, the
  headline before/after metric in ``obs/memory.py``).  Pool exhaustion
  raises :class:`PagePoolExhausted`; under ``ResilienceConfig.preemption``
  the manager preempts a victim, whose pages free page-granularly.
* **Refcounted copy-on-write prefix sharing.**  Pages are keyed by a
  chained hash of the page-aligned token prefix that produced them (KV at
  a position is a pure function of the token prefix), plus a
  partial-tail entry for the final non-aligned page.  ``bind`` maps the
  longest registered chain into the new request's table (refcount++), so
  N requests sharing a system prompt prefill it ONCE — the
  RequestManager starts the newcomer's prefill at the cached offset and
  TTFT collapses to the unshared suffix.  A write into a page another
  request maps (``req_refs >= 2``) copies the page first (all stages, k/v
  + int8 scales) and remaps the writer — divergence mid-decode lands on a
  private copy while sharers keep the original.  The index itself holds a
  reference so shared pages outlive their creator; index-only pages are
  the eviction pool (LRU) when free pages run out.

Why writes never corrupt a sharer: a request only ever READS positions at
or below its own causal frontier, and it WRITES every position from its
cached offset upward itself (prefill then decode, gapless); positions a
mapped page carries beyond the matched prefix are therefore always masked
(future) or already rewritten by the reader itself — and rewrites of
matched positions store bit-identical values (same tokens, same
positions, deterministic projection + quantizer).  COW is required
exactly when TWO requests would interleave writes into one physical page.

Everything here is host-side bookkeeping plus host-ORCHESTRATED device
ops (the COW page copy, the table transfer); no policy decision is traced
into a jitted program.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .kv_allocator import KV_BUFFER_NAMES, KVAllocator, StageKV


class PagePoolExhausted(RuntimeError):
    """No free page and nothing evictable: the pool is over-committed.
    RequestManager._kv_prepare turns this into page-pressure preemption
    when ``ResilienceConfig.preemption`` is on; otherwise it propagates
    (an admission gate sized with ``round_need`` prevents it)."""


class HostTierCorruption(RuntimeError):
    """A host-tier page failed its checksum on restore.  NOT retryable
    (the host copy itself is damaged): the caller drops the entry and
    falls back to the r9 recompute feed, which is bit-identical by
    construction — swap is an optimization the correctness contract
    never depends on."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PageTable:
    """The device-side view of the block table, shipped with each step
    (``extras["pages"]``).  ``table[row, logical_page] = pid``;
    ``divmod(pid, pages_per_row)`` addresses the physical (row, page-slot)
    in every cache buffer.  Registered as a pytree so it rides jit args;
    the static fields key compilation like PrefillBatchConfig.tile_size."""

    table: Any                     # i32[R+1, pages_per_row]
    page_size: int = dataclasses.field(metadata=dict(static=True))
    pages_per_row: int = dataclasses.field(metadata=dict(static=True))


class _Entry:
    """One prefix-index record: a physical page whose content is keyed by
    the token prefix that produced it.  ``tokens`` is the page's actual
    registered token content — lookups VERIFY it (the chained hash is a
    lookup accelerator, not a trust anchor: Python's int-tuple hash is
    non-cryptographic, and a silent collision would map another prompt's
    KV into an unrelated request)."""

    __slots__ = ("pid", "lru", "tokens")

    def __init__(self, pid: int, lru: int, tokens: Tuple[int, ...]):
        self.pid = pid
        self.lru = lru
        self.tokens = tokens


class _HostPage:
    """One page's content copied to host DRAM: the per-buffer blocks in
    the allocator's deterministic ``_page_blocks`` walk order, plus a
    CRC32 over all of them.  The checksum is verified on EVERY restore —
    a corrupt host copy must fall back to recompute, never upload."""

    __slots__ = ("blocks", "crc", "nbytes")

    def __init__(self, blocks: List[np.ndarray], crc: int, nbytes: int):
        self.blocks = blocks
        self.crc = crc
        self.nbytes = nbytes

    def verify(self) -> bool:
        crc = 0
        for blk in self.blocks:
            crc = zlib.crc32(np.ascontiguousarray(blk).tobytes(), crc)
        return crc == self.crc

    def corrupt_for_test(self) -> None:
        """Flip one byte of the first block WITHOUT updating the checksum
        (chaos-test hook: a restore must detect this and recompute)."""
        raw = bytearray(np.ascontiguousarray(self.blocks[0]).tobytes())
        raw[0] ^= 0xFF
        self.blocks[0] = np.frombuffer(
            bytes(raw), dtype=self.blocks[0].dtype
        ).reshape(self.blocks[0].shape)


class _Spill:
    """One preempted/evicted request's spilled pages: logical pages
    ``[0, ceil(hi/page_size))`` of its row, the fed-token prefix that
    produced them (the content-identity witness restore verifies), and
    the write frontier ``hi`` the restore resumes at."""

    __slots__ = ("pages", "tokens", "hi", "nbytes", "lru")

    def __init__(self, pages: List[_HostPage], tokens: List[int], hi: int):
        self.pages = pages
        self.tokens = tokens
        self.hi = hi
        self.nbytes = sum(p.nbytes for p in pages)
        self.lru = 0


class _Demoted:
    """One prefix-index page demoted to the host tier instead of being
    forgotten at LRU eviction: content + the entry's token identity and
    protected extent, so a later bind can promote it back as if the
    index had never evicted it."""

    __slots__ = ("page", "tokens", "protected", "lru")

    def __init__(self, page: _HostPage, tokens: Tuple[int, ...],
                 protected: int):
        self.page = page
        self.tokens = tokens
        self.protected = protected
        self.lru = 0


class HostPageTier:
    """Bounded host-DRAM pool under :class:`PagedKVAllocator`: holds
    spilled request pages (``_Spill`` per rid) and demoted prefix-index
    pages (``_Demoted`` per index key) with ONE LRU across both kinds.

    Capacity is enforced at admission: storing a unit evicts
    least-recently-used units until it fits; a unit larger than the
    whole tier is refused (the caller falls back to recompute — the
    correctness contract never depends on a store succeeding).  Host
    numpy only (device pinning is a real-TPU nicety the CPU/test path
    has no analogue for); nothing here is traced into a jitted program,
    so attaching a tier can never change serve outputs.

    ``signature`` is the owning allocator's :meth:`PagedKVAllocator.
    swap_signature` — migration/fleet readmission adopts entries onto a
    successor allocator only when the signatures match exactly (same
    page geometry, same per-page buffer shapes/dtypes)."""

    def __init__(self, capacity_bytes: int, signature: Tuple = ()):
        self.capacity_bytes = int(capacity_bytes)
        self.signature = signature
        self.bytes_used = 0
        self.evictions = 0
        self._spills: Dict[int, _Spill] = {}
        self._demoted: Dict[Tuple, _Demoted] = {}
        self._lru_tick = 0

    def _stamp(self, unit) -> None:
        self._lru_tick += 1
        unit.lru = self._lru_tick

    def _unit_bytes(self, unit) -> int:
        return unit.nbytes if isinstance(unit, _Spill) else unit.page.nbytes

    def _make_room(self, need: int) -> bool:
        if need > self.capacity_bytes:
            return False
        while self.bytes_used + need > self.capacity_bytes:
            units = [(s.lru, 0, rid) for rid, s in self._spills.items()]
            units += [(d.lru, 1, key) for key, d in self._demoted.items()]
            if not units:
                return False
            _, kind, key = min(units)
            if kind == 0:
                self.drop_spill(key)
            else:
                self.drop_demoted(key)
            self.evictions += 1
        return True

    # ---- spilled requests --------------------------------------------
    def put_spill(self, rid: int, spill: _Spill) -> bool:
        self.drop_spill(rid)
        if not self._make_room(spill.nbytes):
            return False
        self._spills[int(rid)] = spill
        self.bytes_used += spill.nbytes
        self._stamp(spill)
        return True

    def get_spill(self, rid: int) -> Optional[_Spill]:
        s = self._spills.get(int(rid))
        if s is not None:
            self._stamp(s)
        return s

    def drop_spill(self, rid: int) -> None:
        s = self._spills.pop(int(rid), None)
        if s is not None:
            self.bytes_used -= s.nbytes

    def pop_spill(self, rid: int) -> Optional[_Spill]:
        s = self._spills.pop(int(rid), None)
        if s is not None:
            self.bytes_used -= s.nbytes
        return s

    # ---- demoted index pages -----------------------------------------
    def put_demoted(self, key: Tuple, rec: _Demoted) -> bool:
        self.drop_demoted(key)
        if not self._make_room(rec.page.nbytes):
            return False
        self._demoted[key] = rec
        self.bytes_used += rec.page.nbytes
        self._stamp(rec)
        return True

    def get_demoted(self, key: Tuple) -> Optional[_Demoted]:
        d = self._demoted.get(key)
        if d is not None:
            self._stamp(d)
        return d

    def drop_demoted(self, key: Tuple) -> None:
        d = self._demoted.pop(key, None)
        if d is not None:
            self.bytes_used -= d.page.nbytes

    # ---- occupancy ----------------------------------------------------
    def pages_held(self) -> int:
        return (sum(len(s.pages) for s in self._spills.values())
                + len(self._demoted))

    def snapshot(self) -> Dict:
        return {
            "host_pages": self.pages_held(),
            "host_bytes": self.bytes_used,
            "host_capacity_bytes": self.capacity_bytes,
            "host_spilled_requests": len(self._spills),
            "host_evictions": self.evictions,
        }


def validate_page_tile(page_size: int, prefill_tile: int) -> None:
    """Construction-time contract shared by both managers: the tiled
    prefill path writes each tile as ONE block DUS, so a tile straddling
    a page boundary would scatter across two physical pages — fail here,
    not inside a kernel grid (sibling of the page/max_seq_len asserts)."""
    if page_size and page_size % prefill_tile:
        raise ValueError(
            f"kv_page_size {page_size} must be a multiple of the "
            f"prefill tile {prefill_tile} (tile-aligned block KV "
            "writes must not straddle a page boundary)")


def _common_prefix_len(a: Sequence[int], b: Sequence[int]) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class PagedKVAllocator(KVAllocator):
    """Block-table KV allocation with refcounted COW prefix sharing.

    Drop-in behind the r12 interface; see the module docstring for the
    design.  ``page_size`` defaults to 512 — the int8 dequant-fused
    kernel's block fetch granularity, so a kernel seq-block is exactly
    one page at production shapes.
    """

    paged = True

    def __init__(self, stages: Sequence[StageKV], max_requests: int,
                 max_seq_len: int, page_size: int = 512):
        super().__init__(stages, max_requests, max_seq_len)
        # satellite (mirror of the r6 prefill_tile divisibility fix): the
        # page geometry is validated HERE, at construction, instead of
        # failing deep inside a Pallas kernel grid — the page must tile
        # both the logical span (max_seq_len) and the 128-lane-padded
        # physical seq axis the buffers actually allocate.
        s_pad = -(-max_seq_len // 128) * 128
        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        if max_seq_len % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq_len "
                f"{max_seq_len} (a request's logical span is whole pages)")
        if s_pad % page_size:
            raise ValueError(
                f"page_size {page_size} must divide the 128-lane-padded "
                f"cache seq axis {s_pad} (the physical pool is carved from "
                "the padded buffers; a non-dividing page would straddle "
                "the pad boundary inside the kernel grid)")
        self.page_size = int(page_size)
        self.seq_pad = s_pad
        self.pages_per_row = s_pad // page_size
        self.n_pages = (max_requests + 1) * self.pages_per_row
        # row max_requests is the pad-token scratch row; ONE page of it
        # stays permanently reserved as the scratch page every unmapped
        # table entry points at (reads are causally masked, writes are
        # discarded pad-token garbage) — the rest of the scratch row's
        # pages join the pool, which is why the paged pool's capacity
        # exceeds the slot-contiguous R * max_seq_len.
        self.scratch_pid = max_requests * self.pages_per_row
        # prefix-sharing / lifecycle counters (cumulative; snapshot()
        # publishes them through the paged gauge vocabulary)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_reused = 0
        self.cow_copies = 0
        self.pages_evicted = 0
        # cumulative table-mapping count (every real page mapped into a
        # request's row, incl. COW destinations and prefix-reuse binds) —
        # the StepProfiler polls this into its deterministic
        # ``pages_mapped`` work counter (obs/profiler.py)
        self.pages_mapped = 0
        # host-tier swap counters (cumulative; the tier regression class
        # in bench_compare).  The tier itself is attached explicitly
        # (attach_host_tier) and survives allocate()/teardown(): KV at a
        # position is a pure function of the fed token prefix, so a host
        # copy stays valid across buffer reallocation.
        self.host_tier: Optional[HostPageTier] = None
        self.pages_spilled = 0
        self.pages_restored = 0
        self.swap_bytes = 0
        self.restore_failures = 0
        self.recompute_tokens_saved = 0
        self._init_pool()

    # ------------------------------------------------------------------
    def _init_pool(self) -> None:
        self._table = np.full((self.max_requests + 1, self.pages_per_row),
                              self.scratch_pid, np.int32)
        self._req_refs = np.zeros(self.n_pages, np.int32)
        self._idx_refs = np.zeros(self.n_pages, np.int32)
        # LIFO free pool, low pids first out (deterministic)
        self._free: List[int] = [p for p in range(self.n_pages - 1, -1, -1)
                                 if p != self.scratch_pid]
        self._slot_of: Dict[int, int] = {}
        self._chain: Dict[int, Dict] = {}
        # prefix index: ("f", chain_hash) -> full-page entry;
        # ("p", chain_hash, tail_tuple) -> partial-tail entry.
        # _partial_by_base buckets the partial keys per chain hash so a
        # bind's tail lookup scans its own bucket, not the whole index.
        self._entries: Dict[Tuple, _Entry] = {}
        self._partial_by_base: Dict[int, List[Tuple]] = {}
        self._key_of_pid: Dict[int, Tuple] = {}
        # pid -> protected extent (page offsets [0, n) whose content the
        # index vouches for): a write into a protected range by ANYONE
        # must copy-on-write, or the index would serve corrupted KV to
        # later matching binds (a sole-holder sharer diverging inside the
        # registered range is the dangerous case — see prepare_slot_span)
        self._protected: Dict[int, int] = {}
        self._lru_tick = 0
        self._device_table = None

    def allocate(self):
        """(Re)allocate zeroed buffers AND reset the page pool: zeroed
        caches invalidate every indexed page's content, so the prefix
        index must not survive a reallocation."""
        out = super().allocate()
        self._init_pool()
        return out

    def reset_attribution(self) -> None:
        """New serving session over the SAME buffers (rids restart): every
        request mapping releases, but the prefix index stays — its pages'
        content is still valid, so a fleet-wide prompt survives manager
        turnover (the whole point of index-held references)."""
        for rid in list(self._slot_of):
            self.release(rid)
        super().reset_attribution()

    # ------------------------------------------------------------------
    def _touch(self, key: Tuple) -> None:
        self._lru_tick += 1
        self._entries[key].lru = self._lru_tick

    def _invalidate_device(self) -> None:
        self._device_table = None

    def page_view(self) -> PageTable:
        """Device-side table pytree (cached; rebuilt after any mutation)."""
        if self._device_table is None:
            import jax.numpy as jnp

            self._device_table = PageTable(
                table=jnp.asarray(self._table),
                page_size=self.page_size,
                pages_per_row=self.pages_per_row,
            )
        return self._device_table

    # ---- pool primitives ----------------------------------------------
    def _alloc_page(self) -> int:
        if self._free:
            return self._free.pop()
        # evict least-recently-used index-only pages (no request maps them)
        victims = sorted(
            (e.lru, key) for key, e in self._entries.items()
            if self._req_refs[e.pid] == 0)
        if not victims:
            raise PagePoolExhausted(
                f"page pool exhausted: {self.n_pages - 1} pages all held by "
                "live requests (admission should gate on round_need; "
                "enable ResilienceConfig.preemption for page-pressure "
                "eviction)")
        _, key = victims[0]
        # demote the victim to the host tier before forgetting it: a
        # later bind matching the same chain promotes it back instead of
        # re-prefilling.  Full-page entries only — a partial tail is one
        # sub-page of recompute, not worth a tier slot.
        if self.host_tier is not None and key[0] == "f":
            e = self._entries[key]
            rec = _Demoted(self._read_page(e.pid), e.tokens,
                           int(self._protected.get(e.pid, self.page_size)))
            if self.host_tier.put_demoted(key, rec):
                self.pages_spilled += 1
                self.swap_bytes += rec.page.nbytes
        self._drop_entry(key)
        self.pages_evicted += 1
        return self._free.pop()

    def _drop_entry(self, key: Tuple) -> None:
        e = self._entries.pop(key)
        if key[0] == "p":
            bucket = self._partial_by_base.get(key[1], [])
            if key in bucket:
                bucket.remove(key)
            if not bucket:
                self._partial_by_base.pop(key[1], None)
        self._key_of_pid.pop(e.pid, None)
        self._protected.pop(e.pid, None)
        self._idx_refs[e.pid] = 0
        if self._req_refs[e.pid] == 0:
            self._free.append(e.pid)

    def _map(self, slot: int, k: int, pid: int) -> None:
        self._table[slot, k] = pid
        self._req_refs[pid] += 1
        self.pages_mapped += 1
        self._invalidate_device()

    def _unmap(self, slot: int, k: int) -> None:
        pid = int(self._table[slot, k])
        if pid == self.scratch_pid:
            return
        self._table[slot, k] = self.scratch_pid
        self._req_refs[pid] -= 1
        if self._req_refs[pid] == 0 and self._idx_refs[pid] == 0:
            self._free.append(pid)
        self._invalidate_device()

    def _copy_page(self, src: int, dst: int) -> None:
        """Device copy of one page's content (k/v + int8 scale planes)
        across EVERY stage's buffers — the COW data move.  Host-orchestrated
        lax slice/update with concrete indices; the updated arrays re-bind
        into the stage state dicts the next jitted step donates."""
        ps = self.page_size
        sr, ss = divmod(src, self.pages_per_row)
        dr, ds = divmod(dst, self.pages_per_row)
        for stage in self.stages:
            state = stage.state
            if not state:
                continue
            for bufs in state.values():
                for name in list(bufs):
                    if name not in KV_BUFFER_NAMES:
                        continue
                    arr = bufs[name]
                    tail = (0,) * (arr.ndim - 3)
                    blk = jax.lax.dynamic_slice(
                        arr, (sr, 0, ss * ps) + tail,
                        (1, arr.shape[1], ps) + arr.shape[3:])
                    bufs[name] = jax.lax.dynamic_update_slice(
                        arr, blk, (dr, 0, ds * ps) + tail)

    # ---- the r12 interface, page-granular -----------------------------
    def bind(self, rid: int, slot: Optional[int] = None, tokens=None,
             need: Optional[int] = None, align: int = 1,
             **_) -> Optional[Dict]:
        """Map a request into the table, reusing every registered prefix
        page its fed-token sequence matches.

        ``slot``: the cache row (required for mapping; a bare ``bind(rid)``
        degrades to attribution-only, the base behavior).  ``tokens``: the
        sequence prefill will feed (prompt, or prompt+generated on
        preemption readmission — KV is a pure function of it, so the chain
        hash covers recompute reuse too).  ``align``: the prefill tile —
        the returned ``cached_tokens`` is rounded down to it so the tiled
        prefill path's tile-aligned-start contract holds when the manager
        resumes feeding at the cached offset.

        Returns ``{"cached_tokens", "hit_pages"}``; ``cached_tokens`` is
        capped at ``len(tokens) - 1`` so the final fed position is always
        recomputed (its logits are the first-token sample point).
        """
        super().bind(rid)
        if slot is None:
            return None
        rid, slot = int(rid), int(slot)
        self._slot_of[rid] = slot
        toks = [int(t) for t in (tokens or [])]
        ps = self.page_size
        hashes: List[int] = []
        h = 0
        for k in range(len(toks) // ps):
            h = hash((h, tuple(toks[k * ps:(k + 1) * ps])))
            hashes.append(h)
        info = {"tokens": toks, "hashes": hashes, "written_hi": 0,
                "registered": 0, "tail_done": False}
        self._chain[rid] = info

        # longest registered full-page chain — each hit VERIFIES the
        # entry's stored tokens against the bind's own page (the chained
        # hash only routes the lookup; a non-cryptographic collision must
        # read as a miss, never as someone else's KV)
        hit_pids: List[int] = []
        for k, h_k in enumerate(hashes):
            e = self._entries.get(("f", h_k))
            if e is None and self.host_tier is not None:
                # promotion: a page the index evicted may still sit in
                # the host tier — checksum-verify and re-register it so
                # the chain keeps matching (as if never evicted)
                e = self._promote_full(
                    ("f", h_k), tuple(toks[k * ps:(k + 1) * ps]))
            if e is None or e.tokens != tuple(toks[k * ps:(k + 1) * ps]):
                break
            hit_pids.append(e.pid)
        cached_pages = len(hit_pids)
        cached = cached_pages * ps
        # partial-tail extension under the last matched chain hash: the
        # best entry is the one sharing the longest token prefix with the
        # remaining feed (content beyond the match is causally masked for
        # the reader — see the module docstring's safety argument)
        h_base = hashes[cached_pages - 1] if cached_pages else 0
        part_pid, best_c, part_key = None, 0, None
        for key in self._partial_by_base.get(h_base, ()):
            c = _common_prefix_len(key[2], toks[cached:])
            if c > best_c:
                best_c, part_pid, part_key = c, self._entries[key].pid, key
        usable = cached + best_c
        if toks:
            usable = min(usable, len(toks) - 1)
        if align > 1:
            usable -= usable % align
        if usable <= 0:
            if toks:  # a tokenless bind (attribution/on-demand pages
                      # only, e.g. the spec draft cache) is not a miss
                self.prefix_misses += 1
            return {"cached_tokens": 0, "hit_pages": 0}
        # map only the pages the resumed feed READS (those overlapping
        # [0, usable)); the page containing the resume point will be
        # partially re-fed — value-identical rewrites, COW if contended
        n_full = min(cached_pages, -(-usable // ps))
        for k in range(n_full):
            self._map(slot, k, hit_pids[k])
            self._touch(("f", hashes[k]))
        mapped = n_full
        if part_pid is not None and usable > cached:
            self._map(slot, cached_pages, part_pid)
            self._touch(part_key)
            mapped += 1
        info["written_hi"] = usable
        self.prefix_hits += 1
        self.prefix_tokens_reused += usable
        return {"cached_tokens": usable, "hit_pages": mapped}

    def _register(self, rid: int, info: Optional[Dict]) -> None:
        """Publish ``rid``'s finished pages into the prefix index: full
        pages once their span is written, the partial tail once the whole
        fed sequence is written (its content is then exactly the fed
        tokens — later decode writes only dirty positions BEYOND the
        matchable range, which lookups never trust)."""
        if info is None:
            return
        slot = self._slot_of.get(rid)
        if slot is None:
            return
        ps = self.page_size
        wh = info["written_hi"]
        hashes = info["hashes"]
        while (info["registered"] < len(hashes)
               and (info["registered"] + 1) * ps <= wh):
            k = info["registered"]
            self._register_entry(
                ("f", hashes[k]), int(self._table[slot, k]),
                tuple(info["tokens"][k * ps:(k + 1) * ps]), ps)
            info["registered"] += 1
        n_full = len(hashes)
        tail = tuple(info["tokens"][n_full * ps:])
        if (not info["tail_done"] and tail and wh >= len(info["tokens"])
                and info["registered"] == n_full
                and n_full < self.pages_per_row):
            h_base = hashes[-1] if hashes else 0
            self._register_entry(("p", h_base, tail),
                                 int(self._table[slot, n_full]),
                                 tail, len(tail))
            info["tail_done"] = True

    def _register_entry(self, key: Tuple, pid: int,
                        tokens: Tuple[int, ...], protected: int) -> None:
        """``protected``: page offsets [0, n) whose content the entry
        vouches for — any later write below it copy-on-writes (see
        prepare_slot_span)."""
        if pid == self.scratch_pid:
            return
        if key in self._entries or pid in self._key_of_pid:
            return  # same content already indexed, or page already keyed
        self._lru_tick += 1
        self._entries[key] = _Entry(pid, self._lru_tick, tokens)
        if key[0] == "p":
            self._partial_by_base.setdefault(key[1], []).append(key)
        self._key_of_pid[pid] = key
        self._idx_refs[pid] = 1
        self._protected[pid] = int(protected)

    def prepare_write(self, rid: int, lo: int, hi: int) -> None:
        """Make positions ``[lo, hi)`` of ``rid``'s row writable: allocate
        unmapped logical pages from the pool, copy-on-write pages another
        request maps.  Also the registration hook — content below the
        request's write frontier is final exactly here, BEFORE the next
        dispatch's writes, so pages publish with deterministic timing
        (a request's tail page registers at its first decode-write
        prepare; its own next write then COWs it away if someone mapped
        it meanwhile — divergence-mid-decode)."""
        rid = int(rid)
        slot = self._slot_of.get(rid)
        info = self._chain.get(rid)
        if slot is None or hi <= lo:
            return
        self._register(rid, info)
        self.prepare_slot_span(slot, lo, hi)
        if info is not None and hi > info["written_hi"]:
            info["written_hi"] = int(hi)

    def prepare_slot_span(self, slot: int, lo: int, hi: int) -> None:
        """Slot-addressed page mapping + COW for writes at ``[lo, hi)`` —
        the rid-less half of :meth:`prepare_write`, used directly by the
        on-device spec scan (which advances committed depths without
        per-step host boundaries, so it prepares each slot's worst-case
        span up front and skips the prefix-registration hook).

        COW fires when (a) another REQUEST maps the page, or (b) the
        write starts inside an index entry's PROTECTED extent.  (b) is
        load-bearing even for a sole holder: a request that mapped a
        registered page on a SHORTER match than the entry's (its tokens
        diverge inside the protected range) would otherwise overwrite
        content the index still vouches for, silently corrupting every
        later bind that matches the full entry.  A registrant's own
        forward writes start AT the protected boundary (offset ==
        extent), so the common decode path never pays the copy.
        """
        if hi <= lo:
            return
        ps = self.page_size
        for k in range(int(lo) // ps,
                       min((int(hi) - 1) // ps, self.pages_per_row - 1) + 1):
            pid = int(self._table[slot, k])
            if pid == self.scratch_pid:
                self._map(slot, k, self._alloc_page())
                continue
            off_lo = max(int(lo) - k * ps, 0)  # first written page offset
            protected = (self._protected.get(pid, 0)
                         if self._idx_refs[pid] else 0)
            if self._req_refs[pid] > 1 or off_lo < protected:
                dst = self._alloc_page()
                self._copy_page(pid, dst)
                self._unmap(slot, k)
                self._map(slot, k, dst)
                self.cow_copies += 1

    def release(self, rid: int, tokens: Optional[int] = None) -> float:
        """Unmap every page of the request's row (refcount--, zero-ref
        unindexed pages return to the pool) after a final registration
        pass, so a completed request's shareable prefix outlives it."""
        rid = int(rid)
        info = self._chain.pop(rid, None)
        if info is not None:
            self._register(rid, info)  # before the slot mapping drops
        slot = self._slot_of.pop(rid, None)
        if slot is not None:
            for k in range(self.pages_per_row):
                self._unmap(slot, k)
        return super().release(rid, tokens)

    def teardown(self):
        """Base teardown (release attribution + drop buffers) PLUS a page
        pool + prefix-index reset: unlike ``reset_attribution`` (same
        buffers, index content still valid), the buffers are gone here,
        so an index entry surviving would vouch for KV that no longer
        exists — the migration-retirement analogue of ``allocate``'s
        index invalidation."""
        leaked = super().teardown()
        self._init_pool()
        return leaked

    # ---- host-tier spill / restore ------------------------------------
    def attach_host_tier(self, capacity_bytes: int) -> Optional[HostPageTier]:
        """Attach a bounded host-DRAM tier (``ResilienceConfig.
        host_tier_bytes``).  Idempotent; 0/negative capacity detaches."""
        if capacity_bytes and int(capacity_bytes) > 0:
            if (self.host_tier is None
                    or self.host_tier.capacity_bytes != int(capacity_bytes)):
                self.host_tier = HostPageTier(int(capacity_bytes))
        else:
            self.host_tier = None
        return self.host_tier

    def _kv_buffers(self):
        """Deterministic (stage, node, buffer) walk over every KV plane —
        ONE ordering shared by spill capture, restore upload, and
        ``swap_signature``, so a host page's block list lines up with the
        buffers it re-enters."""
        for stage in self.stages:
            state = stage.state
            if not state:
                continue
            for node in sorted(state):
                bufs = state[node]
                for name in sorted(n for n in bufs
                                   if n in KV_BUFFER_NAMES):
                    yield bufs, name

    def swap_signature(self) -> Tuple:
        """Page-content compatibility key: page geometry plus every KV
        buffer's per-page block shape and dtype, in walk order.  Two
        allocators with equal signatures can exchange host pages
        (migration/fleet adoption); anything else must recompute."""
        blocks = tuple(
            (name, (int(bufs[name].shape[1]),) +
             tuple(int(d) for d in bufs[name].shape[3:]),
             str(bufs[name].dtype))
            for bufs, name in self._kv_buffers())
        return (self.page_size, blocks)

    def _read_page(self, pid: int) -> _HostPage:
        """Device -> host copy of one physical page across every KV
        buffer, with a chained CRC32 over the raw bytes."""
        ps = self.page_size
        r, s = divmod(int(pid), self.pages_per_row)
        blocks: List[np.ndarray] = []
        crc, nbytes = 0, 0
        for bufs, name in self._kv_buffers():
            arr = bufs[name]
            tail = (0,) * (arr.ndim - 3)
            blk = np.asarray(jax.lax.dynamic_slice(
                arr, (r, 0, s * ps) + tail,
                (1, arr.shape[1], ps) + arr.shape[3:]))
            crc = zlib.crc32(np.ascontiguousarray(blk).tobytes(), crc)
            blocks.append(blk)
            nbytes += blk.nbytes
        return _HostPage(blocks, crc, nbytes)

    def _write_page(self, pid: int, page: _HostPage) -> None:
        """Host -> device upload of one page (inverse of ``_read_page``;
        the updated arrays re-bind into the stage state dicts exactly
        like the COW copy)."""
        ps = self.page_size
        r, s = divmod(int(pid), self.pages_per_row)
        it = iter(page.blocks)
        for bufs, name in self._kv_buffers():
            arr = bufs[name]
            tail = (0,) * (arr.ndim - 3)
            bufs[name] = jax.lax.dynamic_update_slice(
                arr, next(it), (r, 0, s * ps) + tail)

    def spill(self, rid: int, tokens: Sequence[int]) -> Optional[Dict]:
        """Copy ``rid``'s written pages to the host tier — called BEFORE
        the mapping is released (preemption, page-pressure eviction,
        migration drain, brownout SPILL).  ``tokens`` is the
        authoritative fed sequence (prompt + generated): the chain's own
        token list only covers the bind-time feed, not decode-written
        positions, and restore verifies content identity against it.

        Returns ``{"pages", "nbytes", "tokens"}`` or None when nothing
        spilled (no tier, nothing written, or the tier refused — in
        every None case the r9 recompute feed covers recovery)."""
        tier = self.host_tier
        if tier is None:
            return None
        rid = int(rid)
        slot = self._slot_of.get(rid)
        info = self._chain.get(rid)
        if slot is None or info is None:
            return None
        toks = [int(t) for t in tokens]
        hi = min(int(info["written_hi"]), len(toks))
        if hi <= 0:
            return None
        ps = self.page_size
        pages: List[_HostPage] = []
        for k in range(-(-hi // ps)):
            pid = int(self._table[slot, k])
            if pid == self.scratch_pid:
                # unwritten hole (shouldn't happen below written_hi, but
                # truncate defensively: beyond here is recompute's job)
                hi = min(hi, k * ps)
                break
            pages.append(self._read_page(pid))
        pages = pages[:-(-hi // ps)] if hi > 0 else []
        if hi <= 0 or not pages:
            return None
        rec = _Spill(pages, toks, int(hi))
        tier.signature = self.swap_signature()
        if not tier.put_spill(rid, rec):
            return None  # larger than the whole tier: pure recompute
        self.pages_spilled += len(pages)
        self.swap_bytes += rec.nbytes
        return {"pages": len(pages), "nbytes": rec.nbytes,
                "tokens": int(hi)}

    def restore(self, rid: int, align: int = 1) -> Optional[Dict]:
        """Upload ``rid``'s spilled pages back onto its (re)bound row and
        advance the write frontier — called right after ``bind`` on
        readmission, so it only covers the span bind's prefix hits did
        not already map.  The spill entry is consumed either way.

        Content identity is verified first (the spilled token prefix
        must equal the new feed's — a stale entry from rid reuse drops
        silently, it is NOT a failure); every needed page is
        checksum-verified BEFORE the table mutates, and a corrupt page
        raises :class:`HostTierCorruption` with the bind result
        untouched so the caller falls back to recompute bit-identically.
        Pool exhaustion mid-upload degrades to a partial restore (the
        tail recomputes).  Returns ``{"restored_tokens", "pages",
        "nbytes", "tokens_saved"}`` or None."""
        tier = self.host_tier
        if tier is None:
            return None
        rid = int(rid)
        slot = self._slot_of.get(rid)
        info = self._chain.get(rid)
        if slot is None or info is None:
            return None
        ent = tier.get_spill(rid)
        if ent is None:
            return None
        toks = info["tokens"]
        ps = self.page_size
        n = min(int(ent.hi), len(toks) - 1 if toks else 0)
        if align > 1:
            n -= n % align
        if n <= 0 or ent.tokens[:n] != toks[:n]:
            tier.drop_spill(rid)  # stale (rid reuse / changed feed)
            return None
        cur = int(info["written_hi"])
        if n <= cur:
            tier.drop_spill(rid)  # prefix hits already cover the span
            return None
        try:
            k_lo, k_hi = cur // ps, (n - 1) // ps
            for k in range(k_lo, k_hi + 1):
                if not ent.pages[k].verify():
                    self.restore_failures += 1
                    raise HostTierCorruption(
                        f"rid {rid}: host page {k} failed its checksum "
                        "on restore")
            restored = n
            pages_up, nbytes = 0, 0
            try:
                for k in range(k_lo, k_hi + 1):
                    pid = int(self._table[slot, k])
                    exclusive = (pid != self.scratch_pid
                                 and self._req_refs[pid] == 1
                                 and self._idx_refs[pid] == 0)
                    if not exclusive:
                        # shared prefix page / index page / unmapped:
                        # land the upload on a fresh private page
                        dst = self._alloc_page()
                        self._unmap(slot, k)
                        self._map(slot, k, dst)
                        pid = dst
                    self._write_page(pid, ent.pages[k])
                    pages_up += 1
                    nbytes += ent.pages[k].nbytes
            except PagePoolExhausted:
                restored = min(n, k * ps)
                if align > 1:
                    restored -= restored % align
                if restored <= cur:
                    return None  # nothing gained; recompute covers it
            info["written_hi"] = max(cur, restored)
            gained = max(restored - cur, 0)
            self.pages_restored += pages_up
            self.swap_bytes += nbytes
            self.recompute_tokens_saved += gained
            return {"restored_tokens": int(restored), "pages": pages_up,
                    "nbytes": nbytes, "tokens_saved": int(gained)}
        finally:
            tier.drop_spill(rid)

    def has_spill(self, rid: int) -> bool:
        return (self.host_tier is not None
                and int(rid) in self.host_tier._spills)

    def drop_spill(self, rid: int) -> None:
        if self.host_tier is not None:
            self.host_tier.drop_spill(rid)

    def adopt_spills(self, other, rids: Sequence[int]) -> int:
        """Move ``rids``' spilled pages from another allocator's host
        tier onto this one (migration readmission, fleet failover) —
        only when the swap signatures match exactly; a shape-mismatched
        successor recomputes.  Attaches a tier here if absent (capacity
        inherited).  Returns the number of spills moved."""
        src = getattr(other, "host_tier", None)
        if src is None or other is self:
            return 0
        sig = self.swap_signature()
        if src.signature != sig:
            return 0
        if self.host_tier is None:
            self.host_tier = HostPageTier(src.capacity_bytes)
        self.host_tier.signature = sig
        moved = 0
        for rid in rids:
            s = src.pop_spill(int(rid))
            if s is not None and self.host_tier.put_spill(int(rid), s):
                moved += 1
        return moved

    def _promote_full(self, key: Tuple,
                      want: Tuple[int, ...]) -> Optional[_Entry]:
        """Re-register a demoted index page from the host tier (bind's
        hit-scan miss path).  Never evicts to make room — promotion into
        a full pool would recurse into demotion; a free page must exist
        or the bind just recomputes."""
        tier = self.host_tier
        rec = tier.get_demoted(key)
        if rec is None or rec.tokens != want:
            return None
        if not self._free:
            return None
        if not rec.page.verify():
            tier.drop_demoted(key)
            self.restore_failures += 1
            return None
        pid = self._free.pop()
        self._write_page(pid, rec.page)
        self._register_entry(key, pid, rec.tokens, rec.protected)
        e = self._entries.get(key)
        if e is None or e.pid != pid:  # registration refused (page keyed)
            self._free.append(pid)
            return None
        tier.drop_demoted(key)
        self.pages_restored += 1
        self.swap_bytes += rec.page.nbytes
        self.recompute_tokens_saved += self.page_size
        return e

    # ---- capacity / headroom, page-granular ---------------------------
    @property
    def capacity_tokens(self) -> int:
        """Token capacity of the page POOL (every non-scratch page times
        the page size) — any mix of requests can occupy it, which is the
        capacity-multiplier half of paging: the slot-contiguous cache
        could only ever fill R * max_seq_len of the same buffers."""
        return (self.n_pages - 1) * self.page_size

    def round_need(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_size) * self.page_size

    def pages_held(self) -> int:
        """Pages currently mapped by live requests."""
        return int((self._req_refs > 0).sum())

    def pages_shared(self) -> int:
        """Pages with more than one holder (requests + index)."""
        return int(((self._req_refs + self._idx_refs) >= 2).sum())

    def snapshot(self, _per_tok: Optional[float] = None,
                 _live: Optional[int] = None) -> Dict:
        """The contiguous snapshot plus the page-pool vocabulary.
        Fragmentation becomes honest under paging: allocated-but-idle is
        only the intra-page tail waste of each request's last page, not a
        whole reserved slot span."""
        snap = super().snapshot(_per_tok, _live)
        per_tok = snap["capacity_bytes"] / max(self.capacity_tokens, 1)
        held = self.pages_held()
        live = snap["live_tokens"]
        free = len(self._free)
        evictable = sum(1 for e in self._entries.values()
                        if self._req_refs[e.pid] == 0)
        snap.update({
            "fragmentation_frac": (1.0 - live / (held * self.page_size)
                                   if held else 0.0),
            # free + evictable is what a new request can actually get
            "headroom_bytes": (free + evictable) * self.page_size * per_tok,
            "page_size": self.page_size,
            "pages_total": self.n_pages - 1,
            "pages_live": held,
            "pages_shared": self.pages_shared(),
            "pages_free": free,
            "pages_indexed": len(self._entries),
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_tokens_reused": self.prefix_tokens_reused,
            "cow_copies": self.cow_copies,
            "pages_evicted": self.pages_evicted,
            "pages_mapped_total": self.pages_mapped,
            "pages_spilled": self.pages_spilled,
            "pages_restored": self.pages_restored,
            "swap_bytes": self.swap_bytes,
            "restore_failures": self.restore_failures,
            "recompute_tokens_saved": self.recompute_tokens_saved,
        })
        if self.host_tier is not None:
            snap.update(self.host_tier.snapshot())
        return snap

    # ---- diagnostics ---------------------------------------------------
    def logical_state(self, slot: int, depth: Optional[int] = None) -> Dict:
        """Reconstruct one slot's logical cache rows through the table
        (numpy; the bit-identity tests compare this against the
        slot-contiguous run's rows).  ``depth`` truncates to the live
        prefix — positions beyond a request's frontier are unmapped or
        junk by design."""
        ps, ppr = self.page_size, self.pages_per_row
        pids = self._table[slot]
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for si, stage in enumerate(self.stages):
            state = stage.state or {}
            for node, bufs in state.items():
                got: Dict[str, np.ndarray] = {}
                for name, arr in bufs.items():
                    if name not in KV_BUFFER_NAMES:
                        continue
                    a = np.asarray(arr)
                    parts = []
                    for pid in pids:
                        r, s = divmod(int(pid), ppr)
                        parts.append(a[r, :, s * ps:(s + 1) * ps])
                    row = np.concatenate(parts, axis=1)
                    got[name] = row[:, :depth] if depth is not None else row
                out[node] = got
        return out
