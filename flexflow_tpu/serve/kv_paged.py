"""Paged KV cache with copy-on-write prefix sharing.

The capacity multiplier the ROADMAP names: through r12 KV is
slot-contiguous — every bound slot reserves ``max_seq_len`` positions of
which only the live prefix is occupied, so high-occupancy serving
fragments HBM and every request re-prefills its own copy of a fleet-wide
system prompt.  This module brings the vLLM/PagedAttention block-table
design (Kwon et al., SOSP'23) and SGLang/RadixAttention-style prefix
reuse (Zheng et al.) to the TPU serve stack, **behind the exact r12
KVAllocator interface** (``bind``/``observe``/``release``/
``bytes_per_token``/``capacity_bytes``), so admission control, preemption
pricing, the serve search, and the memory ledger keep consulting one
arithmetic:

* **Physical layout is unchanged.**  The cache buffers stay the
  ``[max_requests+1, KV, S_pad, D]`` arrays the jitted step donates; the
  allocator reinterprets each row's seq axis as ``S_pad / page_size``
  fixed pages, so the pool holds ``(R+1) * S_pad / page_size`` pages and
  a page id ``pid`` addresses ``(row, slot) = divmod(pid, pages_per_row)``
  in EVERY buffer of every stage simultaneously (one logical table; the
  per-stage pools are the per-stage physical planes, exactly the pp
  capacity contract).  The int8 scale planes ``[rows, KV, S]`` page
  alongside K/V — same (row, seq-range) coordinates, no separate table.
* **Block-table indirection, not data movement.**  A per-cache-row table
  ``i32[R+1, pages_per_row]`` maps logical page -> physical page.  The
  Pallas decode/prefill/tree kernels gather the page base per kv-chunk
  through a scalar-prefetched copy of the table
  (``ops/pallas/attention.py``); the KV write paths and the gather
  fallback translate (row, position) through the same table on device
  (``serve/ops.py``).  Masks and positions stay logical, the fetched
  values are identical, so the paged path is BIT-IDENTICAL to the
  slot-contiguous path — the correctness contract tests/test_kv_paged.py
  pins across decode/prefill/mixed/pp2/int8/spec.
* **On-demand pages.**  ``prepare_write(rid, lo, hi)`` (called by the
  RequestManager before every dispatch that writes) maps missing pages
  from the free pool, so a request holds ``ceil(live/page)`` pages
  instead of a ``max_seq_len`` span — ``kv_fragmentation_frac`` collapses
  from the slot-reservation waste to intra-page tail waste (~0, the
  headline before/after metric in ``obs/memory.py``).  Pool exhaustion
  raises :class:`PagePoolExhausted`; under ``ResilienceConfig.preemption``
  the manager preempts a victim, whose pages free page-granularly.
* **Refcounted copy-on-write prefix sharing.**  Pages are keyed by a
  chained hash of the page-aligned token prefix that produced them (KV at
  a position is a pure function of the token prefix), plus a
  partial-tail entry for the final non-aligned page.  ``bind`` maps the
  longest registered chain into the new request's table (refcount++), so
  N requests sharing a system prompt prefill it ONCE — the
  RequestManager starts the newcomer's prefill at the cached offset and
  TTFT collapses to the unshared suffix.  A write into a page another
  request maps (``req_refs >= 2``) copies the page first (all stages, k/v
  + int8 scales) and remaps the writer — divergence mid-decode lands on a
  private copy while sharers keep the original.  The index itself holds a
  reference so shared pages outlive their creator; index-only pages are
  the eviction pool (LRU) when free pages run out.

Why writes never corrupt a sharer: a request only ever READS positions at
or below its own causal frontier, and it WRITES every position from its
cached offset upward itself (prefill then decode, gapless); positions a
mapped page carries beyond the matched prefix are therefore always masked
(future) or already rewritten by the reader itself — and rewrites of
matched positions store bit-identical values (same tokens, same
positions, deterministic projection + quantizer).  COW is required
exactly when TWO requests would interleave writes into one physical page.

Everything here is host-side bookkeeping plus host-ORCHESTRATED device
ops (the COW page copy, the table transfer); no policy decision is traced
into a jitted program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .kv_allocator import KV_BUFFER_NAMES, KVAllocator, StageKV


class PagePoolExhausted(RuntimeError):
    """No free page and nothing evictable: the pool is over-committed.
    RequestManager._kv_prepare turns this into page-pressure preemption
    when ``ResilienceConfig.preemption`` is on; otherwise it propagates
    (an admission gate sized with ``round_need`` prevents it)."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PageTable:
    """The device-side view of the block table, shipped with each step
    (``extras["pages"]``).  ``table[row, logical_page] = pid``;
    ``divmod(pid, pages_per_row)`` addresses the physical (row, page-slot)
    in every cache buffer.  Registered as a pytree so it rides jit args;
    the static fields key compilation like PrefillBatchConfig.tile_size."""

    table: Any                     # i32[R+1, pages_per_row]
    page_size: int = dataclasses.field(metadata=dict(static=True))
    pages_per_row: int = dataclasses.field(metadata=dict(static=True))


class _Entry:
    """One prefix-index record: a physical page whose content is keyed by
    the token prefix that produced it.  ``tokens`` is the page's actual
    registered token content — lookups VERIFY it (the chained hash is a
    lookup accelerator, not a trust anchor: Python's int-tuple hash is
    non-cryptographic, and a silent collision would map another prompt's
    KV into an unrelated request)."""

    __slots__ = ("pid", "lru", "tokens")

    def __init__(self, pid: int, lru: int, tokens: Tuple[int, ...]):
        self.pid = pid
        self.lru = lru
        self.tokens = tokens


def validate_page_tile(page_size: int, prefill_tile: int) -> None:
    """Construction-time contract shared by both managers: the tiled
    prefill path writes each tile as ONE block DUS, so a tile straddling
    a page boundary would scatter across two physical pages — fail here,
    not inside a kernel grid (sibling of the page/max_seq_len asserts)."""
    if page_size and page_size % prefill_tile:
        raise ValueError(
            f"kv_page_size {page_size} must be a multiple of the "
            f"prefill tile {prefill_tile} (tile-aligned block KV "
            "writes must not straddle a page boundary)")


def _common_prefix_len(a: Sequence[int], b: Sequence[int]) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class PagedKVAllocator(KVAllocator):
    """Block-table KV allocation with refcounted COW prefix sharing.

    Drop-in behind the r12 interface; see the module docstring for the
    design.  ``page_size`` defaults to 512 — the int8 dequant-fused
    kernel's block fetch granularity, so a kernel seq-block is exactly
    one page at production shapes.
    """

    paged = True

    def __init__(self, stages: Sequence[StageKV], max_requests: int,
                 max_seq_len: int, page_size: int = 512):
        super().__init__(stages, max_requests, max_seq_len)
        # satellite (mirror of the r6 prefill_tile divisibility fix): the
        # page geometry is validated HERE, at construction, instead of
        # failing deep inside a Pallas kernel grid — the page must tile
        # both the logical span (max_seq_len) and the 128-lane-padded
        # physical seq axis the buffers actually allocate.
        s_pad = -(-max_seq_len // 128) * 128
        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        if max_seq_len % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq_len "
                f"{max_seq_len} (a request's logical span is whole pages)")
        if s_pad % page_size:
            raise ValueError(
                f"page_size {page_size} must divide the 128-lane-padded "
                f"cache seq axis {s_pad} (the physical pool is carved from "
                "the padded buffers; a non-dividing page would straddle "
                "the pad boundary inside the kernel grid)")
        self.page_size = int(page_size)
        self.seq_pad = s_pad
        self.pages_per_row = s_pad // page_size
        self.n_pages = (max_requests + 1) * self.pages_per_row
        # row max_requests is the pad-token scratch row; ONE page of it
        # stays permanently reserved as the scratch page every unmapped
        # table entry points at (reads are causally masked, writes are
        # discarded pad-token garbage) — the rest of the scratch row's
        # pages join the pool, which is why the paged pool's capacity
        # exceeds the slot-contiguous R * max_seq_len.
        self.scratch_pid = max_requests * self.pages_per_row
        # prefix-sharing / lifecycle counters (cumulative; snapshot()
        # publishes them through the paged gauge vocabulary)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_reused = 0
        self.cow_copies = 0
        self.pages_evicted = 0
        # cumulative table-mapping count (every real page mapped into a
        # request's row, incl. COW destinations and prefix-reuse binds) —
        # the StepProfiler polls this into its deterministic
        # ``pages_mapped`` work counter (obs/profiler.py)
        self.pages_mapped = 0
        self._init_pool()

    # ------------------------------------------------------------------
    def _init_pool(self) -> None:
        self._table = np.full((self.max_requests + 1, self.pages_per_row),
                              self.scratch_pid, np.int32)
        self._req_refs = np.zeros(self.n_pages, np.int32)
        self._idx_refs = np.zeros(self.n_pages, np.int32)
        # LIFO free pool, low pids first out (deterministic)
        self._free: List[int] = [p for p in range(self.n_pages - 1, -1, -1)
                                 if p != self.scratch_pid]
        self._slot_of: Dict[int, int] = {}
        self._chain: Dict[int, Dict] = {}
        # prefix index: ("f", chain_hash) -> full-page entry;
        # ("p", chain_hash, tail_tuple) -> partial-tail entry.
        # _partial_by_base buckets the partial keys per chain hash so a
        # bind's tail lookup scans its own bucket, not the whole index.
        self._entries: Dict[Tuple, _Entry] = {}
        self._partial_by_base: Dict[int, List[Tuple]] = {}
        self._key_of_pid: Dict[int, Tuple] = {}
        # pid -> protected extent (page offsets [0, n) whose content the
        # index vouches for): a write into a protected range by ANYONE
        # must copy-on-write, or the index would serve corrupted KV to
        # later matching binds (a sole-holder sharer diverging inside the
        # registered range is the dangerous case — see prepare_slot_span)
        self._protected: Dict[int, int] = {}
        self._lru_tick = 0
        self._device_table = None

    def allocate(self):
        """(Re)allocate zeroed buffers AND reset the page pool: zeroed
        caches invalidate every indexed page's content, so the prefix
        index must not survive a reallocation."""
        out = super().allocate()
        self._init_pool()
        return out

    def reset_attribution(self) -> None:
        """New serving session over the SAME buffers (rids restart): every
        request mapping releases, but the prefix index stays — its pages'
        content is still valid, so a fleet-wide prompt survives manager
        turnover (the whole point of index-held references)."""
        for rid in list(self._slot_of):
            self.release(rid)
        super().reset_attribution()

    # ------------------------------------------------------------------
    def _touch(self, key: Tuple) -> None:
        self._lru_tick += 1
        self._entries[key].lru = self._lru_tick

    def _invalidate_device(self) -> None:
        self._device_table = None

    def page_view(self) -> PageTable:
        """Device-side table pytree (cached; rebuilt after any mutation)."""
        if self._device_table is None:
            import jax.numpy as jnp

            self._device_table = PageTable(
                table=jnp.asarray(self._table),
                page_size=self.page_size,
                pages_per_row=self.pages_per_row,
            )
        return self._device_table

    # ---- pool primitives ----------------------------------------------
    def _alloc_page(self) -> int:
        if self._free:
            return self._free.pop()
        # evict least-recently-used index-only pages (no request maps them)
        victims = sorted(
            (e.lru, key) for key, e in self._entries.items()
            if self._req_refs[e.pid] == 0)
        if not victims:
            raise PagePoolExhausted(
                f"page pool exhausted: {self.n_pages - 1} pages all held by "
                "live requests (admission should gate on round_need; "
                "enable ResilienceConfig.preemption for page-pressure "
                "eviction)")
        _, key = victims[0]
        self._drop_entry(key)
        self.pages_evicted += 1
        return self._free.pop()

    def _drop_entry(self, key: Tuple) -> None:
        e = self._entries.pop(key)
        if key[0] == "p":
            bucket = self._partial_by_base.get(key[1], [])
            if key in bucket:
                bucket.remove(key)
            if not bucket:
                self._partial_by_base.pop(key[1], None)
        self._key_of_pid.pop(e.pid, None)
        self._protected.pop(e.pid, None)
        self._idx_refs[e.pid] = 0
        if self._req_refs[e.pid] == 0:
            self._free.append(e.pid)

    def _map(self, slot: int, k: int, pid: int) -> None:
        self._table[slot, k] = pid
        self._req_refs[pid] += 1
        self.pages_mapped += 1
        self._invalidate_device()

    def _unmap(self, slot: int, k: int) -> None:
        pid = int(self._table[slot, k])
        if pid == self.scratch_pid:
            return
        self._table[slot, k] = self.scratch_pid
        self._req_refs[pid] -= 1
        if self._req_refs[pid] == 0 and self._idx_refs[pid] == 0:
            self._free.append(pid)
        self._invalidate_device()

    def _copy_page(self, src: int, dst: int) -> None:
        """Device copy of one page's content (k/v + int8 scale planes)
        across EVERY stage's buffers — the COW data move.  Host-orchestrated
        lax slice/update with concrete indices; the updated arrays re-bind
        into the stage state dicts the next jitted step donates."""
        ps = self.page_size
        sr, ss = divmod(src, self.pages_per_row)
        dr, ds = divmod(dst, self.pages_per_row)
        for stage in self.stages:
            state = stage.state
            if not state:
                continue
            for bufs in state.values():
                for name in list(bufs):
                    if name not in KV_BUFFER_NAMES:
                        continue
                    arr = bufs[name]
                    tail = (0,) * (arr.ndim - 3)
                    blk = jax.lax.dynamic_slice(
                        arr, (sr, 0, ss * ps) + tail,
                        (1, arr.shape[1], ps) + arr.shape[3:])
                    bufs[name] = jax.lax.dynamic_update_slice(
                        arr, blk, (dr, 0, ds * ps) + tail)

    # ---- the r12 interface, page-granular -----------------------------
    def bind(self, rid: int, slot: Optional[int] = None, tokens=None,
             need: Optional[int] = None, align: int = 1,
             **_) -> Optional[Dict]:
        """Map a request into the table, reusing every registered prefix
        page its fed-token sequence matches.

        ``slot``: the cache row (required for mapping; a bare ``bind(rid)``
        degrades to attribution-only, the base behavior).  ``tokens``: the
        sequence prefill will feed (prompt, or prompt+generated on
        preemption readmission — KV is a pure function of it, so the chain
        hash covers recompute reuse too).  ``align``: the prefill tile —
        the returned ``cached_tokens`` is rounded down to it so the tiled
        prefill path's tile-aligned-start contract holds when the manager
        resumes feeding at the cached offset.

        Returns ``{"cached_tokens", "hit_pages"}``; ``cached_tokens`` is
        capped at ``len(tokens) - 1`` so the final fed position is always
        recomputed (its logits are the first-token sample point).
        """
        super().bind(rid)
        if slot is None:
            return None
        rid, slot = int(rid), int(slot)
        self._slot_of[rid] = slot
        toks = [int(t) for t in (tokens or [])]
        ps = self.page_size
        hashes: List[int] = []
        h = 0
        for k in range(len(toks) // ps):
            h = hash((h, tuple(toks[k * ps:(k + 1) * ps])))
            hashes.append(h)
        info = {"tokens": toks, "hashes": hashes, "written_hi": 0,
                "registered": 0, "tail_done": False}
        self._chain[rid] = info

        # longest registered full-page chain — each hit VERIFIES the
        # entry's stored tokens against the bind's own page (the chained
        # hash only routes the lookup; a non-cryptographic collision must
        # read as a miss, never as someone else's KV)
        hit_pids: List[int] = []
        for k, h_k in enumerate(hashes):
            e = self._entries.get(("f", h_k))
            if e is None or e.tokens != tuple(toks[k * ps:(k + 1) * ps]):
                break
            hit_pids.append(e.pid)
        cached_pages = len(hit_pids)
        cached = cached_pages * ps
        # partial-tail extension under the last matched chain hash: the
        # best entry is the one sharing the longest token prefix with the
        # remaining feed (content beyond the match is causally masked for
        # the reader — see the module docstring's safety argument)
        h_base = hashes[cached_pages - 1] if cached_pages else 0
        part_pid, best_c, part_key = None, 0, None
        for key in self._partial_by_base.get(h_base, ()):
            c = _common_prefix_len(key[2], toks[cached:])
            if c > best_c:
                best_c, part_pid, part_key = c, self._entries[key].pid, key
        usable = cached + best_c
        if toks:
            usable = min(usable, len(toks) - 1)
        if align > 1:
            usable -= usable % align
        if usable <= 0:
            if toks:  # a tokenless bind (attribution/on-demand pages
                      # only, e.g. the spec draft cache) is not a miss
                self.prefix_misses += 1
            return {"cached_tokens": 0, "hit_pages": 0}
        # map only the pages the resumed feed READS (those overlapping
        # [0, usable)); the page containing the resume point will be
        # partially re-fed — value-identical rewrites, COW if contended
        n_full = min(cached_pages, -(-usable // ps))
        for k in range(n_full):
            self._map(slot, k, hit_pids[k])
            self._touch(("f", hashes[k]))
        mapped = n_full
        if part_pid is not None and usable > cached:
            self._map(slot, cached_pages, part_pid)
            self._touch(part_key)
            mapped += 1
        info["written_hi"] = usable
        self.prefix_hits += 1
        self.prefix_tokens_reused += usable
        return {"cached_tokens": usable, "hit_pages": mapped}

    def _register(self, rid: int, info: Optional[Dict]) -> None:
        """Publish ``rid``'s finished pages into the prefix index: full
        pages once their span is written, the partial tail once the whole
        fed sequence is written (its content is then exactly the fed
        tokens — later decode writes only dirty positions BEYOND the
        matchable range, which lookups never trust)."""
        if info is None:
            return
        slot = self._slot_of.get(rid)
        if slot is None:
            return
        ps = self.page_size
        wh = info["written_hi"]
        hashes = info["hashes"]
        while (info["registered"] < len(hashes)
               and (info["registered"] + 1) * ps <= wh):
            k = info["registered"]
            self._register_entry(
                ("f", hashes[k]), int(self._table[slot, k]),
                tuple(info["tokens"][k * ps:(k + 1) * ps]), ps)
            info["registered"] += 1
        n_full = len(hashes)
        tail = tuple(info["tokens"][n_full * ps:])
        if (not info["tail_done"] and tail and wh >= len(info["tokens"])
                and info["registered"] == n_full
                and n_full < self.pages_per_row):
            h_base = hashes[-1] if hashes else 0
            self._register_entry(("p", h_base, tail),
                                 int(self._table[slot, n_full]),
                                 tail, len(tail))
            info["tail_done"] = True

    def _register_entry(self, key: Tuple, pid: int,
                        tokens: Tuple[int, ...], protected: int) -> None:
        """``protected``: page offsets [0, n) whose content the entry
        vouches for — any later write below it copy-on-writes (see
        prepare_slot_span)."""
        if pid == self.scratch_pid:
            return
        if key in self._entries or pid in self._key_of_pid:
            return  # same content already indexed, or page already keyed
        self._lru_tick += 1
        self._entries[key] = _Entry(pid, self._lru_tick, tokens)
        if key[0] == "p":
            self._partial_by_base.setdefault(key[1], []).append(key)
        self._key_of_pid[pid] = key
        self._idx_refs[pid] = 1
        self._protected[pid] = int(protected)

    def prepare_write(self, rid: int, lo: int, hi: int) -> None:
        """Make positions ``[lo, hi)`` of ``rid``'s row writable: allocate
        unmapped logical pages from the pool, copy-on-write pages another
        request maps.  Also the registration hook — content below the
        request's write frontier is final exactly here, BEFORE the next
        dispatch's writes, so pages publish with deterministic timing
        (a request's tail page registers at its first decode-write
        prepare; its own next write then COWs it away if someone mapped
        it meanwhile — divergence-mid-decode)."""
        rid = int(rid)
        slot = self._slot_of.get(rid)
        info = self._chain.get(rid)
        if slot is None or hi <= lo:
            return
        self._register(rid, info)
        self.prepare_slot_span(slot, lo, hi)
        if info is not None and hi > info["written_hi"]:
            info["written_hi"] = int(hi)

    def prepare_slot_span(self, slot: int, lo: int, hi: int) -> None:
        """Slot-addressed page mapping + COW for writes at ``[lo, hi)`` —
        the rid-less half of :meth:`prepare_write`, used directly by the
        on-device spec scan (which advances committed depths without
        per-step host boundaries, so it prepares each slot's worst-case
        span up front and skips the prefix-registration hook).

        COW fires when (a) another REQUEST maps the page, or (b) the
        write starts inside an index entry's PROTECTED extent.  (b) is
        load-bearing even for a sole holder: a request that mapped a
        registered page on a SHORTER match than the entry's (its tokens
        diverge inside the protected range) would otherwise overwrite
        content the index still vouches for, silently corrupting every
        later bind that matches the full entry.  A registrant's own
        forward writes start AT the protected boundary (offset ==
        extent), so the common decode path never pays the copy.
        """
        if hi <= lo:
            return
        ps = self.page_size
        for k in range(int(lo) // ps,
                       min((int(hi) - 1) // ps, self.pages_per_row - 1) + 1):
            pid = int(self._table[slot, k])
            if pid == self.scratch_pid:
                self._map(slot, k, self._alloc_page())
                continue
            off_lo = max(int(lo) - k * ps, 0)  # first written page offset
            protected = (self._protected.get(pid, 0)
                         if self._idx_refs[pid] else 0)
            if self._req_refs[pid] > 1 or off_lo < protected:
                dst = self._alloc_page()
                self._copy_page(pid, dst)
                self._unmap(slot, k)
                self._map(slot, k, dst)
                self.cow_copies += 1

    def release(self, rid: int, tokens: Optional[int] = None) -> float:
        """Unmap every page of the request's row (refcount--, zero-ref
        unindexed pages return to the pool) after a final registration
        pass, so a completed request's shareable prefix outlives it."""
        rid = int(rid)
        info = self._chain.pop(rid, None)
        if info is not None:
            self._register(rid, info)  # before the slot mapping drops
        slot = self._slot_of.pop(rid, None)
        if slot is not None:
            for k in range(self.pages_per_row):
                self._unmap(slot, k)
        return super().release(rid, tokens)

    def teardown(self):
        """Base teardown (release attribution + drop buffers) PLUS a page
        pool + prefix-index reset: unlike ``reset_attribution`` (same
        buffers, index content still valid), the buffers are gone here,
        so an index entry surviving would vouch for KV that no longer
        exists — the migration-retirement analogue of ``allocate``'s
        index invalidation."""
        leaked = super().teardown()
        self._init_pool()
        return leaked

    # ---- capacity / headroom, page-granular ---------------------------
    @property
    def capacity_tokens(self) -> int:
        """Token capacity of the page POOL (every non-scratch page times
        the page size) — any mix of requests can occupy it, which is the
        capacity-multiplier half of paging: the slot-contiguous cache
        could only ever fill R * max_seq_len of the same buffers."""
        return (self.n_pages - 1) * self.page_size

    def round_need(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_size) * self.page_size

    def pages_held(self) -> int:
        """Pages currently mapped by live requests."""
        return int((self._req_refs > 0).sum())

    def pages_shared(self) -> int:
        """Pages with more than one holder (requests + index)."""
        return int(((self._req_refs + self._idx_refs) >= 2).sum())

    def snapshot(self, _per_tok: Optional[float] = None,
                 _live: Optional[int] = None) -> Dict:
        """The contiguous snapshot plus the page-pool vocabulary.
        Fragmentation becomes honest under paging: allocated-but-idle is
        only the intra-page tail waste of each request's last page, not a
        whole reserved slot span."""
        snap = super().snapshot(_per_tok, _live)
        per_tok = snap["capacity_bytes"] / max(self.capacity_tokens, 1)
        held = self.pages_held()
        live = snap["live_tokens"]
        free = len(self._free)
        evictable = sum(1 for e in self._entries.values()
                        if self._req_refs[e.pid] == 0)
        snap.update({
            "fragmentation_frac": (1.0 - live / (held * self.page_size)
                                   if held else 0.0),
            # free + evictable is what a new request can actually get
            "headroom_bytes": (free + evictable) * self.page_size * per_tok,
            "page_size": self.page_size,
            "pages_total": self.n_pages - 1,
            "pages_live": held,
            "pages_shared": self.pages_shared(),
            "pages_free": free,
            "pages_indexed": len(self._entries),
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_tokens_reused": self.prefix_tokens_reused,
            "cow_copies": self.cow_copies,
            "pages_evicted": self.pages_evicted,
            "pages_mapped_total": self.pages_mapped,
        })
        return snap

    # ---- diagnostics ---------------------------------------------------
    def logical_state(self, slot: int, depth: Optional[int] = None) -> Dict:
        """Reconstruct one slot's logical cache rows through the table
        (numpy; the bit-identity tests compare this against the
        slot-contiguous run's rows).  ``depth`` truncates to the live
        prefix — positions beyond a request's frontier are unmapped or
        junk by design."""
        ps, ppr = self.page_size, self.pages_per_row
        pids = self._table[slot]
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for si, stage in enumerate(self.stages):
            state = stage.state or {}
            for node, bufs in state.items():
                got: Dict[str, np.ndarray] = {}
                for name, arr in bufs.items():
                    if name not in KV_BUFFER_NAMES:
                        continue
                    a = np.asarray(arr)
                    parts = []
                    for pid in pids:
                        r, s = divmod(int(pid), ppr)
                        parts.append(a[r, :, s * ps:(s + 1) * ps])
                    row = np.concatenate(parts, axis=1)
                    got[name] = row[:, :depth] if depth is not None else row
                out[node] = got
        return out
