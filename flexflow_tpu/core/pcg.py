"""Parallel Computation Graph: Layer graph + mesh + per-op parallel configs.

The TPU-native analogue of FlexFlow's PCG (reference: ``src/runtime/graph.cc``,
``include/flexflow/graph.h``).  A FlexFlow PCG binds each op to a
``MachineView`` and each tensor to ``ParallelDim`` degrees, and reifies
communication as parallel-op nodes.  Here:

* the machine is a ``jax.sharding.Mesh`` with named axes,
* each op gets a *parallel config* ``{parallel_dim_name: (mesh axes)}``
  (the searchable object — the analogue of a MachineView assignment),
* :meth:`PCG.plan` propagates shardings through the graph and inserts explicit
  parallel ops (Repartition/Combine/Replicate/Reduction/AllReduce/AllToAll)
  wherever a producer's sharding differs from a consumer's requirement —
  the analogue of Unity's parallelization substitutions being materialized.

The resulting :class:`Plan` is what both the interpreter (execution) and the
simulator (costing) consume.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from jax.sharding import Mesh

from .graph import Graph, Node, TensorSpec
from .op import Op, ShardingSolution
from .sharding import TensorSharding
from ..parallel.parallel_ops import ParallelOp, reshard_path

Config = Dict[str, Tuple[str, ...]]


@dataclasses.dataclass
class Step:
    """One executable step of a planned PCG (op or parallel op)."""

    node: Node                      # original node, or synthetic for parallel ops
    in_vids: List[int]              # plan-local value ids consumed
    out_vids: List[int]             # plan-local value ids produced
    in_shardings: List[TensorSharding]
    out_shardings: List[TensorSharding]
    in_specs: List[TensorSpec]
    out_specs: List[TensorSpec]
    config: Config = dataclasses.field(default_factory=dict)
    is_parallel: bool = False

    @property
    def name(self) -> str:
        return self.node.name


@dataclasses.dataclass
class Plan:
    """Fully-resolved execution plan: steps + boundary shardings."""

    mesh: Mesh
    steps: List[Step]
    input_vids: Dict[int, int]                 # graph input tid -> vid
    output_vids: List[int]                     # vids of graph outputs
    input_shardings: Dict[int, TensorSharding]  # graph input tid -> sharding
    output_shardings: List[TensorSharding]
    param_shardings: Dict[str, Dict[str, TensorSharding]]  # node -> pname -> sh
    value_specs: Dict[int, TensorSpec]
    value_shardings: Dict[int, TensorSharding]

    def pretty(self) -> str:
        lines = [f"Plan over mesh {dict(self.mesh.shape)}:"]
        for s in self.steps:
            tag = "comm" if s.is_parallel else "op  "
            ins = ", ".join(
                f"v{v}:{sh}" for v, sh in zip(s.in_vids, s.in_shardings)
            )
            outs = ", ".join(
                f"v{v}:{sh}" for v, sh in zip(s.out_vids, s.out_shardings)
            )
            cfg = f" cfg={s.config}" if s.config else ""
            lines.append(f"  [{tag}] {s.name}: ({ins}) -> ({outs}){cfg}")
        return "\n".join(lines)


class PCG:
    """A Layer graph bound to a mesh with per-op parallel configs."""

    def __init__(
        self,
        graph: Graph,
        mesh: Mesh,
        configs: Optional[Dict[str, Config]] = None,
        input_shardings: Optional[Dict[int, TensorSharding]] = None,
        output_tids: Optional[List[int]] = None,
    ):
        self.graph = graph
        self.mesh = mesh
        self.configs: Dict[str, Config] = dict(configs or {})
        self.input_shardings = dict(input_shardings or {})
        if output_tids is None:
            consumed = {t for n in graph.nodes for t in n.inputs}
            output_tids = [
                t
                for n in graph.nodes
                for t in n.outputs
                if t not in consumed
            ]
        self.output_tids = output_tids

    # ------------------------------------------------------------------
    def with_configs(self, configs: Dict[str, Config]) -> "PCG":
        return PCG(
            self.graph, self.mesh, configs, self.input_shardings, self.output_tids
        )

    def default_input_sharding(self, tid: int, cons_req: TensorSharding) -> TensorSharding:
        """Graph inputs adopt their first consumer's requirement (so batches
        arrive already sharded instead of being resharded on-device)."""
        return self.input_shardings.get(tid, cons_req)

    # ------------------------------------------------------------------
    def plan(self) -> Plan:
        g = self.graph
        mesh = self.mesh
        next_vid = [0]

        def new_vid() -> int:
            next_vid[0] += 1
            return next_vid[0] - 1

        value_specs: Dict[int, TensorSpec] = {}
        value_shardings: Dict[int, TensorSharding] = {}
        tid_to_vid: Dict[int, int] = {}
        steps: List[Step] = []
        input_vids: Dict[int, int] = {}
        input_shardings: Dict[int, TensorSharding] = {}
        param_shardings: Dict[str, Dict[str, TensorSharding]] = {}
        pending_inputs: Dict[int, TensorSpec] = {
            tid: g.spec(tid) for tid in g.input_tids
        }

        def materialize_input(tid: int, req: TensorSharding) -> int:
            spec = pending_inputs.pop(tid)
            sh = self.default_input_sharding(tid, req)
            vid = new_vid()
            tid_to_vid[tid] = vid
            value_specs[vid] = spec
            value_shardings[vid] = sh
            input_vids[tid] = vid
            input_shardings[tid] = sh
            return vid

        def reshard_to(vid: int, want: TensorSharding, base_name: str) -> int:
            have = value_shardings[vid]
            if (tuple(have.dims), have.partial_axes) == (
                tuple(want.dims),
                want.partial_axes,
            ):
                return vid
            for pop in reshard_path(have, want, mesh):
                spec = value_specs[vid]
                out_sh = pop.transform_sharding(value_shardings[vid], mesh)
                nvid = new_vid()
                nname = g.unique_name(f"{base_name}.{pop.type_name}")
                synth = Node(-1, nname, pop, [], [])
                steps.append(
                    Step(
                        node=synth,
                        in_vids=[vid],
                        out_vids=[nvid],
                        in_shardings=[value_shardings[vid]],
                        out_shardings=[out_sh],
                        in_specs=[spec],
                        out_specs=[spec],
                        is_parallel=True,
                    )
                )
                value_specs[nvid] = spec
                value_shardings[nvid] = out_sh
                vid = nvid
            return vid

        for node in g.topo_order():
            in_specs = [g.spec(t) for t in node.inputs]
            config = self.configs.get(node.name, {})
            producer_shs: List[Optional[TensorSharding]] = []
            for t in node.inputs:
                if t in tid_to_vid:
                    producer_shs.append(value_shardings[tid_to_vid[t]])
                else:
                    producer_shs.append(None)
            sol: ShardingSolution = node.op.apply_config(
                config, in_specs, mesh, producer_shs
            )
            # validate solution
            out_specs = [g.spec(t) for t in node.outputs]
            for sh, spec in zip(sol.inputs, in_specs):
                sh.validate(spec.shape, mesh)
            for sh, spec in zip(sol.outputs, out_specs):
                sh.validate(spec.shape, mesh)

            in_vids = []
            for t, req in zip(node.inputs, sol.inputs):
                if t in pending_inputs:
                    vid = materialize_input(t, req)
                else:
                    vid = tid_to_vid[t]
                vid = reshard_to(vid, req, node.name)
                in_vids.append(vid)

            out_vids = []
            for t, sh, spec in zip(node.outputs, sol.outputs, out_specs):
                vid = new_vid()
                tid_to_vid[t] = vid
                value_specs[vid] = spec
                value_shardings[vid] = sh
                out_vids.append(vid)

            if sol.params:
                param_shardings[node.name] = dict(sol.params)
            else:
                ps = node.op.params()
                if ps:
                    param_shardings[node.name] = {
                        p.name: TensorSharding.replicated(p.spec.ndim) for p in ps
                    }

            steps.append(
                Step(
                    node=node,
                    in_vids=in_vids,
                    out_vids=out_vids,
                    in_shardings=[value_shardings[v] for v in in_vids],
                    out_shardings=list(sol.outputs),
                    in_specs=in_specs,
                    out_specs=out_specs,
                    config=config,
                )
            )

        # unconsumed graph inputs (e.g. labels fed straight to loss): replicated
        for tid in list(pending_inputs):
            materialize_input(tid, TensorSharding.replicated(g.spec(tid).ndim))

        # graph outputs: clear partial sums so callers see full values
        output_vids = []
        output_shardings = []
        for t in self.output_tids:
            vid = tid_to_vid[t]
            sh = value_shardings[vid]
            if sh.partial_axes:
                want = TensorSharding(sh.dims, frozenset())
                vid = reshard_to(vid, want, f"out_t{t}")
                sh = value_shardings[vid]
            output_vids.append(vid)
            output_shardings.append(sh)

        return Plan(
            mesh=mesh,
            steps=steps,
            input_vids=input_vids,
            output_vids=output_vids,
            input_shardings=input_shardings,
            output_shardings=output_shardings,
            param_shardings=param_shardings,
            value_specs=value_specs,
            value_shardings=value_shardings,
        )
