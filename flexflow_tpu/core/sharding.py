"""Per-tensor sharding annotations over a named device mesh.

This is the TPU-native analogue of FlexFlow's ``ParallelTensor``/``ParallelDim``
machinery (reference: ``include/flexflow/parallel_tensor.h`` — per-dimension
partition *degree* + replication flags, bound to a ``MachineView``).  On TPU the
"machine view" is a ``jax.sharding.Mesh`` and a per-dimension assignment of
mesh axis names; the partition degree of a dimension is the product of the
sizes of the mesh axes assigned to it.

Three orthogonal properties describe how a global logical tensor lives on the
mesh:

* ``dims[i].axes`` — mesh axes that shard logical dimension ``i``
  (FlexFlow: ``ParallelDim::degree`` on a non-replica dim).
* replication — any mesh axis not referenced by ``dims`` or ``partial_axes``
  implicitly replicates the tensor (FlexFlow: replica dims).
* ``partial_axes`` — mesh axes over which the values are *partial sums* that
  must be reduced before the mathematical value is materialized (FlexFlow:
  the state consumed by the ``Reduction``/``AllReduce`` parallel ops).
  GSPMD has no user-visible notion of this, which is exactly why the PCG
  reifies it: the Unity-style search must see and cost the pending reduction.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclasses.dataclass(frozen=True)
class DimSharding:
    """Sharding of one logical tensor dimension: the mesh axes that split it."""

    axes: Tuple[str, ...] = ()

    def degree(self, mesh_shape: dict) -> int:
        d = 1
        for a in self.axes:
            d *= mesh_shape[a]
        return d


@dataclasses.dataclass(frozen=True)
class TensorSharding:
    """Full sharding annotation for one PCG tensor.

    ``dims`` has one entry per logical dimension.  ``partial_axes`` marks mesh
    axes over which the tensor is an unreduced partial sum.
    """

    dims: Tuple[DimSharding, ...]
    partial_axes: frozenset = frozenset()

    # ---- constructors -------------------------------------------------
    @staticmethod
    def replicated(ndim: int) -> "TensorSharding":
        return TensorSharding(tuple(DimSharding() for _ in range(ndim)))

    @staticmethod
    def from_axes(
        ndim: int,
        axis_map: Optional[dict] = None,
        partial: Iterable[str] = (),
    ) -> "TensorSharding":
        """axis_map: {dim_index: mesh_axis_name or tuple of names}."""
        axis_map = axis_map or {}
        dims = []
        for i in range(ndim):
            a = axis_map.get(i, ())
            if isinstance(a, str):
                a = (a,)
            dims.append(DimSharding(tuple(a)))
        return TensorSharding(tuple(dims), frozenset(partial))

    # ---- queries ------------------------------------------------------
    @property
    def ndim(self) -> int:
        return len(self.dims)

    def sharded_axes(self) -> Tuple[str, ...]:
        out = []
        for d in self.dims:
            out.extend(d.axes)
        return tuple(out)

    def used_axes(self) -> frozenset:
        return frozenset(self.sharded_axes()) | self.partial_axes

    def is_fully_replicated(self) -> bool:
        return not self.used_axes()

    def dim_degree(self, dim: int, mesh: Mesh) -> int:
        return self.dims[dim].degree(dict(mesh.shape))

    def local_shape(self, global_shape: Sequence[int], mesh: Mesh) -> Tuple[int, ...]:
        """Per-device shard shape (shard_map body sees this)."""
        shape = []
        for size, d in zip(global_shape, self.dims):
            deg = d.degree(dict(mesh.shape))
            if size % deg != 0:
                raise ValueError(
                    f"dim of size {size} not divisible by degree {deg} "
                    f"(axes {d.axes})"
                )
            shape.append(size // deg)
        return tuple(shape)

    def validate(self, global_shape: Sequence[int], mesh: Mesh) -> None:
        if len(global_shape) != len(self.dims):
            raise ValueError(
                f"sharding rank {len(self.dims)} != tensor rank {len(global_shape)}"
            )
        seen = set()
        for d in self.dims:
            for a in d.axes:
                if a not in mesh.shape:
                    raise ValueError(f"unknown mesh axis {a!r}")
                if a in seen:
                    raise ValueError(f"mesh axis {a!r} used to shard two dims")
                seen.add(a)
        for a in self.partial_axes:
            if a not in mesh.shape:
                raise ValueError(f"unknown mesh axis {a!r} in partial_axes")
            if a in seen:
                raise ValueError(f"mesh axis {a!r} both shards a dim and is partial")
        self.local_shape(global_shape, mesh)

    # ---- conversion to JAX sharding machinery -------------------------
    def partition_spec(self) -> PartitionSpec:
        """PartitionSpec for GSPMD / shard_map in_specs.

        Note: partial-ness is NOT representable in a PartitionSpec; callers on
        the GSPMD path must ensure partial tensors never escape a jitted
        computation un-reduced (the PCG normalizer guarantees this by inserting
        Reduction/AllReduce nodes).
        """
        entries = []
        for d in self.dims:
            if len(d.axes) == 0:
                entries.append(None)
            elif len(d.axes) == 1:
                entries.append(d.axes[0])
            else:
                entries.append(tuple(d.axes))
        # trailing Nones are fine to keep; PartitionSpec handles them
        return PartitionSpec(*entries)

    def named_sharding(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.partition_spec())

    # ---- rewriting helpers (used by parallel ops / search) ------------
    def with_dim(self, dim: int, axes: Tuple[str, ...]) -> "TensorSharding":
        dims = list(self.dims)
        dims[dim] = DimSharding(tuple(axes))
        return TensorSharding(tuple(dims), self.partial_axes)

    def without_partial(self, axes: Iterable[str]) -> "TensorSharding":
        return TensorSharding(self.dims, self.partial_axes - frozenset(axes))

    def with_partial(self, axes: Iterable[str]) -> "TensorSharding":
        return TensorSharding(self.dims, self.partial_axes | frozenset(axes))

    def __str__(self) -> str:
        parts = []
        for d in self.dims:
            parts.append("x".join(d.axes) if d.axes else "-")
        s = "[" + ",".join(parts) + "]"
        if self.partial_axes:
            s += "+partial(" + ",".join(sorted(self.partial_axes)) + ")"
        return s
