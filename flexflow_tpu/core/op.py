"""Operator base class + registry.

The TPU-native analogue of FlexFlow's ``Op``/``OpMeta``/task trio (reference:
``include/flexflow/operator.h``, ``src/ops/*``).  Where a FlexFlow op carries
``init_task``/``forward_task``/``backward_task`` CUDA kernels, a TPU op carries
one pure-JAX ``lower`` function (XLA autodiff supplies the backward) plus a
*sharding rule*: the declarative description of which logical dims the op can
be parallelized over, replacing per-op ``MachineView`` handling.

Sharding rules use an einsum-like notation.  Each op exposes

* ``parallel_dims()`` — named, shardable logical dims with their (tensor, dim)
  bindings, e.g. Linear: ``{"sample": [(in0,0)], "channel_out": [(w,1),(out,-1)],
  "channel_in": [(in0,-1),(w,0)]}`` — the SOAP dimensions of the MLSys'19 paper.
* ``apply_config(config, mesh)`` — given ``{parallel_dim_name: (mesh axes)}``,
  produce required input/param shardings and resulting output shardings
  (including partial-sum marking for contracted dims).

The PCG normalizer then inserts explicit parallel ops wherever a producer's
sharding differs from a consumer's requirement.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .graph import ParamSpec, TensorSpec
from .sharding import TensorSharding


@dataclasses.dataclass
class OpContext:
    """Runtime context handed to ``Op.lower``.

    mode: "spmd"  — arrays are global; GSPMD handles comm (default training path)
          "local" — arrays are per-device shards inside shard_map; parallel ops
                    lower to explicit lax collectives (serve / manual path)
    """

    mode: str = "spmd"
    mesh: Any = None
    training: bool = False
    rng: Optional[jax.Array] = None
    config: Optional[Dict[str, Tuple[str, ...]]] = None
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def fold_rng(self, salt: int) -> Optional[jax.Array]:
        if self.rng is None:
            return None
        return jax.random.fold_in(self.rng, salt)


@dataclasses.dataclass
class ShardingSolution:
    """Output of ``Op.apply_config``: what the op needs and what it produces."""

    inputs: List[TensorSharding]          # required sharding per input tensor
    outputs: List[TensorSharding]         # produced sharding per output tensor
    params: Dict[str, TensorSharding] = dataclasses.field(default_factory=dict)


class Op:
    """Base operator. Subclasses set ``type_name`` and implement the hooks."""

    type_name: str = "op"

    # ---- shapes -------------------------------------------------------
    def infer_shapes(self, in_specs: List[TensorSpec]) -> List[TensorSpec]:
        raise NotImplementedError

    # ---- weights ------------------------------------------------------
    def params(self) -> List[ParamSpec]:
        return []

    # ---- compute ------------------------------------------------------
    def lower(
        self,
        ctx: OpContext,
        inputs: List[jax.Array],
        params: Dict[str, jax.Array],
    ) -> List[jax.Array]:
        raise NotImplementedError

    # ---- parallelization ----------------------------------------------
    def parallel_dims(self, in_specs: List[TensorSpec]) -> Dict[str, int]:
        """Named shardable parallel dims -> global extent.

        Default: ops with a leading sample/batch dim on input 0 expose it.
        """
        if in_specs and in_specs[0].ndim >= 1:
            return {"sample": in_specs[0].shape[0]}
        return {}

    def apply_config(
        self,
        config: Dict[str, Tuple[str, ...]],
        in_specs: List[TensorSpec],
        mesh: Any,
        in_shardings: Optional[List[Optional[TensorSharding]]] = None,
    ) -> ShardingSolution:
        """Map a parallel config to concrete tensor shardings.

        ``in_shardings`` carries the producers' shardings (None for graph
        inputs) so propagation-style ops can adopt them instead of forcing a
        reshard; ops may ignore it.

        Default implementation: "sample" shards dim 0 of every input and every
        output; params replicated. Works for elementwise-ish ops.
        """
        sample_axes = tuple(config.get("sample", ()))
        out_specs = self.infer_shapes(list(in_specs))
        ins = []
        for s in in_specs:
            sh = TensorSharding.replicated(s.ndim)
            if sample_axes and s.ndim >= 1:
                sh = sh.with_dim(0, sample_axes)
            ins.append(sh)
        outs = []
        for s in out_specs:
            sh = TensorSharding.replicated(s.ndim)
            if sample_axes and s.ndim >= 1:
                sh = sh.with_dim(0, sample_axes)
            outs.append(sh)
        return ShardingSolution(inputs=ins, outputs=outs)

    # ---- cost hints (used by the simulator) ---------------------------
    def flops(self, in_specs: List[TensorSpec]) -> int:
        """Approximate forward FLOPs; default: elementwise over output."""
        out = self.infer_shapes(list(in_specs))
        return sum(s.size for s in out)

    def is_parallel_op(self) -> bool:
        return False

    def attr_signature(self) -> Tuple:
        """Hashable signature of op attributes (for cost caching)."""
        items = []
        for k, v in sorted(vars(self).items()):
            if isinstance(v, (int, float, str, bool, tuple, type(None))):
                items.append((k, v))
        return (self.type_name, tuple(items))

    def __repr__(self) -> str:
        return f"{type(self).__name__}"


def bias_once(bias: jax.Array, axes, ctx: OpContext) -> jax.Array:
    """Zero a bias on all but one shard when the op output is a partial sum.

    When an op's output is partial over mesh ``axes`` (row-parallel linear,
    TP attention out-proj, vocab-sharded embedding), the bias must be counted
    exactly once by the later reduction.  In spmd mode arrays are global and
    GSPMD's own all-reduce already yields the true sum, so the bias is added
    as-is; only local/shard_map mode needs the one-shard trick.
    """
    if axes and ctx.mode == "local" and ctx.mesh is not None:
        idx = jnp.int32(0)
        for a in axes:
            idx = idx + jax.lax.axis_index(a)
        return jnp.where(idx == 0, bias, jnp.zeros_like(bias))
    return bias


# ---------------------------------------------------------------------------
# registry (op type name -> class), for strategy/serialization round-trips
# ---------------------------------------------------------------------------
OP_REGISTRY: Dict[str, type] = {}


def register_op(cls):
    OP_REGISTRY[cls.type_name] = cls
    return cls
