"""Frontend computation graph: the TPU-native analogue of FlexFlow's Layer graph.

FlexFlow keeps two graphs (reference: ``src/runtime/layer.cc``,
``src/runtime/model.cc``): a user-built *Layer* graph that only knows tensor
shapes, and a lowered *Parallel Computation Graph* whose tensors carry
partitioning.  We keep the same split: :class:`Graph` here is the Layer graph
(shapes + dtypes only); :mod:`flexflow_tpu.core.pcg` wraps it with a mesh and
per-tensor :class:`~flexflow_tpu.core.sharding.TensorSharding` annotations and
reifies resharding as parallel-op nodes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """Static shape + dtype of one logical (global) tensor."""

    shape: Tuple[int, ...]
    dtype: Any = jnp.float32

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def nbytes(self) -> int:
        return self.size * jnp.dtype(self.dtype).itemsize

    def __str__(self) -> str:
        return f"{jnp.dtype(self.dtype).name}{list(self.shape)}"


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    """A weight owned by a node: spec + initializer name."""

    name: str
    spec: TensorSpec
    initializer: Any = None  # Initializer instance or None -> op default
    trainable: bool = True


class Tensor:
    """Handle to a tensor in a Graph (what FFModel builder methods return)."""

    __slots__ = ("graph", "tid")

    def __init__(self, graph: "Graph", tid: int):
        self.graph = graph
        self.tid = tid

    @property
    def spec(self) -> TensorSpec:
        return self.graph.tensor_specs[self.tid]

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.spec.shape

    @property
    def dtype(self):
        return self.spec.dtype

    def __repr__(self) -> str:
        return f"Tensor(t{self.tid}: {self.spec})"


@dataclasses.dataclass
class Node:
    """One operator instance in the graph."""

    nid: int
    name: str  # unique, e.g. "dense_3"
    op: Any  # flexflow_tpu.core.op.Op
    inputs: List[int]  # tensor ids
    outputs: List[int]  # tensor ids

    def __repr__(self) -> str:
        ins = ",".join(f"t{t}" for t in self.inputs)
        outs = ",".join(f"t{t}" for t in self.outputs)
        return f"{self.name}({ins})->({outs})"


class Graph:
    """A DAG of Nodes over tensor ids, built incrementally (append-only)."""

    def __init__(self):
        self.nodes: List[Node] = []
        self.tensor_specs: List[TensorSpec] = []
        self.producer: Dict[int, Tuple[int, int]] = {}  # tid -> (nid, out_idx)
        self.input_tids: List[int] = []  # graph inputs (placeholders)
        self._name_counts: Dict[str, int] = {}

    # ---- construction -------------------------------------------------
    def add_input(self, spec: TensorSpec) -> Tensor:
        tid = self._new_tensor(spec)
        self.input_tids.append(tid)
        return Tensor(self, tid)

    def _new_tensor(self, spec: TensorSpec) -> int:
        self.tensor_specs.append(spec)
        return len(self.tensor_specs) - 1

    def unique_name(self, base: str) -> str:
        n = self._name_counts.get(base, 0)
        self._name_counts[base] = n + 1
        return f"{base}_{n}" if n else base

    def add_node(
        self,
        op: Any,
        inputs: Sequence[Tensor],
        name: Optional[str] = None,
    ) -> List[Tensor]:
        for t in inputs:
            if t.graph is not self:
                raise ValueError("input tensor from a different graph")
        name = self.unique_name(name or op.type_name)
        in_specs = [t.spec for t in inputs]
        out_specs = op.infer_shapes(in_specs)
        nid = len(self.nodes)
        out_tids = [self._new_tensor(s) for s in out_specs]
        node = Node(nid, name, op, [t.tid for t in inputs], out_tids)
        self.nodes.append(node)
        for i, tid in enumerate(out_tids):
            self.producer[tid] = (nid, i)
        return [Tensor(self, tid) for tid in out_tids]

    # ---- queries ------------------------------------------------------
    def topo_order(self) -> List[Node]:
        # append-only construction => node list is already topologically sorted
        return self.nodes

    def consumers(self, tid: int) -> List[Tuple[Node, int]]:
        out = []
        for node in self.nodes:
            for slot, t in enumerate(node.inputs):
                if t == tid:
                    out.append((node, slot))
        return out

    def spec(self, tid: int) -> TensorSpec:
        return self.tensor_specs[tid]

    def param_specs(self) -> Dict[str, Dict[str, ParamSpec]]:
        """{node_name: {param_name: ParamSpec}} for all weighted nodes."""
        out: Dict[str, Dict[str, ParamSpec]] = {}
        for node in self.nodes:
            ps = node.op.params()
            if ps:
                out[node.name] = {p.name: p for p in ps}
        return out

    def __str__(self) -> str:
        lines = []
        for tid in self.input_tids:
            lines.append(f"  input t{tid}: {self.tensor_specs[tid]}")
        for node in self.nodes:
            outs = ", ".join(
                f"t{t}:{self.tensor_specs[t]}" for t in node.outputs
            )
            ins = ", ".join(f"t{t}" for t in node.inputs)
            lines.append(f"  {node.name}: ({ins}) -> {outs}")
        return "Graph(\n" + "\n".join(lines) + "\n)"


def live_cuts(graph: "Graph", final_tids: Sequence[int]) -> List[frozenset]:
    """Per-boundary live tensor sets: the cut-tracking core of the SESE
    segment machinery (``FFModel._pipeline_segments`` uses it for the GPipe
    training executor; the serve stage split uses it for pipeline-parallel
    serving).

    ``live_cuts(g, finals)[i]`` is the set of tensor ids produced at or
    before node ``i`` (graph inputs included) that are still needed strictly
    after it — consumed by a later node, or listed in ``final_tids`` (the
    protected outputs).  A boundary whose live set is small is a cheap
    pipeline cut: only those tensors cross between stages.  A single-tensor
    live set is exactly the SESE (single-entry/single-exit) segment boundary
    the training pipeline carves at; serve graphs with fused residual
    norms carry ``{residual, hidden}`` between decoder layers, so their
    natural cuts are two tensors wide.
    """
    nodes = graph.nodes
    keep = set(final_tids)
    last_use: Dict[int, int] = {}
    for i, node in enumerate(nodes):
        for t in node.inputs:
            last_use[t] = i
    live = {t for t in graph.input_tids if last_use.get(t) is not None}
    out: List[frozenset] = []
    for i, node in enumerate(nodes):
        for t in node.inputs:
            if last_use.get(t) == i and t not in keep:
                live.discard(t)
        for t in node.outputs:
            if last_use.get(t, -1) > i or t in keep:
                live.add(t)
        out.append(frozenset(live))
    return out
