"""Plan -> executable JAX functions.

The TPU-native replacement for FlexFlow's execution layer: where the reference
walks the PCG issuing Legion index launches per op task (reference:
``FFModel::forward`` in ``src/runtime/model.cc``), here the whole PCG lowers
into ONE traced JAX function that XLA compiles and fuses.  Two modes:

* ``spmd``  — ops compute on global arrays; the chosen shardings are enforced
  with ``with_sharding_constraint`` and GSPMD emits the collectives.  This is
  the default training path (XLA sees the whole step; fusion + overlap).
* ``local`` — the function body runs under ``jax.shard_map``; ops compute on
  per-device shards and parallel ops are explicit ``lax`` collectives.  Used
  where manual communication placement matters (serve, ring attention) and for
  validating that the reified parallel ops are exactly the collectives we cost.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..compat import shard_map
from .op import OpContext
from .pcg import Plan, Step
from .sharding import TensorSharding


def _mesh_is_trivial(mesh: Mesh) -> bool:
    return mesh.size == 1


def build_forward(plan: Plan, mode: str = "spmd") -> Callable:
    """Return ``fn(params, inputs, rng=None, training=False) -> list[out]``.

    ``params``: ``{node_name: {param_name: array}}`` (global arrays).
    ``inputs``: ``{tid: array}`` for every graph input (global arrays).
    In either mode the returned function takes and returns GLOBAL arrays and is
    safe to ``jax.jit`` / differentiate.

    Stateful execution (the serve path — KV caches): pass ``state`` (a dict
    ``{node_name: pytree}``) and optionally ``extras`` (shared values visible
    to every op, e.g. the ``BatchConfig``).  Ops marked ``stateful = True``
    receive their state at ``ctx.extras["state"]`` and publish the updated
    state to ``ctx.extras["state_out"]``; the call then returns
    ``(outputs, new_state)``.  This replaces the reference's mutable per-op
    ``OpMeta`` device state (e.g. ``IncMultiHeadSelfAttentionMeta``'s KV cache)
    with explicit functional threading so the whole step stays jittable and
    the caches can be donated.
    """

    mesh = plan.mesh
    trivial = _mesh_is_trivial(mesh)

    def body(params, inputs, rng, training, state=None, extras=None):
        env: Dict[int, jax.Array] = {}
        new_state = {} if state is not None else None
        for tid, vid in plan.input_vids.items():
            env[vid] = inputs[tid]
        for i, step in enumerate(plan.steps):
            ctx = OpContext(
                mode=mode if not trivial else "spmd",
                mesh=None if trivial else mesh,
                training=training,
                rng=None if rng is None else jax.random.fold_in(rng, i),
                config=step.config,
                extras={
                    "out_sharding": step.out_shardings[0]
                    if step.out_shardings
                    else None,
                    "out_shardings": step.out_shardings,
                    "in_shardings": step.in_shardings,
                    "in_specs": step.in_specs,
                    "out_specs": step.out_specs,
                },
            )
            if extras:
                ctx.extras.update(extras)
            if state is not None and getattr(step.node.op, "stateful", False):
                ctx.extras["state"] = state.get(step.node.name)
            args = [env[v] for v in step.in_vids]
            outs = step.node.op.lower(ctx, args, params.get(step.node.name, {}))
            if new_state is not None and "state_out" in ctx.extras:
                new_state[step.node.name] = ctx.extras["state_out"]
            if mode == "spmd" and not trivial and not step.is_parallel:
                outs = [
                    _constrain_spmd(o, sh, mesh)
                    for o, sh in zip(outs, step.out_shardings)
                ]
            for v, o in zip(step.out_vids, outs):
                env[v] = o
        outputs = [env[v] for v in plan.output_vids]
        if state is not None:
            return outputs, new_state
        return outputs

    if mode == "spmd" or trivial:

        def fn(params, inputs, rng=None, training=False, state=None, extras=None):
            return body(params, inputs, rng, training, state, extras)

        return fn

    # ---- local mode: wrap in shard_map --------------------------------
    param_pspecs = {
        name: {
            p: sh.partition_spec() for p, sh in shs.items()
        }
        for name, shs in plan.param_shardings.items()
    }

    input_pspecs = {
        tid: plan.input_shardings[tid].partition_spec()
        for tid in plan.input_vids
    }
    out_pspecs = [sh.partition_spec() for sh in plan.output_shardings]

    def fn(params, inputs, rng=None, training=False, state=None, extras=None):
        if state is not None or extras is not None:
            raise NotImplementedError(
                "stateful execution (serve) is only supported in spmd mode; "
                "local/shard_map mode would need state pspecs threaded through"
            )
        # params not listed in the plan (unused nodes) are passed replicated
        pspecs = {
            name: param_pspecs.get(
                name, jax.tree.map(lambda _: PartitionSpec(), sub)
            )
            for name, sub in params.items()
        }

        def local_body(params_, inputs_):
            return body(params_, inputs_, rng, training)

        mapped = shard_map(
            local_body,
            mesh=mesh,
            in_specs=(pspecs, input_pspecs),
            out_specs=out_pspecs,
        )
        return mapped(params, inputs)

    return fn


def _constrain_spmd(x: jax.Array, sh: TensorSharding, mesh: Mesh) -> jax.Array:
    if sh.partial_axes:
        # partial-sum state is not expressible in a PartitionSpec; leave the
        # value unconstrained and let GSPMD carry it to the reduction point
        return x
    return lax.with_sharding_constraint(x, sh.named_sharding(mesh))


# ---------------------------------------------------------------------------
# parameter initialization & placement
# ---------------------------------------------------------------------------
def init_params(
    graph, plan: Plan, rng: jax.Array, dtype=None, only=None
) -> Dict[str, Dict[str, jax.Array]]:
    """Initialize all node params as global arrays placed per plan shardings.

    ``only``: optional set of node names to materialize.  The per-param rng
    key index still advances over EVERY node of ``graph`` in order, so a
    stage-split model (pipeline-parallel serving initializes each stage
    against its own sub-plan) draws bit-identical weights to the
    single-plan initialization with the same seed.
    """
    from ..training.initializer import default_initializer_for

    mesh = plan.mesh
    params: Dict[str, Dict[str, jax.Array]] = {}
    i = 0
    for node in graph.nodes:
        ps = node.op.params()
        if not ps:
            continue
        if only is not None and node.name not in only:
            i += len(ps)
            continue
        sub = {}
        for p in ps:
            key = jax.random.fold_in(rng, i)
            i += 1
            init = p.initializer or default_initializer_for(node.op, p)
            arr = init(key, p.spec.shape, dtype or p.spec.dtype)
            sh = plan.param_shardings.get(node.name, {}).get(p.name)
            if sh is not None and not _mesh_is_trivial(mesh):
                arr = jax.device_put(arr, sh.named_sharding(mesh))
            sub[p.name] = arr
        params[node.name] = sub
    return params


def place_inputs(plan: Plan, inputs: Dict[int, jax.Array]) -> Dict[int, jax.Array]:
    """device_put graph inputs according to their planned shardings."""
    if _mesh_is_trivial(plan.mesh):
        return inputs
    out = {}
    for tid, x in inputs.items():
        sh = plan.input_shardings.get(tid)
        if sh is None:
            out[tid] = x
        else:
            out[tid] = jax.device_put(x, sh.named_sharding(plan.mesh))
    return out
