"""Version-compatibility shims for the supported JAX range.

No internal imports here (this module sits below everything else).
"""


def shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` left ``jax.experimental`` in newer JAX and renamed
    its replication-check kwarg (``check_rep`` -> ``check_vma``); dispatch
    on what the installed JAX provides.  The check stays off either way:
    the mapped bodies use explicit collectives whose replication the
    checker can't always infer.
    """
    try:
        from jax import shard_map as sm
        kw = {"check_vma": False}
    except ImportError:  # pragma: no cover - depends on installed jax
        from jax.experimental.shard_map import shard_map as sm
        kw = {"check_rep": False}
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
