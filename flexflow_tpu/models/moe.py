"""Mixture-of-Experts MLP — parity with ``examples/cpp/mixture_of_experts``.

Reference: the MoE example stacks an MLP whose middle layer routes through
``group_by -> experts -> aggregate``; here the same graph comes from
``FFModel.moe_layer`` with fixed-capacity dispatch (see ops/moe.py), and
expert parallelism is one strategy entry away.
"""

from __future__ import annotations

from typing import Optional

from ..config import FFConfig
from ..model import FFModel


def build_moe_classifier(
    config: Optional[FFConfig] = None,
    mesh=None,
    batch: int = 32,
    in_dim: int = 64,
    num_experts: int = 4,
    expert_hidden: int = 128,
    num_classes: int = 10,
    k: int = 2,
    capacity_factor: float = 2.0,
    ep_axes=(),
    dp_axes=(),
):
    """Returns (FFModel, input_tensor, output_tensor, strategy)."""
    ff = FFModel(config or FFConfig(batch_size=batch), mesh=mesh)
    x_in = ff.create_tensor((batch, in_dim))
    h = ff.dense(x_in, in_dim, activation="relu", name="pre")
    h = ff.moe_layer(
        h, num_experts, in_dim, hidden_dim=expert_hidden, k=k,
        capacity_factor=capacity_factor, name="moe",
    )
    out = ff.softmax(ff.dense(h, num_classes, name="head"))
    strategy = {}
    if ep_axes:
        for node in ("moe.group_by", "moe.experts", "moe.aggregate"):
            strategy[node] = {"expert": ep_axes}
    if dp_axes:
        for node in ("pre", "head"):
            strategy[node] = {"sample": dp_axes}
    return ff, x_in, out, strategy
