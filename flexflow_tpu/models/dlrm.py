"""DLRM — deep learning recommendation model (BASELINE config #3).

Reference: ``examples/cpp/DLRM/dlrm.cc`` — sparse categorical features through
per-table embeddings (sum-aggregated), dense features through a bottom MLP,
feature interaction, top MLP to a CTR logit.  The TPU-native win is the
sharding: embedding tables model-parallel over a mesh axis (vocab-sharded
``entry`` dim -> partial-sum lookups resolved by one AllReduce) while the
batch is data-parallel — exactly the reference's hybrid DLRM strategy, with
the NCCL all-to-all replaced by GSPMD-lowered ICI collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from ..config import FFConfig
from ..model import FFModel


def build_dlrm(
    config: Optional[FFConfig] = None,
    mesh=None,
    batch: int = 32,
    dense_dim: int = 13,
    table_sizes: Sequence[int] = (1000, 1000, 1000, 1000),
    embed_dim: int = 64,
    bottom_mlp: Sequence[int] = (512, 256, 64),
    top_mlp: Sequence[int] = (256, 64, 1),
    mp_axes=(),
    dp_axes=(),
):
    """Returns (FFModel, dense_tensor, [sparse_tensors], output, strategy)."""
    assert bottom_mlp[-1] == embed_dim, "bottom MLP must end at embed_dim"
    ff = FFModel(config or FFConfig(batch_size=batch), mesh=mesh)
    strategy = {}

    dense_in = ff.create_tensor((batch, dense_dim))
    x = dense_in
    for i, h in enumerate(bottom_mlp):
        name = f"bottom_mlp.{i}"
        x = ff.dense(x, h, activation="relu", name=name)
        if dp_axes:
            strategy[name] = {"sample": dp_axes}

    feats = [x]
    sparse_ins = []
    for t, size in enumerate(table_sizes):
        ids = ff.create_tensor((batch, 1), dtype=jnp.int32)
        sparse_ins.append(ids)
        name = f"emb_table.{t}"
        e = ff.embedding(ids, size, embed_dim, aggr="sum", name=name)
        if mp_axes:  # vocab-sharded table: the DLRM model-parallel dimension
            strategy[name] = {"entry": mp_axes}
        feats.append(e)

    inter = ff.concat(feats, axis=1, name="interaction_concat")
    y = inter
    for i, h in enumerate(top_mlp):
        name = f"top_mlp.{i}"
        act = "relu" if i < len(top_mlp) - 1 else "sigmoid"
        y = ff.dense(y, h, activation=act, name=name)
        if dp_axes:
            strategy[name] = {"sample": dp_axes}
    return ff, dense_in, sparse_ins, y, strategy
