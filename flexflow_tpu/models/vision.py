"""Vision model zoo: AlexNet, ResNet, Inception blocks.

Reference: ``examples/cpp/AlexNet/alexnet.cc``, ``ResNet/resnet.cc``,
``InceptionV3/inception.cc`` — the cuDNN conv stacks the reference trains as
examples.  NCHW graphs through Conv2D/Pool2D/BatchNorm; XLA:TPU re-lays-out
for the MXU's convolution path on its own.
"""

from __future__ import annotations

from typing import Optional

from ..config import FFConfig
from ..model import FFModel


def _pool_scaled(ff, x, name, kernel=(3, 3), stride=(2, 2), **kw):
    """Pool2D clamped to the incoming spatial dims.

    The classic AlexNet kernels assume 224x224 inputs; at example-scale image
    sizes a 3x3/s2 pool can exceed the remaining spatial extent and produce a
    zero-size tensor.  Clamp kernel (and stride) to the input so the stack
    stays valid at any configured image size; skip entirely at 1x1.
    """
    h, w = x.shape[2], x.shape[3]
    if h <= 1 and w <= 1:
        return x
    kh, kw_ = min(kernel[0], h), min(kernel[1], w)
    sh, sw = min(stride[0], kh), min(stride[1], kw_)
    return ff.pool2d(x, kernel=(kh, kw_), stride=(sh, sw), name=name, **kw)


def build_alexnet(config=None, mesh=None, batch=4, num_classes=10,
                  image=(3, 64, 64)):
    """AlexNet-style stack (scaled to the configured image size)."""
    ff = FFModel(config or FFConfig(batch_size=batch), mesh=mesh)
    x_in = ff.create_tensor((batch,) + tuple(image))
    x = ff.conv2d(x_in, 64, kernel=(11, 11), stride=(4, 4), padding="SAME",
                  activation="relu", name="conv1")
    x = _pool_scaled(ff, x, "pool1")
    x = ff.conv2d(x, 192, kernel=(5, 5), activation="relu", name="conv2")
    x = _pool_scaled(ff, x, "pool2")
    x = ff.conv2d(x, 384, activation="relu", name="conv3")
    x = ff.conv2d(x, 256, activation="relu", name="conv4")
    x = ff.conv2d(x, 256, activation="relu", name="conv5")
    x = _pool_scaled(ff, x, "pool5")
    x = ff.flat(x, name="flat")
    x = ff.dense(x, 512, activation="relu", name="fc6")
    x = ff.dense(x, 512, activation="relu", name="fc7")
    out = ff.softmax(ff.dense(x, num_classes, name="fc8"))
    return ff, x_in, out


def _res_block(ff, x, channels, stride, name):
    """Basic ResNet block: conv-bn-relu, conv-bn, shortcut add, relu."""
    h = ff.conv2d(x, channels, stride=(stride, stride), use_bias=False,
                  name=f"{name}.conv1")
    h = ff.batch_norm(h, relu=True, name=f"{name}.bn1")
    h = ff.conv2d(h, channels, use_bias=False, name=f"{name}.conv2")
    h = ff.batch_norm(h, name=f"{name}.bn2")
    if stride != 1 or x.shape[1] != channels:
        x = ff.conv2d(x, channels, kernel=(1, 1), stride=(stride, stride),
                      use_bias=False, name=f"{name}.short")
        x = ff.batch_norm(x, name=f"{name}.short_bn")
    return ff.relu(ff.add(h, x, name=f"{name}.add"), name=f"{name}.out")


def build_resnet18(config=None, mesh=None, batch=4, num_classes=10,
                   image=(3, 64, 64)):
    ff = FFModel(config or FFConfig(batch_size=batch), mesh=mesh)
    x_in = ff.create_tensor((batch,) + tuple(image))
    x = ff.conv2d(x_in, 64, kernel=(7, 7), stride=(2, 2), use_bias=False,
                  name="stem.conv")
    x = ff.batch_norm(x, relu=True, name="stem.bn")
    x = _pool_scaled(ff, x, "stem.pool")
    for stage, (ch, stride) in enumerate([(64, 1), (128, 2), (256, 2),
                                          (512, 2)]):
        for blk in range(2):
            x = _res_block(ff, x, ch, stride if blk == 0 else 1,
                           f"layer{stage + 1}.{blk}")
    x = ff.pool2d(x, kernel=x.shape[2:], stride=(1, 1), pool_type="avg",
                  name="gap")
    x = ff.flat(x, name="flat")
    out = ff.softmax(ff.dense(x, num_classes, name="fc"))
    return ff, x_in, out


def _inception_block(ff, x, c1, c3r, c3, c5r, c5, cp, name):
    """GoogLeNet-style mixed block: 1x1 | 1x1-3x3 | 1x1-5x5 | pool-1x1."""
    b1 = ff.conv2d(x, c1, kernel=(1, 1), activation="relu", name=f"{name}.b1")
    b3 = ff.conv2d(x, c3r, kernel=(1, 1), activation="relu",
                   name=f"{name}.b3r")
    b3 = ff.conv2d(b3, c3, kernel=(3, 3), activation="relu", name=f"{name}.b3")
    b5 = ff.conv2d(x, c5r, kernel=(1, 1), activation="relu",
                   name=f"{name}.b5r")
    b5 = ff.conv2d(b5, c5, kernel=(5, 5), activation="relu", name=f"{name}.b5")
    bp = ff.pool2d(x, kernel=(3, 3), stride=(1, 1), padding="SAME",
                   name=f"{name}.pool")
    bp = ff.conv2d(bp, cp, kernel=(1, 1), activation="relu", name=f"{name}.bp")
    return ff.concat([b1, b3, b5, bp], axis=1, name=f"{name}.cat")


def build_inception(config=None, mesh=None, batch=4, num_classes=10,
                    image=(3, 64, 64)):
    """Compact Inception: stem + two mixed blocks + head (InceptionV3's
    graph shape — parallel branches merged by channel concat — at example
    scale)."""
    ff = FFModel(config or FFConfig(batch_size=batch), mesh=mesh)
    x_in = ff.create_tensor((batch,) + tuple(image))
    x = ff.conv2d(x_in, 32, stride=(2, 2), activation="relu", name="stem1")
    x = ff.conv2d(x, 64, activation="relu", name="stem2")
    x = _pool_scaled(ff, x, "stem_pool")
    x = _inception_block(ff, x, 64, 48, 64, 8, 16, 32, "mixed0")
    x = _inception_block(ff, x, 64, 48, 64, 8, 16, 32, "mixed1")
    x = ff.pool2d(x, kernel=x.shape[2:], stride=(1, 1), pool_type="avg",
                  name="gap")
    x = ff.flat(x, name="flat")
    out = ff.softmax(ff.dense(x, num_classes, name="fc"))
    return ff, x_in, out
