"""Transformer encoder stack — the training Transformer example.

Reference: ``examples/cpp/Transformer/transformer.cc`` `[B]` —
``create_attention_encoder_decoder``-style stack of MHA + feed-forward blocks,
the Unity search benchmark graph (BASELINE config #2).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..model import FFModel


def create_transformer_encoder(
    model: FFModel,
    input_tensor,
    num_layers: int = 2,
    hidden_dim: int = 512,
    num_heads: int = 8,
    ff_dim: int = 2048,
    dropout: float = 0.0,
    prefix: str = "enc",
):
    """Post-LN encoder blocks: x = LN(x + MHA(x)); x = LN(x + FFN(x))."""
    x = input_tensor
    for i in range(num_layers):
        p = f"{prefix}{i}"
        attn = model.multihead_attention(
            x, x, x, hidden_dim, num_heads, dropout=dropout,
            name=f"{p}_attn",
        )
        x = model.layer_norm(model.add(attn, x, name=f"{p}_attn_res"),
                             name=f"{p}_ln1")
        h = model.dense(x, ff_dim, activation="relu", name=f"{p}_ff1")
        if dropout:
            h = model.dropout(h, dropout, name=f"{p}_ffdrop")
        h = model.dense(h, hidden_dim, name=f"{p}_ff2")
        x = model.layer_norm(model.add(h, x, name=f"{p}_ff_res"),
                             name=f"{p}_ln2")
    return x


def build_transformer_classifier(
    config=None,
    mesh=None,
    batch: int = 8,
    seq: int = 64,
    num_layers: int = 2,
    hidden_dim: int = 256,
    num_heads: int = 8,
    ff_dim: int = 1024,
    num_classes: int = 16,
    dropout: float = 0.0,
):
    """Transformer encoder + mean-pool + softmax head (training benchmark)."""
    from ..config import FFConfig

    model = FFModel(config or FFConfig(), mesh=mesh)
    x = model.create_tensor((batch, seq, hidden_dim))
    h = create_transformer_encoder(
        model, x, num_layers, hidden_dim, num_heads, ff_dim, dropout
    )
    pooled = model.reduce_mean(h, axes=(1,), name="pool")
    logits = model.dense(pooled, num_classes, name="head")
    out = model.softmax(logits)
    return model
