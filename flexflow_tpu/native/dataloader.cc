// Native batch-staging engine for the training data pipeline.
//
// Reference: the C++ dataloaders in the reference runtime
// (src/loc/loader.cc + SingleDataLoader) — worker threads gather shuffled
// batches into staging buffers so the accelerator never waits on host-side
// indexing.  TPU-native shape: the Python DataLoader hands this engine a
// pinned view of the (row-major) dataset; the worker thread memcpys the
// permuted rows for upcoming batches into a bounded queue of staging
// buffers WITHOUT holding the GIL, and the Python side wraps each ready
// buffer with numpy/jax.device_put.  Unlike Python's fancy-index gather,
// the copy runs concurrently with training (no GIL); the per-batch buffer
// allocation is malloc-cheap next to the row memcpys it stages.
//
// Plain C ABI (no pybind11 in this environment): driven via ctypes from
// flexflow_tpu/data/native.py.  Build: `make -C flexflow_tpu/native`.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<uint8_t> x;
  std::vector<uint8_t> y;
  int64_t epoch;
  int64_t index;  // batch index within the epoch
};

struct Loader {
  const uint8_t* x;       // [n, row_bytes] row-major dataset (borrowed)
  const uint8_t* y;       // [n, label_bytes] labels (borrowed)
  int64_t n;
  int64_t row_bytes;
  int64_t label_bytes;
  int64_t batch;
  int64_t batches_per_epoch;
  bool shuffle;
  uint64_t seed;

  std::thread worker;
  std::mutex mu;
  std::condition_variable ready_cv;   // consumer waits: queue non-empty
  std::condition_variable space_cv;   // producer waits: queue below depth
  std::queue<Batch> queue;
  size_t depth;
  std::atomic<bool> stop{false};
  Batch current;  // last batch handed to the consumer (owns the memory)

  void run() {
    std::mt19937_64 rng(seed);
    std::vector<int64_t> perm(n);
    for (int64_t i = 0; i < n; ++i) perm[i] = i;
    for (int64_t epoch = 0;; ++epoch) {
      if (shuffle) {
        // Fisher-Yates with the engine's own stream: reproducible for a
        // given seed, independent of Python's RNG state
        for (int64_t i = n - 1; i > 0; --i) {
          std::uniform_int_distribution<int64_t> d(0, i);
          std::swap(perm[i], perm[d(rng)]);
        }
      }
      for (int64_t b = 0; b < batches_per_epoch; ++b) {
        Batch out;
        out.epoch = epoch;
        out.index = b;
        out.x.resize(batch * row_bytes);
        out.y.resize(batch * label_bytes);
        for (int64_t j = 0; j < batch; ++j) {
          const int64_t src = perm[b * batch + j];
          std::memcpy(out.x.data() + j * row_bytes, x + src * row_bytes,
                      row_bytes);
          std::memcpy(out.y.data() + j * label_bytes, y + src * label_bytes,
                      label_bytes);
        }
        std::unique_lock<std::mutex> lk(mu);
        space_cv.wait(lk, [&] { return queue.size() < depth || stop; });
        if (stop) return;
        queue.push(std::move(out));
        ready_cv.notify_one();
      }
    }
  }
};

}  // namespace

extern "C" {

// Create a loader over borrowed host buffers (caller keeps them alive).
// Returns an opaque handle.
void* ffdl_create(const void* x, const void* y, int64_t n, int64_t row_bytes,
                  int64_t label_bytes, int64_t batch, int32_t prefetch,
                  int32_t shuffle, uint64_t seed) {
  if (n <= 0 || batch <= 0 || batch > n || row_bytes <= 0) return nullptr;
  auto* l = new Loader();
  l->x = static_cast<const uint8_t*>(x);
  l->y = static_cast<const uint8_t*>(y);
  l->n = n;
  l->row_bytes = row_bytes;
  l->label_bytes = label_bytes;
  l->batch = batch;
  l->batches_per_epoch = n / batch;
  l->shuffle = shuffle != 0;
  l->seed = seed;
  l->depth = prefetch > 0 ? static_cast<size_t>(prefetch) : 1;
  l->worker = std::thread([l] { l->run(); });
  return l;
}

int64_t ffdl_batches_per_epoch(void* handle) {
  return static_cast<Loader*>(handle)->batches_per_epoch;
}

// Block until the next staged batch is ready; returns pointers valid until
// the NEXT ffdl_next/ffdl_destroy call.  Returns the epoch number.
int64_t ffdl_next(void* handle, const void** out_x, const void** out_y) {
  auto* l = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(l->mu);
  l->ready_cv.wait(lk, [&] { return !l->queue.empty(); });
  l->current = std::move(l->queue.front());
  l->queue.pop();
  l->space_cv.notify_one();
  *out_x = l->current.x.data();
  *out_y = l->current.y.data();
  return l->current.epoch;
}

void ffdl_destroy(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(l->mu);
    l->stop = true;
  }
  l->space_cv.notify_all();
  l->worker.join();
  delete l;
}

}  // extern "C"
