"""FFConfig: global configuration + FlexFlow-style CLI flag parsing.

Reference: ``include/flexflow/config.h`` / ``FFConfig::parse_args`` in
``src/runtime/model.cc`` — Legion-style argv (``-ll:gpu``, ``-b``, ``-e``,
``--budget``, ``--only-data-parallel``, ``--import``/``--export``).  Device
enumeration (``FFHandler`` per-GPU cuDNN handles) collapses to
``jax.devices()`` + a mesh spec; there is nothing to initialize per-device.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax


@dataclasses.dataclass
class FFConfig:
    # training loop
    batch_size: int = 64
    epochs: int = 1
    learning_rate: float = 0.01

    # machine: mesh axis name -> size; None = one axis "dp" over all devices
    mesh_shape: Optional[Dict[str, int]] = None
    num_devices: Optional[int] = None  # cap the device count (None = all)

    # Unity-style search
    search_budget: int = 0          # 0 = no search (use default/imported strategy)
    search_alpha: float = 0.05      # MCMC temperature-ish factor
    only_data_parallel: bool = False
    # pipeline parallelism (compile-path): microbatches per step when the
    # mesh has a "pp" axis and pipeline_or_gspmd picks the pipeline;
    # pipeline = "auto" (cost model decides) | "force" | "off"
    pipeline_microbatches: int = 4
    pipeline: str = "auto"
    import_strategy_file: Optional[str] = None
    export_strategy_file: Optional[str] = None

    # numerics
    compute_dtype: str = "float32"

    # profiling
    profiling: bool = False
    seed: int = 0

    @staticmethod
    def parse_args(argv: Optional[List[str]] = None) -> "FFConfig":
        import sys

        argv = list(sys.argv[1:] if argv is None else argv)
        cfg = FFConfig()
        i = 0

        def take() -> str:
            nonlocal i
            i += 1
            return argv[i - 1]

        while i < len(argv):
            a = take()
            if a in ("-b", "--batch-size"):
                cfg.batch_size = int(take())
            elif a in ("-e", "--epochs"):
                cfg.epochs = int(take())
            elif a in ("-lr", "--learning-rate"):
                cfg.learning_rate = float(take())
            elif a == "--budget" or a == "--search-budget":
                cfg.search_budget = int(take())
            elif a == "--search-alpha":
                cfg.search_alpha = float(take())
            elif a == "--only-data-parallel":
                cfg.only_data_parallel = True
            elif a == "--import" or a == "--import-strategy":
                cfg.import_strategy_file = take()
            elif a == "--export" or a == "--export-strategy":
                cfg.export_strategy_file = take()
            elif a == "--mesh":
                # e.g. --mesh dp=4,tp=2
                cfg.mesh_shape = {}
                for part in take().split(","):
                    k, v = part.split("=")
                    cfg.mesh_shape[k.strip()] = int(v)
            elif a in ("-ll:gpu", "-ll:tpu", "--devices"):
                cfg.num_devices = int(take())
            elif a == "--dtype":
                cfg.compute_dtype = take()
            elif a == "--profiling":
                cfg.profiling = True
            elif a == "--seed":
                cfg.seed = int(take())
            # unknown flags are ignored (Legion-style tolerance)
        return cfg

    def devices(self):
        devs = jax.devices()
        if self.num_devices is not None:
            devs = devs[: self.num_devices]
        return devs
