"""Reductions over telemetry artifacts: JSONL summaries + serving records.

Two consumers share this module:

* ``scripts/trace_report.py`` — CLI over :func:`summarize_jsonl`: p50/p95
  TTFT/TPOT/queue-wait derived from the request-lifecycle events a
  ``Telemetry`` export carries, per-track span totals (the pp stage
  interleave), the pipeline bubble fraction, and the per-plan
  predicted-vs-measured error table.
* ``bench.py`` — :func:`under_load_summary` is the ``serving_under_load``
  section's record reduction (moved here from bench so the bench, the
  hermetic tests, and the report CLI all run the SAME accounting).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from .metrics import percentile

# request-lifecycle event names (the Telemetry.request_* schema)
_ENQ = "request_enqueue"
_ADMIT = "request_admit"
_PREFILL = "request_prefill_start"
_FIRST = "request_first_token"
_FINISH = "request_finish"
# resilient-serving lifecycle (terminal outcomes + dispatch events)
_TERMINAL_EVENTS = {
    "request_reject": "rejected",
    "request_cancel": "cancelled",
    "request_timeout": "timeout",
    "request_fail": "failed",
}
_PREEMPT = "request_preempt"
_RETRY = "dispatch_retry"
_FAULT = "dispatch_fault"
# paged-KV prefix sharing (serve/kv_paged.py)
_PREFIX_HIT = "prefix_hit"
_PREFIX_MISS = "prefix_miss"
# speculative production mode (serve/spec_infer.py): runtime mode flips
_SPEC_MODE = "spec_mode_changed"
# observe->calibrate->re-plan loop events (obs/drift.py, obs/plan_health.py)
_DRIFT = "drift_detected"
_REPLAN = "replan_recommended"
# memory observability (obs/memory.py): the OOM-risk breach instant
_MEMPRESS = "memory_pressure"
# live plan migration (serve/migration.py): the controller acting on
# replan_recommended — start / completion / rollback of a plan switch
_MIG_EVENTS = ("migration_started", "migration_completed",
               "migration_rolled_back")
# fault-tolerant fleet serving (serve/fleet.py): replica health-state
# transitions + per-request failover onto a survivor
_FLEET_EVENTS = ("replica_up", "replica_degraded", "replica_quarantined",
                 "replica_dead")
_FAILOVER = "request_failed_over"
# SLO-class lanes + brownout (serve/slo.py): ladder transitions and
# explicit lane sheds
_BROWNOUT = "brownout_level_changed"
_LANE_SHED = "lane_shed"
# time-travel serving (obs/replay.py).  replay_mismatch carries a
# trace_id, so these MUST be intercepted before the per-request
# trace_id branch — a mismatch instant is about a replay, not a new
# request, and must not inflate the request count.
_REPLAY_EVENTS = ("trace_recorded", "replay_started", "replay_completed",
                  "replay_mismatch")
# host-tier KV spill/restore (serve/kv_paged.py HostPageTier).  All three
# carry a trace_id, so — like replay_mismatch — they MUST be intercepted
# before the per-request trace_id branch: a spill instant is about an
# already-tracked request's pages, and must not inflate the request count.
_TIER_EVENTS = ("kv_spill", "kv_restore", "kv_restore_failed")


def _pct_ms(xs: List[float], q: float) -> Optional[float]:
    v = percentile(sorted(xs), q)
    return None if v is None else round(v * 1e3, 2)


def summarize_events(events: Sequence[Dict]) -> Dict:
    """Per-request latency distributions from lifecycle events (ts in
    microseconds, trace_event form) + per-track span time.

    ``span_ms_by_track`` sums complete-span durations per track, so it is
    only a wall-time total where spans on one track don't nest/overlap —
    the instrumentation keeps serve-loop, dispatch, pp-macro, and stage
    spans on separate tracks for exactly this reason.
    """
    reqs: Dict[str, Dict] = {}
    track_spans: Dict[int, float] = {}
    track_names: Dict[int, str] = {}
    outcomes: Dict[str, int] = {}
    preemptions = retries = faults = 0
    prefix_hits = prefix_misses = 0
    spec_mode_changes: List[Dict] = []
    drift_events: List[Dict] = []
    replans: List[Dict] = []
    mem_pressure: List[Dict] = []
    migrations: Dict[str, List[Dict]] = {n: [] for n in _MIG_EVENTS}
    fleet_events: Dict[str, List[Dict]] = {n: [] for n in _FLEET_EVENTS}
    failovers: List[Dict] = []
    brownout_changes: List[Dict] = []
    lane_sheds: List[Dict] = []
    replay_events: Dict[str, List[Dict]] = {n: [] for n in _REPLAY_EVENTS}
    tier_events: Dict[str, List[Dict]] = {n: [] for n in _TIER_EVENTS}
    for ev in events:
        ph = ev.get("ph")
        if ph == "M" and ev.get("name") == "thread_name":
            track_names[ev.get("tid")] = ev.get("args", {}).get("name")
            continue
        if ph == "X":
            tid = ev.get("tid")
            track_spans[tid] = track_spans.get(tid, 0.0) \
                + ev.get("dur", 0.0) / 1e6
            continue
        name = ev.get("name")
        if name == _RETRY:
            retries += 1
            continue
        if name == _FAULT:
            faults += 1
            continue
        if name == _PREFIX_HIT:
            prefix_hits += 1
            continue
        if name == _PREFIX_MISS:
            prefix_misses += 1
            continue
        if name == _SPEC_MODE:
            spec_mode_changes.append(ev.get("args", {}))
            continue
        if name == _DRIFT:
            drift_events.append(ev.get("args", {}))
            continue
        if name == _REPLAN:
            replans.append(ev.get("args", {}))
            continue
        if name == _MEMPRESS:
            mem_pressure.append(ev.get("args", {}))
            continue
        if name in migrations:
            migrations[name].append(ev.get("args", {}))
            continue
        if name in fleet_events:
            fleet_events[name].append(ev.get("args", {}))
            continue
        if name == _FAILOVER:
            failovers.append(ev.get("args", {}))
            continue
        if name == _BROWNOUT:
            brownout_changes.append(ev.get("args", {}))
            continue
        if name == _LANE_SHED:
            lane_sheds.append(ev.get("args", {}))
            continue
        if name in replay_events:
            replay_events[name].append(ev.get("args", {}))
            continue
        if name in tier_events:
            tier_events[name].append(ev.get("args", {}))
            continue
        args = ev.get("args", {})
        trace_id = args.get("trace_id")
        if trace_id is None:
            continue
        rec = reqs.setdefault(trace_id, {})
        if name in (_ENQ, _ADMIT, _PREFILL, _FIRST, _FINISH):
            rec[name] = ev.get("ts", 0.0) / 1e6  # -> seconds
            if name == _FINISH:
                rec["n_tokens"] = args.get("n_tokens", 0)
        elif name in _TERMINAL_EVENTS:
            out = _TERMINAL_EVENTS[name]
            rec["outcome"] = out
            outcomes[out] = outcomes.get(out, 0) + 1
        elif name == _PREEMPT:
            preemptions += 1

    ttft, tpot, queue_wait, prefill = [], [], [], []
    completed = 0
    for rec in reqs.values():
        enq = rec.get(_ENQ)
        first = rec.get(_FIRST)
        fin = rec.get(_FINISH)
        if fin is not None:
            outcomes["ok"] = outcomes.get("ok", 0) + 1
        if enq is not None and first is not None:
            ttft.append(first - enq)
            # queue wait ends where prefill begins (fall back to admission
            # when no prefill-start stamp was emitted)
            start = rec.get(_PREFILL, rec.get(_ADMIT))
            if start is not None:
                queue_wait.append(start - enq)
                prefill.append(first - start)
        if fin is not None:
            completed += 1
            if first is not None:
                tpot.append((fin - first) / max(rec.get("n_tokens", 1) - 1, 1))

    spans_by_track = {
        track_names.get(tid, f"track{tid}"): round(total * 1e3, 3)
        for tid, total in sorted(track_spans.items())
    }
    return {
        "requests": len(reqs),
        "completed": completed,
        "ttft_p50_ms": _pct_ms(ttft, 0.50),
        "ttft_p95_ms": _pct_ms(ttft, 0.95),
        "queue_wait_p50_ms": _pct_ms(queue_wait, 0.50),
        "queue_wait_p95_ms": _pct_ms(queue_wait, 0.95),
        "prefill_p50_ms": _pct_ms(prefill, 0.50),
        "tpot_p50_ms": _pct_ms(tpot, 0.50),
        "tpot_p95_ms": _pct_ms(tpot, 0.95),
        "span_ms_by_track": spans_by_track,
        # resilient serving: terminal-outcome mix + recovery activity
        "outcomes": outcomes,
        "preemptions": preemptions,
        "dispatch_retries": retries,
        "dispatch_faults": faults,
        # paged-KV prefix sharing: binds that reused registered pages
        "prefix_hits": prefix_hits,
        "prefix_misses": prefix_misses,
        # speculative production mode: runtime spec on/off flips
        "spec_mode_changes": spec_mode_changes,
        # plan feedback loop: drift excursions + replan recommendations
        "drift_detected": drift_events,
        "replan_recommended": replans,
        # memory observability: OOM-risk breach instants (obs/plan_health.py)
        "memory_pressure": mem_pressure,
        # live plan migration: started/completed/rolled_back event args
        "migrations": {
            "started": migrations["migration_started"],
            "completed": migrations["migration_completed"],
            "rolled_back": migrations["migration_rolled_back"],
        },
        # fault-tolerant fleet serving: replica health transitions +
        # per-request failovers (serve/fleet.py)
        "fleet": {
            "replica_events": {n.replace("replica_", ""): fleet_events[n]
                               for n in _FLEET_EVENTS},
            "failed_over": failovers,
        },
        # SLO-class lanes + brownout (serve/slo.py): degradation-ladder
        # transitions and explicit lane sheds
        "slo": {
            "brownout_changes": brownout_changes,
            "lane_shed": lane_sheds,
        },
        # time-travel serving (obs/replay.py): trace artifacts saved,
        # replay runs, and per-request fidelity violations
        "replay": {
            "recorded": replay_events["trace_recorded"],
            "started": replay_events["replay_started"],
            "completed": replay_events["replay_completed"],
            "mismatches": replay_events["replay_mismatch"],
        },
        # host-tier KV spill/restore (serve/kv_paged.py): per-request
        # swap instants + restore-degraded-to-recompute fallbacks
        "tier": {
            "spills": tier_events["kv_spill"],
            "restores": tier_events["kv_restore"],
            "restore_failures": tier_events["kv_restore_failed"],
        },
    }


def summarize_jsonl(path: str) -> Dict:
    """Summarize a ``Telemetry.export`` JSONL: lifecycle distributions,
    bubble fraction, events/dropped, and per-plan prediction error."""
    events: List[Dict] = []
    meta: Dict = {}
    metrics: Dict = {}
    calibration: Dict = {}
    memory: Dict = {}
    workload: Dict = {}
    store: Dict = {}
    profile: Dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            kind = doc.get("kind")
            if kind == "event":
                events.append(doc)
            elif kind == "telemetry_meta":
                meta = doc
            elif kind == "metrics":
                metrics = doc.get("snapshot", {})
            elif kind == "calibration":
                calibration = doc.get("report", {})
            elif kind == "memory":
                memory = doc.get("report", {})
            elif kind == "workload":
                workload = doc.get("snapshot", {})
            elif kind == "calibration_store":
                store = doc
            elif kind == "profile":
                profile = doc.get("report", {})

    summary = summarize_events(events)
    summary["events"] = meta.get("events", len(events))
    summary["dropped"] = meta.get("dropped", 0)
    summary["bubble_frac"] = metrics.get("pp_bubble_frac")
    # plan feedback loop: live drift score (gauge = last value), the
    # workload window the handle accumulated, and the persisted scales the
    # next search will auto-apply
    summary["workload_drift_score"] = metrics.get("workload_drift_score")
    summary["workload"] = {
        d: {"n": w.get("n"), "mean": (round(w["mean"], 4)
                                      if w.get("mean") is not None else None)}
        for d, w in sorted(workload.get("dims", {}).items())
        if w.get("n")}
    summary["applied_scales"] = store.get("applied_scales", {})
    # registry view of the resilience counters (the trace ring can drop
    # events under pressure; the counters are exact)
    from .telemetry import MIGRATION_COUNTERS, RESILIENCE_COUNTERS

    summary["robustness"] = {
        k: metrics[k] for k in RESILIENCE_COUNTERS if k in metrics}
    # registry view: migrations_completed/rolled_back are exact cumulative
    # counters (survive trace-ring drops, like the resilience counters);
    # the downtime/preempted entries are GAUGES carrying the LAST
    # migration's values — per-migration history lives in the event lists
    # above, not here
    summary["migrations"]["counters"] = {
        k: metrics[k] for k in MIGRATION_COUNTERS if k in metrics}
    # fleet view: the replica health transitions summarize_events already
    # collected, joined with the exact registry counters/gauges
    # (FLEET_COUNTERS — failovers_total and the replica_* counters are
    # cumulative and survive trace-ring drops; the fleet_replicas_*
    # gauges carry the LAST fleet tick's values)
    from .telemetry import FLEET_COUNTERS

    summary["fleet"]["counters"] = {
        k: metrics[k] for k in FLEET_COUNTERS if k in metrics}
    # SLO-lane view: the events summarize_events collected + the exact
    # registry counters (SLO_COUNTERS — deferral/shed/degrade totals and
    # the ladder's escalation counters; brownout_level is a gauge holding
    # the final level) and the per-class pending-depth gauges
    from .telemetry import SLO_COUNTERS

    summary["slo"]["counters"] = {
        k: metrics[k] for k in SLO_COUNTERS if k in metrics}
    summary["slo"]["lane_depths"] = {
        k: metrics[k] for k in sorted(metrics)
        if k.startswith("lane_pending_depth_")}
    # time-travel serving view: the replay events summarize_events
    # collected + the exact registry counters (REPLAY_COUNTERS —
    # replay_mismatches joins bench_compare's exact class at threshold
    # zero: any mismatch means determinism regressed)
    from .telemetry import REPLAY_COUNTERS

    summary["replay"]["counters"] = {
        k: metrics[k] for k in REPLAY_COUNTERS if k in metrics}
    # host-tier view: the swap events summarize_events collected + the
    # exact registry counters (TIER_COUNTERS — kv_restore_failures joins
    # bench_compare's exact class at threshold zero: a clean-path restore
    # must never degrade to recompute)
    from .telemetry import TIER_COUNTERS

    summary["tier"]["counters"] = {
        k: metrics[k] for k in TIER_COUNTERS if k in metrics}
    # trace-drop hardening: surface the ring buffer's dropped-event
    # count under the exact-class regression counter name, so every
    # bench section that embeds a summary carries it into bench_compare
    # (a section silently losing telemetry events fails CI, not just a
    # stderr warning in trace_report)
    summary["telemetry_events_dropped"] = summary["dropped"]

    pred_err: Dict[str, Dict] = {}
    for plan, fields in calibration.get("plans", {}).items():
        row = {f: {"predicted": e.get("predicted"),
                   "measured": e.get("measured"),
                   "error_frac": e.get("error_frac")}
               for f, e in fields.items()}
        pred_err[plan] = row
    summary["prediction_error"] = pred_err
    summary["calibration_components"] = calibration.get("components", {})
    summary["memory"] = memory_section(memory, metrics)
    summary["memory"]["pressure_events"] = summary.pop("memory_pressure")
    # step-level cost attribution (obs/profiler.py): the phase time
    # budget + deterministic work counters + the per-component
    # predicted-vs-executed decomposition — None when no profiler was
    # bound to the exporting handle
    summary["time_budget"] = (time_budget_section(profile, calibration)
                              if profile else None)
    return summary


def time_budget_section(profile: Dict, calibration: Dict) -> Dict:
    """The time-budget view: a StepProfiler report (phases + work
    counters) joined with the calibration ledger's per-component
    ``*_ms`` decomposition (attention / mlp / lm_head / kv_stream /
    comms / hop / host_overhead — the vocabulary
    ``obs.profiler.TIME_COMPONENT_FIELDS`` and
    ``search.serve_search.pp_serve_cost`` share), so the report shows
    WHICH component a whole-plan prediction error lives in."""
    from .profiler import TIME_COMPONENT_FIELDS

    comp_fields = set(TIME_COMPONENT_FIELDS)
    per_plan: Dict[str, Dict] = {}
    for plan, fields in calibration.get("plans", {}).items():
        rows = {f: {"predicted": e.get("predicted"),
                    "measured": e.get("measured"),
                    "error_frac": e.get("error_frac")}
                for f, e in fields.items() if f in comp_fields}
        if rows:
            per_plan[plan] = rows
    scales = {f: c for f, c in calibration.get("components", {}).items()
              if f in comp_fields}
    return {
        "ticks": profile.get("ticks"),
        "phases": profile.get("phases", {}),
        "work": profile.get("work", {}),
        "components": per_plan,
        "component_scales": scales,
    }


def memory_section(memory: Dict, metrics: Dict) -> Dict:
    """The byte-side summary: live watermarks + occupancy distribution +
    the current gauge values + the per-component predicted-vs-allocated
    error table (the memory ledger's analog of ``prediction_error``).

    ``memory`` is a :meth:`~flexflow_tpu.obs.memory.MemoryLedger.report`
    dict (the ``{"kind": "memory"}`` JSONL line); ``metrics`` a registry
    snapshot — the gauge/histogram names come from ``MEMORY_GAUGES`` /
    ``KV_OCCUPANCY_HIST`` so the emitter and this reduction share one
    vocabulary.  Shared by ``bench.py --dry-run``'s ``memory_ledger``
    section and the trace-report CLI (one accounting, two consumers).
    """
    from .memory import (HOST_TIER_GAUGES, KV_OCCUPANCY_HIST, MEMORY_GAUGES,
                         PAGED_GAUGES)

    occ = metrics.get(KV_OCCUPANCY_HIST) or {}
    section: Dict = {
        "live": memory.get("live", {}),
        "occupancy_p50": occ.get("p50"),
        "occupancy_p95": occ.get("p95"),
        "gauges": {g: metrics[g] for g in MEMORY_GAUGES if g in metrics},
        "request_kv_bytes": metrics.get("request_kv_bytes"),
    }
    # paged-KV view (serve/kv_paged.py): page-pool gauges + the prefix
    # cache's hit/reuse counters — present only when a paged allocator
    # published them
    paged = {g: metrics[g] for g in PAGED_GAUGES if g in metrics}
    if paged:
        section["paged"] = paged
        section["prefix_cache"] = {
            k: metrics[k] for k in ("prefix_hits", "prefix_misses",
                                    "prefix_tokens_reused")
            if k in metrics}
    # host-tier view (serve/kv_paged.py HostPageTier): host-DRAM
    # occupancy gauges — present only when a tier was attached
    host = {g: metrics[g] for g in HOST_TIER_GAUGES if g in metrics}
    if host:
        section["host_tier"] = host
    alloc_err: Dict[str, Dict] = {}
    for plan, fields in memory.get("plans", {}).items():
        alloc_err[plan] = {
            f: {"predicted": e.get("predicted"),
                "allocated": e.get("measured"),
                "error_frac": e.get("error_frac")}
            for f, e in fields.items()}
    section["allocation_error"] = alloc_err
    # the per-component suggested_scale table that feeds MachineModel
    # memory-constant calibration (same geometry as the time components)
    section["components"] = memory.get("components", {})
    return section


# JSONL line kinds Telemetry.export writes -> fields each must carry
_REQUIRED_BY_KIND = {
    "telemetry_meta": ("version", "ts_unit", "events", "dropped"),
    "event": (),                      # per-phase rules below
    "metrics": ("snapshot",),
    "calibration": ("report",),
    "memory": ("report",),
    "workload": ("snapshot",),
    "profile": ("report",),
    "calibration_store": ("components", "applied_scales"),
}


def validate_jsonl(path: str) -> List[str]:
    """Validate a ``Telemetry.export`` JSONL against the event schema.

    Returns the list of violations (empty = valid).  The contract checked
    is exactly what :func:`summarize_jsonl` consumes: known line kinds
    with their required fields, well-formed trace events per phase, and —
    for the typed ``request``/``dispatch``/``plan`` categories — names and
    required args from ``telemetry.EVENT_SCHEMA``, the single vocabulary
    the emitters share.  ``bench.py --dry-run``'s export is validated by a
    tier-1 test, so the bench-side emitters and this parser cannot drift
    apart silently (``scripts/trace_report.py --check`` is the CLI).

    Free-form spans/counters on other categories are NOT constrained —
    instrumentation may add tracks freely; only the typed vocabulary is
    load-bearing for the report.
    """
    from .telemetry import EVENT_SCHEMA

    errors: List[str] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        return [f"unreadable: {e}"]
    if not lines:
        return ["empty file"]

    def err(i, msg):
        if len(errors) < 100:  # bounded output on pathological files
            errors.append(f"line {i}: {msg}")

    saw_meta = False
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except ValueError as e:
            err(i, f"not JSON: {e}")
            continue
        kind = doc.get("kind")
        if kind not in _REQUIRED_BY_KIND:
            err(i, f"unknown kind {kind!r}")
            continue
        missing = [k for k in _REQUIRED_BY_KIND[kind] if k not in doc]
        if missing:
            err(i, f"{kind} missing fields {missing}")
        if kind == "telemetry_meta":
            saw_meta = True
            continue
        if kind != "event":
            continue
        # trace-event phase rules
        ph = doc.get("ph")
        base_missing = [k for k in ("name", "ph", "pid", "tid")
                        if k not in doc]
        if base_missing:
            err(i, f"event missing fields {base_missing}")
            continue
        if ph not in ("M", "X", "i", "C"):
            err(i, f"unknown event phase {ph!r}")
            continue
        if ph == "M":
            if doc.get("name") != "thread_name" \
                    or "name" not in doc.get("args", {}):
                err(i, "metadata event must be thread_name with args.name")
            continue
        if "ts" not in doc:
            err(i, f"{ph!r} event missing ts")
        if ph == "X" and "dur" not in doc:
            err(i, "complete span missing dur")
        if ph == "C" and "value" not in doc.get("args", {}):
            err(i, "counter event missing args.value")
        # typed vocabulary: the categories the report parses semantically
        cat = doc.get("cat")
        if ph == "i" and cat in ("request", "dispatch", "plan", "profile",
                                 "fleet", "slo", "replay", "tier"):
            name = doc["name"]
            schema = EVENT_SCHEMA.get(name)
            if schema is None:
                err(i, f"unknown {cat} event {name!r}")
                continue
            want_cat, want_args = schema
            if cat != want_cat:
                err(i, f"{name} has cat {cat!r}, schema says {want_cat!r}")
            args = doc.get("args", {})
            missing = [a for a in want_args if a not in args]
            if missing:
                err(i, f"{name} missing args {missing}")
    if not saw_meta:
        errors.insert(0, "no telemetry_meta line")
    return errors


def under_load_summary(records: Dict, makespan_s: Optional[float] = None,
                       per_replica: bool = True,
                       per_class: bool = True) -> Dict:
    """Reduce ``RequestManager.serve_with_arrivals`` records to the
    ``serving_under_load`` fields: TTFT distribution (split into queue wait
    vs prefill where the records carry the split), per-request TPOT
    p50/p95, goodput.  Pure host-side math — the hermetic small-shape test
    (tests/test_serving_under_load.py) runs it on a virtual clock.

    Multi-worker records (``FleetRouter.serve_with_arrivals`` stamps the
    serving replica into each record's ``replica`` field, plus
    per-request ``failovers``) additionally get a ``per_replica``
    breakdown — the same reduction per serving replica, sharing the
    fleet-wide makespan so per-replica goodputs SUM to the fleet
    aggregate — and a total ``failovers`` count.

    SLO-lane records (``slo_class`` stamped when an
    :class:`~flexflow_tpu.serve.slo.SLOPolicy` was attached) get the
    same-shaped ``per_class`` breakdown — per-class goodput / TTFT /
    TPOT p50/p95 / outcome mix on the shared makespan, the view the
    per-class SLO attainment claims are checked against — plus a
    ``deferred_requests`` count (requests that spent at least one
    brownout window queue-held)."""
    recs = list(records.values())
    outcomes: Dict[str, int] = {}
    for r in recs:
        out = r.get("outcome", "ok")
        outcomes[out] = outcomes.get(out, 0) + 1
    # "completed" = ok finishes only; cancelled/timed-out/rejected/failed
    # requests are terminal but not completions
    done = [r for r in recs
            if "finish_s" in r and r.get("outcome", "ok") == "ok"]
    ttft = [r["first_token_s"] - r["arrival_s"]
            for r in recs if "first_token_s" in r]
    tpot = [(r["finish_s"] - r["first_token_s"])
            / max(len(r["tokens"]) - 1, 1)
            for r in done if "first_token_s" in r]
    queue_wait = [r["queue_wait_s"] for r in recs if "queue_wait_s" in r]
    prefill = [r["prefill_s"] for r in recs if "prefill_s" in r]

    makespan = makespan_s
    if makespan is None and done:
        makespan = (max(r["finish_s"] for r in done)
                    - min(r["arrival_s"] for r in recs))
    total_tokens = sum(len(r["tokens"]) for r in done)
    # deterministic work counters (obs/profiler.py): records carry a
    # per-request "work" dict when a StepProfiler was attached — the
    # totals give bench_compare device-free regression fields
    work_recs = [r["work"] for r in recs if isinstance(r.get("work"), dict)]
    work = None
    if work_recs:
        from .profiler import REQUEST_WORK_COUNTERS

        work = {k: sum(w.get(k, 0) for w in work_recs)
                for k in REQUEST_WORK_COUNTERS}
    # fleet breakdown: group by the serving replica (rejected-before-
    # placement records group under ""), reduce each group with the SAME
    # accounting and the fleet-wide makespan
    replica_summary = None
    failover_total = None
    if per_replica and any("replica" in r for r in recs):
        groups: Dict[str, Dict] = {}
        for rid, r in records.items():
            groups.setdefault(r.get("replica", ""), {})[rid] = r
        replica_summary = {
            name: under_load_summary(group, makespan_s=makespan,
                                     per_replica=False, per_class=False)
            for name, group in sorted(groups.items())}
        failover_total = sum(r.get("failovers", 0) for r in recs)
    # SLO-lane breakdown: group by the stamped class (records without a
    # class — no policy attached when they registered — group under "")
    class_summary = None
    deferred_total = None
    if per_class and any("slo_class" in r for r in recs):
        cgroups: Dict[str, Dict] = {}
        for rid, r in records.items():
            cgroups.setdefault(r.get("slo_class", ""), {})[rid] = r
        class_summary = {
            name: under_load_summary(group, makespan_s=makespan,
                                     per_replica=False, per_class=False)
            for name, group in sorted(cgroups.items())}
        deferred_total = sum(1 for r in recs
                             if r.get("deferred_ticks", 0) > 0)
    return {
        "requests": len(recs),
        "completed": len(done),
        "ttft_p50_ms": _pct_ms(ttft, 0.50),
        "ttft_p95_ms": _pct_ms(ttft, 0.95),
        "ttft_max_ms": _pct_ms(ttft, 1.0),
        "queue_wait_p50_ms": _pct_ms(queue_wait, 0.50),
        "queue_wait_p95_ms": _pct_ms(queue_wait, 0.95),
        "prefill_p50_ms": _pct_ms(prefill, 0.50),
        "tpot_p50_ms": _pct_ms(tpot, 0.50),
        "tpot_p95_ms": _pct_ms(tpot, 0.95),
        "goodput_tokens_per_sec": (round(total_tokens / makespan, 1)
                                   if makespan else None),
        "outcomes": outcomes,
        **({"work": work} if work is not None else {}),
        **({"per_replica": replica_summary}
           if replica_summary is not None else {}),
        **({"failovers": failover_total}
           if failover_total is not None else {}),
        **({"per_class": class_summary}
           if class_summary is not None else {}),
        **({"deferred_requests": deferred_total}
           if deferred_total is not None else {}),
    }
