"""Reductions over telemetry artifacts: JSONL summaries + serving records.

Two consumers share this module:

* ``scripts/trace_report.py`` — CLI over :func:`summarize_jsonl`: p50/p95
  TTFT/TPOT/queue-wait derived from the request-lifecycle events a
  ``Telemetry`` export carries, per-track span totals (the pp stage
  interleave), the pipeline bubble fraction, and the per-plan
  predicted-vs-measured error table.
* ``bench.py`` — :func:`under_load_summary` is the ``serving_under_load``
  section's record reduction (moved here from bench so the bench, the
  hermetic tests, and the report CLI all run the SAME accounting).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from .metrics import percentile

# request-lifecycle event names (the Telemetry.request_* schema)
_ENQ = "request_enqueue"
_ADMIT = "request_admit"
_PREFILL = "request_prefill_start"
_FIRST = "request_first_token"
_FINISH = "request_finish"
# resilient-serving lifecycle (terminal outcomes + dispatch events)
_TERMINAL_EVENTS = {
    "request_reject": "rejected",
    "request_cancel": "cancelled",
    "request_timeout": "timeout",
    "request_fail": "failed",
}
_PREEMPT = "request_preempt"
_RETRY = "dispatch_retry"
_FAULT = "dispatch_fault"


def _pct_ms(xs: List[float], q: float) -> Optional[float]:
    v = percentile(sorted(xs), q)
    return None if v is None else round(v * 1e3, 2)


def summarize_events(events: Sequence[Dict]) -> Dict:
    """Per-request latency distributions from lifecycle events (ts in
    microseconds, trace_event form) + per-track span time.

    ``span_ms_by_track`` sums complete-span durations per track, so it is
    only a wall-time total where spans on one track don't nest/overlap —
    the instrumentation keeps serve-loop, dispatch, pp-macro, and stage
    spans on separate tracks for exactly this reason.
    """
    reqs: Dict[str, Dict] = {}
    track_spans: Dict[int, float] = {}
    track_names: Dict[int, str] = {}
    outcomes: Dict[str, int] = {}
    preemptions = retries = faults = 0
    for ev in events:
        ph = ev.get("ph")
        if ph == "M" and ev.get("name") == "thread_name":
            track_names[ev.get("tid")] = ev.get("args", {}).get("name")
            continue
        if ph == "X":
            tid = ev.get("tid")
            track_spans[tid] = track_spans.get(tid, 0.0) \
                + ev.get("dur", 0.0) / 1e6
            continue
        name = ev.get("name")
        if name == _RETRY:
            retries += 1
            continue
        if name == _FAULT:
            faults += 1
            continue
        args = ev.get("args", {})
        trace_id = args.get("trace_id")
        if trace_id is None:
            continue
        rec = reqs.setdefault(trace_id, {})
        if name in (_ENQ, _ADMIT, _PREFILL, _FIRST, _FINISH):
            rec[name] = ev.get("ts", 0.0) / 1e6  # -> seconds
            if name == _FINISH:
                rec["n_tokens"] = args.get("n_tokens", 0)
        elif name in _TERMINAL_EVENTS:
            out = _TERMINAL_EVENTS[name]
            rec["outcome"] = out
            outcomes[out] = outcomes.get(out, 0) + 1
        elif name == _PREEMPT:
            preemptions += 1

    ttft, tpot, queue_wait, prefill = [], [], [], []
    completed = 0
    for rec in reqs.values():
        enq = rec.get(_ENQ)
        first = rec.get(_FIRST)
        fin = rec.get(_FINISH)
        if fin is not None:
            outcomes["ok"] = outcomes.get("ok", 0) + 1
        if enq is not None and first is not None:
            ttft.append(first - enq)
            # queue wait ends where prefill begins (fall back to admission
            # when no prefill-start stamp was emitted)
            start = rec.get(_PREFILL, rec.get(_ADMIT))
            if start is not None:
                queue_wait.append(start - enq)
                prefill.append(first - start)
        if fin is not None:
            completed += 1
            if first is not None:
                tpot.append((fin - first) / max(rec.get("n_tokens", 1) - 1, 1))

    spans_by_track = {
        track_names.get(tid, f"track{tid}"): round(total * 1e3, 3)
        for tid, total in sorted(track_spans.items())
    }
    return {
        "requests": len(reqs),
        "completed": completed,
        "ttft_p50_ms": _pct_ms(ttft, 0.50),
        "ttft_p95_ms": _pct_ms(ttft, 0.95),
        "queue_wait_p50_ms": _pct_ms(queue_wait, 0.50),
        "queue_wait_p95_ms": _pct_ms(queue_wait, 0.95),
        "prefill_p50_ms": _pct_ms(prefill, 0.50),
        "tpot_p50_ms": _pct_ms(tpot, 0.50),
        "tpot_p95_ms": _pct_ms(tpot, 0.95),
        "span_ms_by_track": spans_by_track,
        # resilient serving: terminal-outcome mix + recovery activity
        "outcomes": outcomes,
        "preemptions": preemptions,
        "dispatch_retries": retries,
        "dispatch_faults": faults,
    }


def summarize_jsonl(path: str) -> Dict:
    """Summarize a ``Telemetry.export`` JSONL: lifecycle distributions,
    bubble fraction, events/dropped, and per-plan prediction error."""
    events: List[Dict] = []
    meta: Dict = {}
    metrics: Dict = {}
    calibration: Dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            kind = doc.get("kind")
            if kind == "event":
                events.append(doc)
            elif kind == "telemetry_meta":
                meta = doc
            elif kind == "metrics":
                metrics = doc.get("snapshot", {})
            elif kind == "calibration":
                calibration = doc.get("report", {})

    summary = summarize_events(events)
    summary["events"] = meta.get("events", len(events))
    summary["dropped"] = meta.get("dropped", 0)
    summary["bubble_frac"] = metrics.get("pp_bubble_frac")
    # registry view of the resilience counters (the trace ring can drop
    # events under pressure; the counters are exact)
    from .telemetry import RESILIENCE_COUNTERS

    summary["robustness"] = {
        k: metrics[k] for k in RESILIENCE_COUNTERS if k in metrics}

    pred_err: Dict[str, Dict] = {}
    for plan, fields in calibration.get("plans", {}).items():
        row = {f: {"predicted": e.get("predicted"),
                   "measured": e.get("measured"),
                   "error_frac": e.get("error_frac")}
               for f, e in fields.items()}
        pred_err[plan] = row
    summary["prediction_error"] = pred_err
    summary["calibration_components"] = calibration.get("components", {})
    return summary


def under_load_summary(records: Dict, makespan_s: Optional[float] = None
                       ) -> Dict:
    """Reduce ``RequestManager.serve_with_arrivals`` records to the
    ``serving_under_load`` fields: TTFT distribution (split into queue wait
    vs prefill where the records carry the split), per-request TPOT
    p50/p95, goodput.  Pure host-side math — the hermetic small-shape test
    (tests/test_serving_under_load.py) runs it on a virtual clock."""
    recs = list(records.values())
    outcomes: Dict[str, int] = {}
    for r in recs:
        out = r.get("outcome", "ok")
        outcomes[out] = outcomes.get(out, 0) + 1
    # "completed" = ok finishes only; cancelled/timed-out/rejected/failed
    # requests are terminal but not completions
    done = [r for r in recs
            if "finish_s" in r and r.get("outcome", "ok") == "ok"]
    ttft = [r["first_token_s"] - r["arrival_s"]
            for r in recs if "first_token_s" in r]
    tpot = [(r["finish_s"] - r["first_token_s"])
            / max(len(r["tokens"]) - 1, 1)
            for r in done if "first_token_s" in r]
    queue_wait = [r["queue_wait_s"] for r in recs if "queue_wait_s" in r]
    prefill = [r["prefill_s"] for r in recs if "prefill_s" in r]

    makespan = makespan_s
    if makespan is None and done:
        makespan = (max(r["finish_s"] for r in done)
                    - min(r["arrival_s"] for r in recs))
    total_tokens = sum(len(r["tokens"]) for r in done)
    return {
        "requests": len(recs),
        "completed": len(done),
        "ttft_p50_ms": _pct_ms(ttft, 0.50),
        "ttft_p95_ms": _pct_ms(ttft, 0.95),
        "ttft_max_ms": _pct_ms(ttft, 1.0),
        "queue_wait_p50_ms": _pct_ms(queue_wait, 0.50),
        "queue_wait_p95_ms": _pct_ms(queue_wait, 0.95),
        "prefill_p50_ms": _pct_ms(prefill, 0.50),
        "tpot_p50_ms": _pct_ms(tpot, 0.50),
        "tpot_p95_ms": _pct_ms(tpot, 0.95),
        "goodput_tokens_per_sec": (round(total_tokens / makespan, 1)
                                   if makespan else None),
        "outcomes": outcomes,
    }
