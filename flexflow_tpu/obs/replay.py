"""Time-travel serving: traffic-trace capture + deterministic replay.

The ROADMAP's fleet-simulator item presupposes an artifact the repo could
not produce before this module: a RECORDED traffic trace.  The serving
stack's strongest correctness tool — the ``(rid, token_index)`` sample
fold, which makes every token a pure function of (seed, rid, index) and
the committed prefix — means a faithfully recorded arrival stream can be
replayed *exactly*: same plan + same seeds ⇒ bit-identical per-request
token streams and terminal outcomes, greedy AND seeded, including under
a recorded fault schedule.  That turns any production incident or perf
question into a hermetic, diffable experiment.

Three pieces:

* :class:`TrafficTraceRecorder` — the ``record_trace=`` handle
  ``RequestManager.serve_with_arrivals`` / ``SpecInferManager`` /
  ``FleetRouter.serve_with_arrivals`` thread their capture hooks
  through.  It writes a VERSIONED JSONL artifact: one ``trace_meta``
  header (driver class, full :class:`~flexflow_tpu.serve.
  request_manager.GenerationConfig` incl. sampling seed, plan key +
  engine shape, fault-injector seed/sites, fleet topology + scheduled
  kills, SLO-policy snapshot), one ``arrival`` line per offered request
  (offset, prompt tokens + hash, max_new, the RAW options dict —
  priority/ttl/deadline/spec/slo_class — malformed dicts replay their
  rejection identically), and one ``outcome`` line per request
  (terminal outcome, token stream + hash, the full latency
  decomposition, replica placement + failover count).
* :class:`TrafficTrace` — the loaded artifact (``TrafficTrace.load``).
* :class:`ReplayHarness` — re-drives any deployment from a trace on the
  virtual clock.  *Fidelity replay* (:meth:`ReplayHarness.replay` +
  :meth:`ReplayHarness.verify`) pins the recorded gen config / sampling
  seed / fault schedule / kill schedule onto a freshly built target and
  asserts per-request bit-identity against the recorded outcomes.
  *What-if replay* (:meth:`ReplayHarness.what_if`) prices a DIFFERENT
  tp×pp×m×kv_dtype×paged×spec×fleet-size candidate with the calibrated
  component cost model (``search.serve_search.price_plan`` /
  ``pp_serve_cost``) and runs the recorded arrivals through a
  deterministic slot-level event simulation — per-class latency /
  goodput / outcome-mix deltas with no device attached, compared under
  ``scripts/bench_compare.py``'s exact-counter/thresholded-latency
  discipline (:meth:`ReplayHarness.diff`).

Everything here is host-side Python on the virtual clock: recording a
trace can never change serve outputs (the recorder only appends to
lists — it never reads the serve loop's clock), pinned by
tests/test_replay.py.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Callable, Dict, List, Optional, Sequence

TRACE_VERSION = 1

# JSONL line kinds a trace artifact carries, in file order
TRACE_LINE_KINDS = ("trace_meta", "arrival", "outcome")

# outcome-record fields replayed runs are verified against (bit-identity
# fields compare exactly; the latency decomposition is measured and rides
# the what-if deltas instead)
FIDELITY_FIELDS = ("tokens", "outcome", "failovers")


def token_hash(tokens: Sequence[int]) -> str:
    """Stable short digest of a token sequence (prompt or output) — the
    integrity stamp arrival/outcome lines carry so a hand-edited trace
    cannot silently masquerade as a faithful recording."""
    payload = ",".join(str(int(t)) for t in tokens).encode()
    return hashlib.sha256(payload).hexdigest()[:16]


def plan_key_of(im) -> str:
    """Best-effort plan key for an InferenceManager-like engine — the
    same ``tp{t}_pp{p}_m{m}`` vocabulary the search/calibration stack
    uses, suffixed with the KV layout knobs that change the engine's
    compiled programs (int8 KV, paged KV)."""
    stages = getattr(im, "stage_plans", None)
    pp = len(stages) if stages else 1
    mesh = getattr(im, "mesh", None)
    if mesh is None:
        meshes = getattr(im, "stage_meshes", None)
        mesh = meshes[0] if meshes else None
    tp = 1
    if mesh is not None:
        try:
            tp = int(dict(zip(mesh.axis_names, mesh.devices.shape))
                     .get("tp", 1))
        except Exception:
            tp = 1
    m = int(getattr(im, "n_micro", 1) or 1)
    key = f"tp{tp}_pp{pp}_m{m}"
    if getattr(im, "kv_dtype", None):
        key += f"_kv{im.kv_dtype}"
    page = getattr(im, "kv_page_size", None)
    if page:
        key += f"_paged{page}"
    return key


def engine_shape_of(im) -> Dict:
    """The engine capacity/layout fields the what-if simulator and the
    fidelity check need from a deployment (serializable)."""
    return {
        "plan_key": plan_key_of(im),
        "max_requests": int(getattr(im, "max_requests", 1)),
        "max_seq_len": int(getattr(im, "max_seq_len", 0)),
        "kv_dtype": getattr(im, "kv_dtype", None),
        "kv_page_size": getattr(im, "kv_page_size", None),
    }


def injector_meta(injector) -> Optional[Dict]:
    """Serialize a :class:`~flexflow_tpu.serve.resilience.FaultInjector`'s
    full seeded schedule provenance (seed + site probabilities + bound) —
    what makes a recorded chaos run reproducible from the artifact
    alone."""
    if injector is None:
        return None
    return {
        "seed": getattr(injector, "seed", None),
        "p": getattr(injector, "p", 0.0),
        "p_by_site": dict(getattr(injector, "p_by_site", {}) or {}),
        "max_faults": getattr(injector, "max_faults", None),
    }


class VirtualClock:
    """Deterministic replay clock: advances ``step`` seconds per reading
    (the same contract as the bench dry-run sections' ``_Tick``)."""

    def __init__(self, step: float = 1e-3, t: float = 0.0):
        self.step = step
        self.t = t

    def __call__(self) -> float:
        self.t += self.step
        return self.t


class TrafficTraceRecorder:
    """The ``record_trace=`` capture handle.

    Serve loops call :meth:`begin_run` on entry (idempotent — a
    live-migration successor manager re-enters the same recorder and its
    meta lands as a ``continuations`` entry), :meth:`record_arrival`
    for every offered arrival at admit time, and :meth:`finalize` with
    the finished records dict.  ``path`` set at construction auto-saves
    on finalize; a bound ``telemetry`` handle emits the
    ``trace_recorded`` instant (EVENT_SCHEMA "replay" category) when the
    artifact lands on disk.

    The recorder NEVER reads the serve loop's clock and never touches
    the request objects — capture is append-only host bookkeeping, so a
    recorded run is bit-identical to an unrecorded one.
    """

    def __init__(self, path: Optional[str] = None, telemetry=None):
        self.path = path
        self.telemetry = telemetry
        self.meta: Optional[Dict] = None
        self.arrivals: List[Dict] = []
        self.outcomes: List[Dict] = []
        self.saved_path: Optional[str] = None

    # ---- capture hooks (called by the serve loops) --------------------
    def begin_run(self, meta: Dict) -> None:
        if self.meta is None:
            self.meta = dict(meta)
        else:
            # a live-migration successor re-entered serve_with_arrivals
            # with the same recorder: the original header stands, the
            # successor's plan provenance is appended
            self.meta.setdefault("continuations", []).append(dict(meta))

    def record_arrival(self, offset_s: float, prompt: Sequence[int],
                       max_new, opts: Optional[Dict]) -> None:
        line = {
            "offset_s": float(offset_s),
            "prompt": [int(t) for t in prompt],
            "prompt_len": len(prompt),
            "prompt_hash": token_hash(prompt),
            "max_new": (None if max_new is None else int(max_new)),
        }
        if opts is not None:
            line["opts"] = opts
        self.arrivals.append(line)

    def finalize(self, records: Dict[int, Dict]) -> None:
        """Stamp every finished serving record (the ``serve_with_arrivals``
        return schema) as an ``outcome`` line, then auto-save if a path
        was configured."""
        self.outcomes = []
        for rid in sorted(records):
            rec = records[rid]
            out = {
                "rid": int(rid),
                "trace_id": rec.get("trace_id", f"r{rid:05d}"),
                "outcome": rec.get("outcome", "ok"),
                "tokens": [int(t) for t in rec.get("tokens", [])],
                "tokens_hash": token_hash(rec.get("tokens", [])),
                "prompt_len": rec.get("prompt_len"),
                "arrival_s": rec.get("arrival_s"),
                "queue_wait_s": rec.get("queue_wait_s"),
                "prefill_s": rec.get("prefill_s"),
                "kv_bytes": rec.get("kv_bytes"),
            }
            for opt in ("first_token_s", "finish_s", "slo_class",
                        "deferred_ticks", "replica", "failovers"):
                if opt in rec:
                    out[opt] = rec[opt]
            self.outcomes.append(out)
        if self.path is not None:
            self.save(self.path)

    # ---- artifact I/O -------------------------------------------------
    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        if path is None:
            raise ValueError("no trace path configured")
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        meta = dict(self.meta or {})
        meta.update({"kind": "trace_meta", "version": TRACE_VERSION,
                     "arrivals": len(self.arrivals),
                     "requests": len(self.outcomes)})
        with open(path, "w") as f:
            f.write(json.dumps(meta) + "\n")
            for a in self.arrivals:
                f.write(json.dumps({"kind": "arrival", **a}) + "\n")
            for o in self.outcomes:
                f.write(json.dumps({"kind": "outcome", **o}) + "\n")
        self.saved_path = path
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            tel.trace_recorded(arrivals=len(self.arrivals), path=path,
                               requests=len(self.outcomes))
        return path

    def trace(self) -> "TrafficTrace":
        """The in-memory view (no file round trip needed)."""
        return TrafficTrace(meta=dict(self.meta or {}),
                            arrivals=list(self.arrivals),
                            outcomes=list(self.outcomes))


@dataclasses.dataclass
class TrafficTrace:
    """A loaded (or in-memory) traffic-trace artifact."""

    meta: Dict
    arrivals: List[Dict]
    outcomes: List[Dict]

    @classmethod
    def load(cls, path: str) -> "TrafficTrace":
        meta: Dict = {}
        arrivals: List[Dict] = []
        outcomes: List[Dict] = []
        with open(path) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                doc = json.loads(line)
                kind = doc.get("kind")
                if kind == "trace_meta":
                    meta = doc
                elif kind == "arrival":
                    arrivals.append(doc)
                elif kind == "outcome":
                    outcomes.append(doc)
                else:
                    raise ValueError(
                        f"{path}:{i}: unknown trace line kind {kind!r}")
        if not meta:
            raise ValueError(f"{path}: no trace_meta line")
        version = meta.get("version")
        if version != TRACE_VERSION:
            raise ValueError(f"{path}: trace version {version!r}, "
                             f"this reader speaks {TRACE_VERSION}")
        return cls(meta=meta, arrivals=arrivals, outcomes=outcomes)

    def validate(self) -> List[str]:
        """Integrity check: declared counts and token hashes.  Returns
        violations (empty = valid) — ``scripts/replay_report.py --check``
        is the CLI."""
        errors: List[str] = []
        if self.meta.get("arrivals") not in (None, len(self.arrivals)):
            errors.append(
                f"meta declares {self.meta.get('arrivals')} arrivals, "
                f"file carries {len(self.arrivals)}")
        if self.meta.get("requests") not in (None, len(self.outcomes)):
            errors.append(
                f"meta declares {self.meta.get('requests')} requests, "
                f"file carries {len(self.outcomes)}")
        if not self.meta.get("gen"):
            errors.append("trace_meta missing gen config (seed provenance)")
        for i, a in enumerate(self.arrivals):
            if token_hash(a.get("prompt", [])) != a.get("prompt_hash"):
                errors.append(f"arrival {i}: prompt hash mismatch")
        for o in self.outcomes:
            if token_hash(o.get("tokens", [])) != o.get("tokens_hash"):
                errors.append(
                    f"outcome {o.get('trace_id')}: tokens hash mismatch")
        return errors

    def arrival_tuples(self) -> List[tuple]:
        """The ``serve_with_arrivals`` arrival list this trace re-drives
        (offset, prompt, max_new[, opts]) — the RAW recorded options
        dict, so a malformed dict replays its rejection identically."""
        tuples = []
        for a in self.arrivals:
            t = (a["offset_s"], list(a["prompt"]), a["max_new"])
            if "opts" in a:
                t = t + (a["opts"],)
            tuples.append(t)
        return tuples

    def records(self) -> Dict[int, Dict]:
        """The recorded outcomes re-shaped as a ``serve_with_arrivals``
        records dict — the input ``obs.report.under_load_summary``
        reduces, so a trace summarizes with the SAME accounting as a
        live run."""
        recs: Dict[int, Dict] = {}
        for o in self.outcomes:
            rec = {k: v for k, v in o.items()
                   if k not in ("kind", "rid", "tokens_hash")}
            recs[o["rid"]] = rec
        return recs


class ReplayHarness:
    """Re-drive a recorded traffic trace against a deployment.

    ``telemetry`` (optional) emits the EVENT_SCHEMA "replay" vocabulary:
    ``replay_started`` / ``replay_completed`` instants plus one
    ``replay_mismatch`` per fidelity violation, and the
    ``replays_run`` / ``replay_mismatches`` exact counters
    ``scripts/bench_compare.py`` guards.
    """

    def __init__(self, trace: TrafficTrace, telemetry=None):
        self.trace = trace
        from .telemetry import telemetry_or_null

        self.telemetry = telemetry_or_null(telemetry)

    # ---- recorded-run provenance --------------------------------------
    def gen_config(self):
        """The recorded run's full GenerationConfig (incl. sampling
        seed) — what fidelity replay pins onto the target."""
        from ..serve.request_manager import GenerationConfig

        return GenerationConfig(**self.trace.meta.get("gen", {}))

    def fault_injector(self):
        """A FRESH FaultInjector with the recorded seed/sites/bound
        (None when the recorded run had no injector).  Fresh per call:
        replaying twice must replay the same schedule twice."""
        fault = self.trace.meta.get("fault")
        if not fault or fault.get("seed") is None:
            return None
        from ..serve.resilience import FaultInjector

        return FaultInjector(seed=fault["seed"], p=fault.get("p", 0.0),
                             p_by_site=fault.get("p_by_site"),
                             max_faults=fault.get("max_faults"))

    def arrivals(self) -> List[tuple]:
        return self.trace.arrival_tuples()

    # ---- fidelity replay ----------------------------------------------
    def pin(self, target) -> None:
        """Pin the recorded provenance onto ``target`` (a RequestManager,
        SpecInferManager, or FleetRouter): gen config + sampling seed,
        fault-injector schedule, and — for a fleet — the recorded
        replica-kill schedule.  The target's weights/plan are the
        caller's choice (fidelity needs the recorded plan + identical
        weights; a different plan is a what-if the caller measures)."""
        gen = self.gen_config()
        inj = self.fault_injector()
        target.gen = gen
        target.injector = inj
        reps = getattr(target, "replicas", None)
        if reps is not None:  # FleetRouter
            for rep in reps:
                rep.rm.gen = gen
                rep.rm.injector = inj
                rep.rm.im.fault_injector = inj
            fleet = self.trace.meta.get("fleet") or {}
            for name, tick in (fleet.get("kills") or {}).items():
                target.schedule_kill(name, int(tick))
        else:
            target.im.fault_injector = inj

    def replay(self, target, clock: Optional[Callable[[], float]] = None,
               quantum: int = 8, pin: bool = True,
               record_trace=None) -> Dict[int, Dict]:
        """Fidelity replay: drive ``target`` with the recorded arrival
        stream on a virtual clock (``pin=True`` installs the recorded
        gen/fault/kill provenance first).  Returns the replayed records;
        :meth:`verify` diffs them against the recording."""
        if pin:
            self.pin(target)
        tel = self.telemetry
        if tel.enabled:
            tel.replay_started(mode="fidelity",
                               driver=self.trace.meta.get("driver", ""),
                               arrivals=len(self.trace.arrivals))
        return target.serve_with_arrivals(
            self.arrivals(), clock=clock or VirtualClock(),
            quantum=quantum, record_trace=record_trace)

    def verify(self, records: Dict[int, Dict]) -> Dict:
        """Bit-identity check of a replayed run against the recording:
        per-request token streams, terminal outcomes, and failover
        counts must match EXACTLY (``FIDELITY_FIELDS``).  Emits one
        ``replay_mismatch`` instant per violation and the
        ``replay_completed`` summary instant."""
        recorded = {o["rid"]: o for o in self.trace.outcomes}
        mismatches: List[Dict] = []
        tel = self.telemetry
        for rid in sorted(set(recorded) | set(records)):
            old, new = recorded.get(rid), records.get(rid)
            tid = (old or new or {}).get("trace_id", f"r{rid:05d}")
            if old is None or new is None:
                mismatches.append({"trace_id": tid, "field": "presence",
                                   "recorded": old is not None,
                                   "replayed": new is not None})
                continue
            for field in FIDELITY_FIELDS:
                if field == "failovers" and field not in old \
                        and field not in new:
                    continue
                ov = old.get(field)
                nv = list(new.get(field) or []) if field == "tokens" \
                    else new.get(field, 0 if field == "failovers" else None)
                if field == "failovers":
                    ov = old.get(field, 0)
                if ov != nv:
                    mismatches.append({"trace_id": tid, "field": field,
                                       "recorded": ov, "replayed": nv})
        if tel.enabled:
            for mm in mismatches:
                tel.replay_mismatch(mm["trace_id"], mm["field"])
            tel.replay_completed(mode="fidelity",
                                 bit_identical=not mismatches,
                                 mismatches=len(mismatches))
        return {
            "requests": len(recorded),
            "replayed": len(records),
            "bit_identical": not mismatches,
            "mismatches": mismatches,
        }

    # ---- what-if replay ------------------------------------------------
    def what_if(self, price: Dict, fleet_size: int = 1,
                max_requests: Optional[int] = None,
                prefill_s_per_token: Optional[float] = None) -> Dict:
        """Price a DIFFERENT deployment candidate against the recorded
        arrival stream with NO device attached.

        ``price`` is a :func:`~flexflow_tpu.search.serve_search.
        price_plan` result (or any dict with ``tpot_s`` — the calibrated
        component-level cost model's steady-state seconds/token;
        ``plan_key`` labels the candidate, so tp×pp×m×kv_dtype×paged×
        spec variants all ride through one field).  ``fleet_size``
        scales the candidate to N identical replicas; ``max_requests``
        overrides the recorded engine's slot count.  Prefill is priced
        at ``prefill_s_per_token`` (default: the candidate's decode
        rate — conservative, one token-time per prompt position).

        The recorded arrivals run through a deterministic slot-level
        event simulation: earliest-free-slot placement over
        ``fleet_size × max_requests`` slots, per-request service =
        prompt prefill + (recorded output length) × tpot, TTL/deadline
        options re-applied to the simulated queue wait (so the outcome
        MIX responds to the candidate, not just the latencies).  Returns
        simulated records (the ``serve_with_arrivals`` schema),
        an ``under_load_summary`` reduction, and the candidate label —
        feed two of these to :meth:`diff` for the delta table.
        """
        tpot = float(price.get("tpot_s") or 0.0)
        if tpot <= 0.0 and price.get("tpot_ms"):
            tpot = float(price["tpot_ms"]) / 1e3
        if tpot <= 0.0:
            raise ValueError("candidate price carries no tpot_s/tpot_ms")
        pf = prefill_s_per_token if prefill_s_per_token is not None else tpot
        plan = self.trace.meta.get("plan") or {}
        slots_per = int(max_requests or plan.get("max_requests") or 1)
        n_slots = max(int(fleet_size), 1) * max(slots_per, 1)
        recorded = {o["rid"]: o for o in self.trace.outcomes}
        tel = self.telemetry
        if tel.enabled:
            tel.replay_started(mode="what_if",
                               driver=self.trace.meta.get("driver", ""),
                               arrivals=len(self.trace.arrivals))

        free_at = [0.0] * n_slots
        records: Dict[int, Dict] = {}
        sim_outcomes: Dict[str, int] = {}
        for rid, arrival in enumerate(sorted(
                self.trace.arrivals, key=lambda a: a["offset_s"])):
            off = float(arrival["offset_s"])
            opts = arrival.get("opts") or {}
            old = recorded.get(rid, {})
            # the output the candidate must serve: the recorded stream
            # (what-if changes WHEN tokens land, never WHICH tokens —
            # the fold makes streams plan-invariant); terminal-early
            # recorded requests fall back to their offered budget
            tokens = list(old.get("tokens", []))
            n_out = len(tokens)
            if n_out == 0 and old.get("outcome") not in ("ok", None):
                n_out = int(arrival.get("max_new") or 0)
            slot = min(range(n_slots), key=lambda s: free_at[s])
            start = max(off, free_at[slot])
            prefill_s = arrival["prompt_len"] * pf
            first = start + prefill_s + tpot
            finish = start + prefill_s + max(n_out, 1) * tpot
            rec: Dict = {
                "arrival_s": off,
                "admitted_s": off,
                "prompt_len": arrival["prompt_len"],
                "trace_id": old.get("trace_id", f"r{rid:05d}"),
                "queue_wait_s": max(start - off, 0.0),
                "prefill_s": prefill_s,
                "tokens": tokens,
                "outcome": "ok",
                "replica": f"sim{slot % max(int(fleet_size), 1)}",
            }
            if isinstance(opts, dict) and opts.get("slo_class") is not None:
                rec["slo_class"] = str(opts["slo_class"])
            # re-apply the request's own latency bound to the SIMULATED
            # schedule: a candidate that queues a request past its
            # ttl/deadline times it out — the outcome mix is priced, not
            # copied
            bound = None
            if isinstance(opts, dict):
                if opts.get("ttl_s") is not None:
                    bound = float(opts["ttl_s"])
                if opts.get("deadline_s") is not None:
                    d = float(opts["deadline_s"])
                    bound = d if bound is None else min(bound, d)
            if bound is not None and first - off > bound:
                rec["outcome"] = "timeout"
                rec["tokens"] = []
                rec["finish_s"] = off + bound
            else:
                if n_out > 0:
                    rec["first_token_s"] = first
                rec["finish_s"] = finish
                free_at[slot] = finish
            sim_outcomes[rec["outcome"]] = \
                sim_outcomes.get(rec["outcome"], 0) + 1
            records[rid] = rec
        from .report import under_load_summary

        summary = under_load_summary(records)
        if tel.enabled:
            tel.replay_completed(mode="what_if", bit_identical=None,
                                 mismatches=0)
        return {
            "candidate": {
                "plan_key": price.get("plan_key", "candidate"),
                "fleet_size": int(fleet_size),
                "slots": n_slots,
                "tpot_ms": round(tpot * 1e3, 4),
                "prefill_s_per_token": pf,
            },
            "records": records,
            "summary": summary,
            "outcomes": sim_outcomes,
        }

    def recorded_summary(self) -> Dict:
        """``under_load_summary`` of the RECORDED run (from the artifact
        alone) — the baseline side of every diff."""
        from .report import under_load_summary

        return under_load_summary(self.trace.records())

    def diff(self, old_summary: Dict, new_summary: Dict,
             default_threshold: float = 0.10) -> Dict:
        """Compare two run summaries (recorded vs replayed, or two
        what-if candidates) under ``scripts/bench_compare.py``'s
        discipline: deterministic counters exact, latency fields
        thresholded (increase = regression), throughput fields
        directional (decrease = regression)."""
        bc = load_bench_compare()
        return bc.compare(old_summary, new_summary,
                          default_threshold=default_threshold)


def load_bench_compare():
    """Import ``scripts/bench_compare.py`` (a script, not a package
    module) by path — obs and the scripts share ONE comparison
    discipline, so the replay diff can never drift from the CI gate."""
    import importlib.util
    import sys

    cached = sys.modules.get("_ff_bench_compare")
    if cached is not None:
        return cached
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "bench_compare.py")
    spec = importlib.util.spec_from_file_location("_ff_bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    sys.modules["_ff_bench_compare"] = mod
    return mod
