"""Workload characterization + drift detection for the serving planner.

A serve plan is priced for ONE traffic mix — the prompt/output lengths,
arrival rate, and occupancy the search saw — and silently degrades when
live traffic walks away from it (ROADMAP: "re-search when telemetry shows
the traffic mix drifted").  This module gives that drift a number:

* :class:`WorkloadProfile` — windowed histograms over the serving
  dimensions the cost model is sensitive to (prompt length, output
  length, inter-arrival gap, slot occupancy, speculative acceptance),
  maintained by the :class:`~flexflow_tpu.obs.telemetry.Telemetry` handle
  from the SAME ``request_*`` lifecycle calls the serving stack already
  makes — no new instrumentation sites, bounded memory (deque windows).
* :func:`psi` — population-stability-index distance between two
  histograms (the standard scorecard-monitoring drift statistic:
  ``sum((p-q) * ln(p/q))`` over smoothed bucket frequencies; 0 for
  identical distributions, ~0.1 "shifting", >0.25 "shifted").
* :class:`DriftDetector` — compares a REFERENCE profile (the one the
  executing plan was searched for) against the live window, emits a
  ``workload_drift_score`` gauge + per-dimension gauges, and an
  edge-triggered ``drift_detected`` instant when the score crosses the
  threshold.

Everything here is host-side arithmetic on Python scalars — nothing can
enter a jitted program, so the r8 bit-identity contract (serve outputs
identical with observability on or off) extends to the drift layer by
construction.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# bucket edges per dimension: lengths are log-spaced (a doubling of the
# prompt-length mix should move mass whole buckets, not fractions of one),
# fractions are deciles, inter-arrival gaps log-spaced in seconds
LEN_EDGES = (4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
IAT_EDGES = (1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0)
FRAC_EDGES = tuple(i / 10 for i in range(1, 10))

PROFILE_DIMS = ("prompt_len", "output_len", "interarrival_s", "occupancy",
                "spec_acceptance")
_DIM_EDGES = {
    "prompt_len": LEN_EDGES,
    "output_len": LEN_EDGES,
    "interarrival_s": IAT_EDGES,
    "occupancy": FRAC_EDGES,
    "spec_acceptance": FRAC_EDGES,
}


class _Window:
    """One dimension: bounded sample window + fixed-edge bucket counts."""

    __slots__ = ("edges", "count", "total", "_xs")

    def __init__(self, edges: Sequence[float], window: int):
        self.edges = tuple(edges)
        self.count = 0      # lifetime observations
        self.total = 0.0    # lifetime sum (for the lifetime mean)
        self._xs: deque = deque(maxlen=window)

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        self._xs.append(v)

    def _bucket(self, v: float) -> int:
        for i, e in enumerate(self.edges):
            if v <= e:
                return i
        return len(self.edges)

    def counts(self) -> List[int]:
        out = [0] * (len(self.edges) + 1)
        for v in self._xs:
            out[self._bucket(v)] += 1
        return out

    def mean(self) -> Optional[float]:
        if not self._xs:
            return None
        return sum(self._xs) / len(self._xs)

    def snapshot(self) -> Dict:
        return {"n": len(self._xs), "count": self.count,
                "mean": self.mean(), "edges": list(self.edges),
                "counts": self.counts()}


class WorkloadProfile:
    """Windowed histograms over the serving-traffic dimensions.

    ``window`` bounds per-dimension memory; the live window is what drift
    compares — a profile is "what traffic looked like recently", not a
    lifetime average that old traffic anchors forever.
    """

    def __init__(self, window: int = 512):
        from collections import deque

        self.window = window
        self._dims: Dict[str, _Window] = {
            d: _Window(_DIM_EDGES[d], window) for d in PROFILE_DIMS
        }
        self._last_arrival: Optional[float] = None
        # paged-KV prefix sharing: the windowed fraction of binds that hit
        # the prefix cache (serve/kv_paged.py).  Not a PSI drift dimension
        # — it feeds the serve search's sharing discount (the fraction of
        # offered prefill work the page pool absorbs).
        self._prefix_hits = deque(maxlen=window)

    # ---- observation hooks (fed by Telemetry.request_* et al.) --------
    def observe_enqueue(self, prompt_len: int,
                        ts: Optional[float] = None) -> None:
        """A request arrived: prompt-length sample + inter-arrival gap.
        ``ts`` is the enqueue instant's OWN timestamp (the caller already
        read the clock for the trace event — reuse it, never re-read)."""
        self._dims["prompt_len"].observe(prompt_len)
        if ts is not None:
            if self._last_arrival is not None and ts >= self._last_arrival:
                self._dims["interarrival_s"].observe(ts - self._last_arrival)
            self._last_arrival = ts

    def observe_finish(self, n_tokens: int) -> None:
        self._dims["output_len"].observe(n_tokens)

    def observe_occupancy(self, occ: float) -> None:
        self._dims["occupancy"].observe(occ)

    def observe_spec_acceptance(self, frac: float) -> None:
        self._dims["spec_acceptance"].observe(frac)

    def observe_prefix(self, hit: bool) -> None:
        """One paged-KV bind's prefix-cache outcome (Telemetry
        .prefix_cache_hit/miss feed this)."""
        self._prefix_hits.append(bool(hit))

    # ---- views ---------------------------------------------------------
    def snapshot(self) -> Dict:
        """JSON-ready per-dimension histograms (the drift comparand and
        the ``{"kind": "workload"}`` JSONL export line)."""
        return {"window": self.window,
                "dims": {d: w.snapshot() for d, w in self._dims.items()}}

    def features(self) -> Dict[str, float]:
        """Plan-facing scalars for ``search_serve_plan(workload=...)``.

        ``arrival_rate_per_s`` derives from the mean inter-arrival gap;
        dimensions with no samples fall back to neutral values (0 rates,
        full occupancy) so a cold profile never mis-steers the search.
        """
        d = self._dims
        mean_iat = d["interarrival_s"].mean()
        occ = d["occupancy"].mean()
        acc = d["spec_acceptance"].mean()
        return {
            "mean_prompt_len": d["prompt_len"].mean() or 0.0,
            "mean_output_len": d["output_len"].mean() or 0.0,
            "arrival_rate_per_s": (1.0 / mean_iat
                                   if mean_iat and mean_iat > 0 else 0.0),
            "mean_occupancy": occ if occ is not None else 1.0,
            "mean_spec_acceptance": acc if acc is not None else 0.0,
            # fraction of recent binds whose prompt prefix was already
            # cached (0.0 cold / unpaged — neutral: no sharing discount)
            "shared_prefix_frac": (sum(self._prefix_hits)
                                   / len(self._prefix_hits)
                                   if self._prefix_hits else 0.0),
            "n_requests": len(d["prompt_len"]._xs),
        }


def psi(p_counts: Iterable[float], q_counts: Iterable[float],
        eps: float = 1e-4) -> float:
    """Population stability index between two bucket-count vectors.

    Counts are normalized to frequencies with ``eps`` smoothing (an empty
    bucket on one side must not produce an infinite log-ratio).  Symmetric
    by construction; 0.0 iff the smoothed frequencies match.
    """
    p = [max(float(x), 0.0) for x in p_counts]
    q = [max(float(x), 0.0) for x in q_counts]
    if len(p) != len(q):
        raise ValueError(f"bucket mismatch: {len(p)} vs {len(q)}")
    sp, sq = sum(p) or 1.0, sum(q) or 1.0
    import math

    score = 0.0
    for a, b in zip(p, q):
        fa = a / sp + eps
        fb = b / sq + eps
        score += (fa - fb) * math.log(fa / fb)
    return score


def drift_score(reference: Dict, live: Dict,
                min_samples: int = 16) -> Dict:
    """Per-dimension PSI between two :meth:`WorkloadProfile.snapshot`
    docs, plus the aggregate ``score`` (the worst dimension — one
    dimension drifting alone is already a mispriced plan).

    Dimensions with fewer than ``min_samples`` live-or-reference samples
    are skipped (reported under ``skipped``) — a 3-sample histogram says
    nothing about the population.
    """
    per_dim: Dict[str, float] = {}
    skipped: List[str] = []
    rdims = reference.get("dims", {})
    ldims = live.get("dims", {})
    for d in PROFILE_DIMS:
        r, l = rdims.get(d), ldims.get(d)
        if r is None or l is None:
            continue
        if r.get("n", 0) < min_samples or l.get("n", 0) < min_samples:
            skipped.append(d)
            continue
        per_dim[d] = round(psi(r["counts"], l["counts"]), 4)
    score = max(per_dim.values()) if per_dim else 0.0
    worst = max(per_dim, key=per_dim.get) if per_dim else None
    return {"score": round(score, 4), "per_dim": per_dim,
            "worst_dim": worst, "skipped": skipped}


class DriftDetector:
    """Reference-vs-live drift with telemetry emission.

    ``reference`` is the profile snapshot the EXECUTING plan was searched
    for (capture it with ``profile.snapshot()`` at plan time).  Each
    :meth:`check` scores the live window against it, sets the
    ``workload_drift_score`` gauge (+ ``workload_psi_<dim>`` per
    dimension), and emits ONE ``drift_detected`` instant per excursion
    above ``threshold`` (edge-triggered; re-arms when the score falls
    back below).
    """

    def __init__(self, reference: Dict, threshold: float = 0.25,
                 min_samples: int = 16):
        if hasattr(reference, "snapshot"):
            reference = reference.snapshot()
        self.reference = reference
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.tripped = False

    def check(self, live, telemetry=None) -> Dict:
        if hasattr(live, "snapshot"):
            live = live.snapshot()
        rep = drift_score(self.reference, live,
                          min_samples=self.min_samples)
        rep["threshold"] = self.threshold
        rep["drifted"] = rep["score"] >= self.threshold
        if telemetry is not None and getattr(telemetry, "enabled", False):
            telemetry.metrics.gauge("workload_drift_score").set(rep["score"])
            telemetry.counter("workload_drift_score", rep["score"])
            for d, s in rep["per_dim"].items():
                telemetry.metrics.gauge(f"workload_psi_{d}").set(s)
            if rep["drifted"] and not self.tripped:
                telemetry.instant(
                    "drift_detected", cat="plan", track="plan_health",
                    score=rep["score"], threshold=self.threshold,
                    worst_dim=rep["worst_dim"])
        self.tripped = rep["drifted"]
        return rep
