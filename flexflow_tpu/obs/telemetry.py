"""The single ``Telemetry`` handle the serving stack is instrumented behind.

One handle bundles the three observability primitives:

* ``trace`` — a :class:`~flexflow_tpu.obs.trace.TraceRecorder` (request
  lifecycle, batch composition, scan quanta, per-stage pipeline dispatch);
* ``metrics`` — a :class:`~flexflow_tpu.obs.metrics.MetricsRegistry`
  (TTFT/TPOT/queue-wait histograms, occupancy/KV-utilization gauges,
  token/hop counters, pp bubble fraction);
* ``calibration`` — a :class:`~flexflow_tpu.obs.calibration.CalibrationLedger`
  (predicted-vs-measured cost accounting per executed plan).

``RequestManager(im, gen, telemetry=Telemetry())`` shares the handle with
the InferenceManager (and, for pipeline serving, every stage dispatch) —
one handle, one clock, one export.

**Serving lifecycle schema.**  The ``request_*`` methods are the canonical
event vocabulary: ``RequestManager`` emits through them, ``bench.py
--dry-run`` synthesizes through them, and ``scripts/trace_report.py``
parses exactly their names/args — adding a lifecycle event means adding a
method here, so the three cannot drift apart.

**Disabled = no-op, guaranteed.**  ``NULL_TELEMETRY`` (a
:class:`NullTelemetry`) answers every instrumentation call with a constant
no-op; ``enabled`` is False so hot paths can skip even argument
construction.  Telemetry is host-side only — nothing here is ever traced
into a jitted program — so serve outputs are bit-identical with telemetry
on or off (pinned by tests/test_obs.py).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, Optional

from .calibration import CalibrationLedger
from .drift import WorkloadProfile
from .memory import KV_OCCUPANCY_HIST, MEMORY_GAUGE_KEYS, MemoryLedger
from .metrics import MetricsRegistry
from .trace import TraceRecorder

# the resilience counter vocabulary (emitted by the request_rejected/
# cancelled/timed_out/preempted/failed + dispatch_retry/fault_observed
# methods below) — report.summarize_jsonl and bench's dry-run section both
# import THIS tuple, so a renamed counter cannot silently drop from either
RESILIENCE_COUNTERS = (
    "requests_rejected", "requests_cancelled", "requests_timeout",
    "requests_preempted", "requests_failed", "recompute_tokens",
    "dispatch_retries", "dispatch_faults",
)

# the typed-instant event schema: name -> (category, required arg keys).
# Telemetry's methods emit exactly these; report.summarize_jsonl parses
# them; scripts/trace_report.py --check validates exported JSONLs against
# THIS table — adding a lifecycle/plan event means adding a row here, so
# the three cannot drift apart (satellite of ISSUE 6: bench and
# trace_report schemas can never diverge silently).
EVENT_SCHEMA = {
    "request_enqueue": ("request", ("trace_id",)),
    "request_admit": ("request", ("trace_id",)),
    "request_prefill_start": ("request", ("trace_id",)),
    "request_first_token": ("request", ("trace_id",)),
    "request_finish": ("request", ("trace_id", "n_tokens")),
    "request_reject": ("request", ("trace_id",)),
    "request_cancel": ("request", ("trace_id",)),
    "request_timeout": ("request", ("trace_id",)),
    "request_preempt": ("request", ("trace_id",)),
    "request_fail": ("request", ("trace_id",)),
    "dispatch_retry": ("dispatch", ("site", "attempt")),
    "dispatch_fault": ("dispatch", ("site",)),
    # the observe->calibrate->re-plan loop (obs/drift.py, obs/plan_health.py)
    "drift_detected": ("plan", ("score",)),
    "replan_recommended": ("plan", ("incumbent", "candidate")),
    # memory observability (obs/memory.py, serve/kv_allocator.py): the
    # OOM-risk breach PlanHealthMonitor emits when projected KV growth
    # from the live workload profile eats the allocator's headroom
    "memory_pressure": ("plan", ("projected_bytes", "capacity_bytes")),
    # paged KV prefix sharing (serve/kv_paged.py): did a request's bind
    # reuse registered prefix pages (skipping that much prefill) or not
    "prefix_hit": ("request", ("trace_id",)),
    "prefix_miss": ("request", ("trace_id",)),
    # speculative serving as a production mode (serve/spec_infer.py): a
    # request's speculation mode flipped at runtime (``set_spec_mode``) —
    # ``args.spec`` carries the new mode
    "spec_mode_changed": ("request", ("trace_id", "spec")),
    # live plan migration (serve/migration.py): the MigrationController
    # acting on ``replan_recommended`` (or an operator request) —
    # started at the drain boundary; completed carries the preempted
    # count + admission-closed downtime; rolled_back names the failed
    # phase and the incumbent every request readmitted on
    "migration_started": ("plan", ("incumbent", "candidate")),
    "migration_completed": ("plan", ("incumbent", "candidate")),
    "migration_rolled_back": ("plan", ("incumbent", "candidate")),
    # step-level cost attribution (obs/profiler.py): one per serve tick,
    # emitted by StepProfiler.tick_end when a Telemetry handle is bound —
    # args carry the tick index plus the tick's deterministic work-counter
    # deltas (flops, kv_bytes_touched, dispatches, ...)
    "step_profile": ("profile", ("tick",)),
    # fault-tolerant fleet serving (serve/fleet.py): the per-replica
    # health state machine's transitions (HEALTHY -> DEGRADED ->
    # QUARANTINED -> DEAD, plus readmission back to HEALTHY after a
    # successful quarantine re-probe) and the failover of one request off
    # a failed replica onto a survivor (original rid preserved — the
    # recompute is bit-identical by the r9 sample-fold contract)
    "replica_up": ("fleet", ("replica",)),
    "replica_degraded": ("fleet", ("replica",)),
    "replica_quarantined": ("fleet", ("replica",)),
    "replica_dead": ("fleet", ("replica",)),
    "request_failed_over": ("request", ("trace_id", "from_replica",
                                        "to_replica")),
    # SLO-class lanes + brownout (serve/slo.py): the BrownoutController
    # walked the degradation ladder one level (args carry both endpoints
    # + the pressure reason), and one degradable-class request was shed
    # by the ladder (explicit REJECTED — never FAILED)
    "brownout_level_changed": ("slo", ("level", "from_level")),
    "lane_shed": ("slo", ("slo_class",)),
    # time-travel serving (obs/replay.py): a traffic-trace artifact
    # landed on disk (trace_recorded), a ReplayHarness run started /
    # finished (mode carries fidelity|what_if), and one per-request
    # fidelity violation (replay_mismatch names the request and the
    # field — tokens/outcome/failovers — that diverged from the
    # recording; a bit-identical replay emits ZERO of these)
    "trace_recorded": ("replay", ("arrivals",)),
    "replay_started": ("replay", ("mode",)),
    "replay_completed": ("replay", ("mode",)),
    "replay_mismatch": ("replay", ("trace_id", "field")),
    # host-tier KV spill/restore (serve/kv_paged.py HostPageTier): one
    # request's mapped pages moved off device (kv_spill — preemption /
    # page-pressure / brownout SPILL), moved back at readmission
    # (kv_restore — tokens_resumed is the write frontier the decode
    # resumes at, tokens_saved the prefill recompute avoided), or a
    # restore degraded to the r9 recompute feed (kv_restore_failed —
    # checksum corruption or swap-in retry exhaustion; never corruption)
    "kv_spill": ("tier", ("trace_id", "pages", "nbytes", "tokens")),
    "kv_restore": ("tier", ("trace_id", "pages", "nbytes",
                            "tokens_resumed", "tokens_saved")),
    "kv_restore_failed": ("tier", ("trace_id", "reason")),
}

# migration counter/gauge vocabulary (report.py folds these into the
# ``migrations`` summary section; the dry-run section and trace_report
# share THIS tuple so a renamed metric cannot silently drop from either).
# The first two are exact cumulative counters; the downtime/preempted
# entries are gauges holding the LAST migration's values — per-migration
# numbers ride the migration_completed event args
MIGRATION_COUNTERS = (
    "migrations_completed", "migrations_rolled_back",
    "migration_downtime_ticks", "migration_preempted_requests",
)

# fleet counter/gauge vocabulary (serve/fleet.py; report.py folds these
# into the ``fleet`` summary section — one tuple shared by the emitters,
# the report, and the bench dry-run so a renamed metric cannot silently
# drop from any of them).  The ``replica_*``/``failovers_total`` entries
# are exact cumulative counters; ``fleet_replicas_healthy`` /
# ``fleet_replicas_alive`` / ``fleet_queue_depth`` are gauges the router
# publishes every fleet tick.
FLEET_COUNTERS = (
    "failovers_total", "replica_ups", "replica_degradations",
    "replica_quarantines", "replica_deaths",
    "fleet_replicas_healthy", "fleet_replicas_alive",
    "fleet_replicas_total", "fleet_queue_depth",
)

# the monotone bad-if-increasing subset scripts/bench_compare.py treats
# like deterministic WORK_COUNTERS (exact compare, any increase between
# two runs of the same workload is a regression — more replicas failing
# per served token); the health gauges stay out (a gauge's direction is
# not monotone-bad, so exact-compare semantics would invert)
FLEET_REGRESSION_COUNTERS = (
    "failovers_total", "replica_degradations", "replica_quarantines",
    "replica_deaths",
)

# SLO-lane / brownout counter vocabulary (serve/slo.py; report.py folds
# these into the ``slo`` summary section — one tuple shared by the
# emitters, the report, and the bench dry-run).  All are exact cumulative
# counters except ``brownout_level``, a gauge holding the ladder's
# current level.
SLO_COUNTERS = (
    "lane_deferred_total", "lane_shed_total", "lane_degraded_total",
    "brownout_escalations", "brownout_deescalations", "brownout_level",
)

# the monotone bad-if-increasing subset that joins bench_compare's exact
# class (deterministic on the seeded virtual clock): more shed /
# deferred requests or more ladder escalations for the same workload
# means the lanes got less graceful.  De-escalations and the level gauge
# stay out (non-monotone direction).
SLO_REGRESSION_COUNTERS = (
    "lane_shed_total", "lane_deferred_total", "lane_degraded_total",
    "brownout_escalations",
)

# Host-tick elimination ratios (on-device continuous batching,
# serve/request_manager.py chained decode stretches).  Raw ``dispatches``
# and ``host_syncs`` are already exact-class via WORK_COUNTERS; these are
# the DERIVED per-unit ratios the ``host_tick`` bench section emits —
# deterministic on the virtual clock and monotone bad-if-increasing
# (more dispatches per token or host syncs per stretch means the host
# tick crept back in), so bench_compare compares them exactly too.
# ``stretch_joins`` (mid-stretch slot joins) is reported but stays out
# of the regression class — its direction depends on the arrival mix.
HOST_TICK_REGRESSION_COUNTERS = (
    "dispatches_per_token", "host_syncs_per_stretch",
)

# Trace-replay counter vocabulary (obs/replay.py; report.py folds these
# into the ``replay`` summary section — one tuple shared by the
# emitters, the report, and the bench ``trace_replay`` dry-run).  All
# exact cumulative counters.
REPLAY_COUNTERS = (
    "traces_recorded", "replays_run", "replay_mismatches",
)

# the monotone bad-if-increasing subset joining bench_compare's exact
# class: ANY replay mismatch means a recorded run stopped replaying
# bit-identically — the strongest determinism regression signal the
# repo has, so the threshold is exactly zero.
REPLAY_REGRESSION_COUNTERS = (
    "replay_mismatches",
)

# Trace-drop hardening: the TraceRecorder ring buffer's dropped-event
# count was only a stderr WARNING in trace_report; as an exact-class
# counter, a bench section that silently starts losing telemetry events
# (capacity regression, emit storm) fails bench_compare instead.
# report.py stamps it into every summary from the telemetry_meta line.
TRACE_REGRESSION_COUNTERS = (
    "telemetry_events_dropped",
)

# Host-tier KV spill/restore counter vocabulary (serve/kv_paged.py;
# report.py folds these into the ``tier`` summary section — one tuple
# shared by the emitters, the report, and the bench ``kv_tiering``
# dry-run).  All exact cumulative counters on the seeded virtual clock.
TIER_COUNTERS = (
    "kv_pages_spilled", "kv_pages_restored", "kv_swap_bytes",
    "kv_restore_failures", "recompute_tokens_saved",
)

# the monotone bad-if-increasing subset joining bench_compare's exact
# class: a restore failure means a checksum-verified swap-in degraded to
# recompute — correct but strictly worse, so the clean-path threshold is
# exactly zero (kv_spilled/kv_restored materialize it at 0 so a healthy
# baseline exports the field and the guard arms).  The volume counters
# stay out: more spills for the same workload can mean better brownout
# behavior, not worse — direction is not monotone.
TIER_REGRESSION_COUNTERS = (
    "kv_restore_failures",
)


class Telemetry:
    enabled = True

    def __init__(self, capacity: int = 65536,
                 clock: Optional[Callable[[], float]] = None,
                 workload_window: int = 512):
        self._clock = clock or time.perf_counter
        self.trace = TraceRecorder(capacity=capacity, clock=self._clock)
        self.metrics = MetricsRegistry()
        self.calibration = CalibrationLedger()
        # windowed traffic-mix characterization, fed by the request_* /
        # batch_composition / spec_acceptance calls below — the live side
        # of drift detection (obs/drift.py).  It reuses the trace events'
        # timestamps, so enabling it costs no extra clock reads.
        self.workload = WorkloadProfile(window=workload_window)
        # the byte-side ledger (obs/memory.py): predicted-vs-allocated HBM
        # per plan component + live watermarks — the analog of
        # ``calibration`` for memory.  Fed by the managers' publish_memory
        # and the KVAllocator's per-tick kv_usage observations.
        self.memory = MemoryLedger()
        # optional persisted CalibrationStore: attach one to have export()
        # write its applied scales alongside the ledger report
        self.store = None
        # optional StepProfiler (obs/profiler.py), bound via
        # StepProfiler.bind(telemetry): export() then writes the phase
        # time budget + deterministic work counters as a "profile" line
        self.profiler = None

    # ---- primitive delegation -----------------------------------------
    def now(self) -> float:
        return self._clock()

    def span(self, name, cat="serve", track="serve", **args):
        return self.trace.span(name, cat, track, **args)

    def instant(self, name, cat="serve", track="serve", **args):
        return self.trace.instant(name, cat, track, **args)

    def counter(self, name, value, track="counters"):
        """Counter-series trace event ("C" phase) only — registry metrics
        are updated explicitly by callers (a name like ``decode_tokens``
        may be a registry Counter; auto-registering a Gauge here would
        type-clash it)."""
        self.trace.counter(name, value, track)

    # ---- serving lifecycle (see module docstring) ---------------------
    def request_enqueued(self, trace_id: str, prompt_len: int = 0) -> float:
        self.metrics.counter("requests_enqueued").inc()
        ts = self.trace.instant("request_enqueue", "request", "requests",
                                trace_id=trace_id, prompt_len=prompt_len)
        self.workload.observe_enqueue(prompt_len, ts=ts)
        return ts

    def request_admitted(self, trace_id: str,
                         queue_wait_s: Optional[float] = None) -> float:
        self.metrics.counter("requests_admitted").inc()
        if queue_wait_s is not None:
            self.metrics.histogram("queue_wait_s").observe(queue_wait_s)
        return self.trace.instant("request_admit", "request", "requests",
                                  trace_id=trace_id,
                                  queue_wait_s=queue_wait_s)

    def request_prefill_started(self, trace_id: str) -> float:
        return self.trace.instant("request_prefill_start", "request",
                                  "requests", trace_id=trace_id)

    def request_first_token(self, trace_id: str,
                            ttft_s: Optional[float] = None,
                            slo_class: Optional[str] = None) -> float:
        if ttft_s is not None:
            self.metrics.histogram("ttft_s").observe(ttft_s)
            if slo_class:
                # per-class attainment: the brownout controller and the
                # plan-health per-class checks read these windows
                self.metrics.histogram(
                    f"ttft_s_cls_{slo_class}").observe(ttft_s)
        return self.trace.instant("request_first_token", "request",
                                  "requests", trace_id=trace_id,
                                  ttft_s=ttft_s)

    def request_finished(self, trace_id: str, n_tokens: int,
                         tpot_s: Optional[float] = None,
                         kv_bytes: Optional[float] = None,
                         slo_class: Optional[str] = None) -> float:
        """``kv_bytes``: the KVAllocator's per-request attribution (peak
        cache bytes the request held) — the byte-side cost of serving it."""
        self.metrics.counter("requests_finished").inc()
        self.metrics.counter("tokens_generated").inc(n_tokens)
        if tpot_s is not None:
            self.metrics.histogram("tpot_s").observe(tpot_s)
            if slo_class:
                self.metrics.histogram(
                    f"tpot_s_cls_{slo_class}").observe(tpot_s)
        if kv_bytes is not None:
            self.metrics.histogram("request_kv_bytes").observe(kv_bytes)
        self.workload.observe_finish(n_tokens)
        return self.trace.instant("request_finish", "request", "requests",
                                  trace_id=trace_id, n_tokens=n_tokens,
                                  tpot_s=tpot_s, kv_bytes=kv_bytes)

    # ---- resilient serving (serve/resilience.py) ----------------------
    def request_rejected(self, trace_id: str, reason: str = "") -> float:
        """Admission control refused the request (bounded queue / KV
        headroom / invalid shape) — an explicit terminal outcome."""
        self.metrics.counter("requests_rejected").inc()
        return self.trace.instant("request_reject", "request", "requests",
                                  trace_id=trace_id, reason=reason)

    def request_cancelled(self, trace_id: str, n_tokens: int = 0) -> float:
        self.metrics.counter("requests_cancelled").inc()
        return self.trace.instant("request_cancel", "request", "requests",
                                  trace_id=trace_id, n_tokens=n_tokens)

    def request_timed_out(self, trace_id: str, n_tokens: int = 0) -> float:
        self.metrics.counter("requests_timeout").inc()
        return self.trace.instant("request_timeout", "request", "requests",
                                  trace_id=trace_id, n_tokens=n_tokens)

    def request_preempted(self, trace_id: str,
                          recompute_tokens: int = 0) -> float:
        """Slot/KV-pressure eviction; ``recompute_tokens`` is the
        prompt+generated length the readmission will re-prefill."""
        self.metrics.counter("requests_preempted").inc()
        self.metrics.counter("recompute_tokens").inc(recompute_tokens)
        return self.trace.instant("request_preempt", "request", "requests",
                                  trace_id=trace_id,
                                  recompute_tokens=recompute_tokens)

    def request_failed(self, trace_id: str, site: str = "") -> float:
        self.metrics.counter("requests_failed").inc()
        return self.trace.instant("request_fail", "request", "requests",
                                  trace_id=trace_id, site=site)

    def dispatch_retry(self, site: str, attempt: int = 1,
                       backoff_s: float = 0.0) -> float:
        self.metrics.counter("dispatch_retries").inc()
        return self.trace.instant("dispatch_retry", "dispatch", "dispatch",
                                  site=site, attempt=attempt,
                                  backoff_s=backoff_s)

    def fault_observed(self, site: str, detail: str = "") -> float:
        """A transient dispatch/hop fault was caught (injected or real)."""
        self.metrics.counter("dispatch_faults").inc()
        return self.trace.instant("dispatch_fault", "dispatch", "dispatch",
                                  site=site, detail=detail)

    # ---- paged KV prefix sharing (serve/kv_paged.py) ------------------
    def prefix_cache_hit(self, trace_id: str, tokens_reused: int = 0,
                         pages: int = 0) -> float:
        """A bind reused ``tokens_reused`` positions of registered prefix
        pages — that much prefill is skipped (TTFT collapses to the
        unshared suffix)."""
        self.metrics.counter("prefix_hits").inc()
        self.metrics.counter("prefix_tokens_reused").inc(tokens_reused)
        self.workload.observe_prefix(True)
        return self.trace.instant("prefix_hit", "request", "requests",
                                  trace_id=trace_id,
                                  tokens_reused=tokens_reused, pages=pages)

    def prefix_cache_miss(self, trace_id: str) -> float:
        self.metrics.counter("prefix_misses").inc()
        self.workload.observe_prefix(False)
        return self.trace.instant("prefix_miss", "request", "requests",
                                  trace_id=trace_id)

    def batch_composition(self, decode_tokens: int, prefill_tokens: int,
                          active_requests: int, max_requests: int,
                          kv_tokens: int, kv_capacity: int) -> None:
        """Per-step batch mix: token split, slot occupancy, KV utilization."""
        m = self.metrics
        m.counter("decode_tokens").inc(decode_tokens)
        m.counter("prefill_tokens").inc(prefill_tokens)
        occ = active_requests / max_requests if max_requests else 0.0
        util = kv_tokens / kv_capacity if kv_capacity else 0.0
        m.gauge("batch_slot_occupancy").set(occ)
        m.gauge("kv_cache_utilization").set(util)
        self.workload.observe_occupancy(occ)
        self.trace.counter("batch_slot_occupancy", occ)
        self.trace.counter("kv_cache_utilization", util)

    def spec_mode_changed(self, trace_id: str, spec: bool) -> float:
        """A request's speculation mode flipped at runtime
        (``RequestManager.set_spec_mode``): spec rows draft+verify
        multi-token per macro step, plain rows decode one token — in the
        SAME mixed batch under a SpecInferManager."""
        self.metrics.counter("spec_mode_changes").inc()
        return self.trace.instant("spec_mode_changed", "request", "requests",
                                  trace_id=trace_id, spec=bool(spec))

    # ---- live plan migration (serve/migration.py) ---------------------
    def migration_started(self, incumbent: str, candidate: str,
                          reasons: str = "") -> float:
        """A live plan switch began: admission is closed and the drain is
        about to preempt the in-flight requests onto the recompute path."""
        return self.trace.instant("migration_started", "plan", "migration",
                                  incumbent=incumbent, candidate=candidate,
                                  reasons=reasons)

    def migration_completed(self, incumbent: str, candidate: str,
                            mode: str = "rebuild",
                            preempted_requests: int = 0,
                            downtime_ticks: int = 0,
                            downtime_s: Optional[float] = None) -> float:
        """The candidate plan is serving: ``preempted_requests`` rode the
        recompute path across the switch, ``downtime_ticks`` serve ticks
        ran with admission closed (the drain grace window), and
        ``mode="spec_flip"`` marks the rebuild-free fast path."""
        m = self.metrics
        m.counter("migrations_completed").inc()
        m.gauge("migration_downtime_ticks").set(downtime_ticks)
        m.gauge("migration_preempted_requests").set(preempted_requests)
        return self.trace.instant(
            "migration_completed", "plan", "migration",
            incumbent=incumbent, candidate=candidate, mode=mode,
            preempted_requests=preempted_requests,
            downtime_ticks=downtime_ticks, downtime_s=downtime_s)

    def migration_rolled_back(self, incumbent: str, candidate: str,
                              phase: str = "", reason: str = "") -> float:
        """The switch failed in ``phase`` (drain/rebuild/readmit):
        admission reopened on the incumbent and every drained request
        readmitted there — zero lost requests by contract."""
        self.metrics.counter("migrations_rolled_back").inc()
        return self.trace.instant(
            "migration_rolled_back", "plan", "migration",
            incumbent=incumbent, candidate=candidate, phase=phase,
            reason=reason)

    # ---- fault-tolerant fleet serving (serve/fleet.py) -----------------
    def replica_up(self, replica: str, reason: str = "") -> float:
        """A replica joined (or re-joined, after a successful quarantine
        re-probe) the dispatch rotation in the HEALTHY state."""
        self.metrics.counter("replica_ups").inc()
        return self.trace.instant("replica_up", "fleet", "fleet",
                                  replica=replica, reason=reason)

    def replica_degraded(self, replica: str, reason: str = "") -> float:
        """Dispatch failures pushed a replica to DEGRADED: it keeps
        serving its in-flight requests but new dispatches avoid it."""
        self.metrics.counter("replica_degradations").inc()
        return self.trace.instant("replica_degraded", "fleet", "fleet",
                                  replica=replica, reason=reason)

    def replica_quarantined(self, replica: str, reason: str = "") -> float:
        """Consecutive failures quarantined a replica: its in-flight
        requests failed over to survivors and it leaves the rotation
        until a re-probe succeeds (or probes exhaust into DEAD)."""
        self.metrics.counter("replica_quarantines").inc()
        return self.trace.instant("replica_quarantined", "fleet", "fleet",
                                  replica=replica, reason=reason)

    def replica_dead(self, replica: str, reason: str = "",
                     failed_over: int = 0) -> float:
        """A replica is terminally dead (quarantine probes exhausted, or
        an operator kill): its KV tore down (refcount no-leak asserted by
        the chaos tests) and ``failed_over`` in-flight requests moved to
        survivors through the r9 recompute path."""
        self.metrics.counter("replica_deaths").inc()
        return self.trace.instant("replica_dead", "fleet", "fleet",
                                  replica=replica, reason=reason,
                                  failed_over=failed_over)

    def request_failed_over(self, trace_id: str, from_replica: str,
                            to_replica: str) -> float:
        """A request left a failed replica and re-dispatched onto a
        survivor with its ORIGINAL rid — the recompute re-prefills
        prompt+generated there, bit-identical for greedy AND seeded
        sampling (the (rid, token_index) fold crosses replicas)."""
        self.metrics.counter("failovers_total").inc()
        return self.trace.instant("request_failed_over", "request",
                                  "requests", trace_id=trace_id,
                                  from_replica=from_replica,
                                  to_replica=to_replica)

    def fleet_health(self, healthy: int, alive: int, total: int,
                     queue_depth: int) -> None:
        """Per-fleet-tick health gauges: HEALTHY replicas, alive
        (HEALTHY + DEGRADED) replicas, the built fleet size, and the
        shared admission queue's depth."""
        m = self.metrics
        m.gauge("fleet_replicas_healthy").set(healthy)
        m.gauge("fleet_replicas_alive").set(alive)
        m.gauge("fleet_replicas_total").set(total)
        m.gauge("fleet_queue_depth").set(queue_depth)
        self.trace.counter("fleet_replicas_healthy", healthy)
        self.trace.counter("fleet_queue_depth", queue_depth)

    # ---- SLO-class lanes + brownout (serve/slo.py) ---------------------
    def brownout_level_changed(self, level: int, from_level: int,
                               level_name: str = "",
                               reason: str = "") -> float:
        """The BrownoutController stepped the degradation ladder one
        level (up on ``escalate_after`` pressured windows, down on
        ``deescalate_after`` clean ones — the hysteresis contract)."""
        m = self.metrics
        if level > from_level:
            m.counter("brownout_escalations").inc()
        else:
            m.counter("brownout_deescalations").inc()
        m.gauge("brownout_level").set(level)
        return self.trace.instant("brownout_level_changed", "slo", "slo",
                                  level=level, from_level=from_level,
                                  level_name=level_name, reason=reason)

    def lane_shed(self, slo_class: str, trace_id: str = "",
                  reason: str = "") -> float:
        """The ladder shed one degradable-class request (queued or — at
        CRITICAL_ONLY — live) as an explicit ``REJECTED``."""
        self.metrics.counter("lane_shed_total").inc()
        return self.trace.instant("lane_shed", "slo", "slo",
                                  slo_class=slo_class, trace_id=trace_id,
                                  reason=reason)

    def lane_deferred(self, slo_class: str, count: int = 1) -> None:
        """``count`` queued requests of a degradable class were held out
        of engine slots this brownout window (DEFER_BATCH semantics)."""
        self.metrics.counter("lane_deferred_total").inc(count)

    def lane_degraded(self, slo_class: str, count: int = 1) -> None:
        """``count`` live requests had speculation flipped off and/or
        their output capped (DEGRADE_BATCH semantics)."""
        self.metrics.counter("lane_degraded_total").inc(count)

    def lane_depths(self, depths: Dict[str, int]) -> None:
        """Per-class pending-queue depth gauges, published each brownout
        evaluation window (``lane_pending_depth_<class>``)."""
        for name, depth in depths.items():
            self.metrics.gauge(f"lane_pending_depth_{name}").set(depth)
            self.trace.counter(f"lane_pending_depth_{name}", depth)

    def trace_recorded(self, arrivals: int, path: str = "",
                       requests: int = 0) -> float:
        """A traffic-trace artifact (obs/replay.py JSONL) landed on
        disk: ``arrivals`` offered requests, ``requests`` finished
        outcome lines."""
        self.metrics.counter("traces_recorded").inc()
        return self.trace.instant("trace_recorded", "replay", "replay",
                                  arrivals=arrivals, path=path,
                                  requests=requests)

    def replay_started(self, mode: str, driver: str = "",
                       arrivals: int = 0) -> float:
        """A ReplayHarness run began re-driving a recorded trace
        (``mode`` is fidelity|what_if)."""
        return self.trace.instant("replay_started", "replay", "replay",
                                  mode=mode, driver=driver,
                                  arrivals=arrivals)

    def replay_completed(self, mode: str, bit_identical=None,
                         mismatches: int = 0) -> float:
        """A ReplayHarness run finished (``bit_identical`` is the
        fidelity verdict; None for what-if runs, which price a DIFFERENT
        plan and have no bit-identity contract)."""
        self.metrics.counter("replays_run").inc()
        # materialize the mismatch counter at 0 even on a clean run: the
        # exact-class guard only fires when the REFERENCE artifact
        # carries the field, so a healthy baseline must export it
        self.metrics.counter("replay_mismatches").inc(0)
        return self.trace.instant("replay_completed", "replay", "replay",
                                  mode=mode, bit_identical=bit_identical,
                                  mismatches=mismatches)

    def replay_mismatch(self, trace_id: str, field: str) -> float:
        """One per-request fidelity violation: ``field`` (tokens /
        outcome / failovers / presence) diverged from the recording.
        Exact-class regression counter — any increase fails
        bench_compare."""
        self.metrics.counter("replay_mismatches").inc()
        return self.trace.instant("replay_mismatch", "replay", "replay",
                                  trace_id=trace_id, field=field)

    # ---- host-tier KV spill/restore (serve/kv_paged.py) ----------------
    def kv_spilled(self, trace_id: str, pages: int = 0, nbytes: int = 0,
                   tokens: int = 0) -> float:
        """One request's mapped KV pages moved to the host tier
        (preemption, page pressure, or the brownout SPILL action)."""
        m = self.metrics
        m.counter("kv_pages_spilled").inc(pages)
        m.counter("kv_swap_bytes").inc(nbytes)
        # materialize the failure counter at 0 on the clean path: the
        # exact-class guard only fires when the reference artifact
        # carries the field, so a healthy baseline must export it
        m.counter("kv_restore_failures").inc(0)
        return self.trace.instant("kv_spill", "tier", "tier",
                                  trace_id=trace_id, pages=pages,
                                  nbytes=nbytes, tokens=tokens)

    def kv_restored(self, trace_id: str, pages: int = 0, nbytes: int = 0,
                    tokens_resumed: int = 0, tokens_saved: int = 0) -> float:
        """A readmitted request's pages came back from the host tier —
        ``tokens_resumed`` is the restored write frontier, ``tokens_saved``
        the prefill recompute the restore avoided."""
        m = self.metrics
        m.counter("kv_pages_restored").inc(pages)
        m.counter("kv_swap_bytes").inc(nbytes)
        m.counter("recompute_tokens_saved").inc(tokens_saved)
        m.counter("kv_restore_failures").inc(0)
        return self.trace.instant("kv_restore", "tier", "tier",
                                  trace_id=trace_id, pages=pages,
                                  nbytes=nbytes,
                                  tokens_resumed=tokens_resumed,
                                  tokens_saved=tokens_saved)

    def kv_restore_failed(self, trace_id: str, reason: str = "") -> float:
        """One restore degraded to the r9 recompute feed (checksum
        corruption or swap-in retry exhaustion).  Exact-class regression
        counter — any increase on a clean-path workload fails
        bench_compare."""
        self.metrics.counter("kv_restore_failures").inc()
        return self.trace.instant("kv_restore_failed", "tier", "tier",
                                  trace_id=trace_id, reason=reason)

    def spec_batch_mix(self, spec_requests: int, plain_requests: int) -> None:
        """One mixed verify macro-step's request composition: how many
        rows shipped a draft tree (multi-token verify) vs a root-only
        tree (single-token decode).  The mixed-batch composition gauge —
        the observable that a heterogeneous mix really shares one step."""
        m = self.metrics
        m.gauge("spec_batch_spec_requests").set(spec_requests)
        m.gauge("spec_batch_plain_requests").set(plain_requests)
        total = spec_requests + plain_requests
        frac = spec_requests / total if total else 0.0
        m.gauge("spec_batch_spec_frac").set(frac)
        m.counter("spec_verify_rounds").inc()
        self.trace.counter("spec_batch_spec_frac", frac)

    def spec_acceptance(self, accepted: int, drafted: int) -> float:
        """One speculative verify round's accept result for a request:
        ``accepted`` of ``drafted`` tree tokens survived the walk.  Feeds
        the acceptance-rate histogram the workload profile tracks (spec
        pricing is acceptance-sensitive) and the cumulative counters.
        Returns the acceptance fraction."""
        frac = accepted / drafted if drafted > 0 else 0.0
        m = self.metrics
        m.counter("spec_tokens_drafted").inc(drafted)
        m.counter("spec_tokens_accepted").inc(accepted)
        m.histogram("spec_acceptance_frac").observe(frac)
        self.workload.observe_spec_acceptance(frac)
        return frac

    # ---- predicted-vs-measured ----------------------------------------
    def record_plan_prediction(self, plan_key: str, **fields) -> None:
        self.calibration.predict(plan_key, **fields)

    def record_plan_measured(self, plan_key: str, **fields) -> None:
        self.calibration.measure(plan_key, **fields)

    # ---- memory observability (obs/memory.py) -------------------------
    def kv_usage(self, snap: Dict) -> None:
        """One KVAllocator occupancy observation (see
        :meth:`~flexflow_tpu.serve.kv_allocator.KVAllocator.observe` for
        the snapshot fields): publishes the live-side gauge vocabulary
        (``MEMORY_GAUGES``), the occupancy histogram/counter series, and
        folds the watermark into the memory ledger."""
        m = self.metrics
        occ = snap.get("occupancy_frac", 0.0)
        for gauge, key in MEMORY_GAUGE_KEYS.items():
            m.gauge(gauge).set(snap.get(key, 0.0))
        if "pages_live" in snap:  # paged allocator: page-pool vocabulary
            from .memory import PAGED_GAUGE_KEYS

            for gauge, key in PAGED_GAUGE_KEYS.items():
                m.gauge(gauge).set(snap.get(key, 0.0))
        if "host_pages" in snap:  # host tier attached: occupancy view
            from .memory import HOST_TIER_GAUGE_KEYS

            for gauge, key in HOST_TIER_GAUGE_KEYS.items():
                m.gauge(gauge).set(snap.get(key, 0.0))
        m.histogram(KV_OCCUPANCY_HIST).observe(occ)
        self.trace.counter("kv_occupancy_frac", occ)
        self.memory.observe_live(snap.get("live_bytes", 0.0),
                                 snap.get("capacity_bytes", 0.0),
                                 snap.get("live_tokens", 0))

    def memory_plan_predicted(self, plan_key: str, **fields) -> None:
        """``plan_memory_parts``' per-component prediction (GB fields)."""
        self.memory.predict(plan_key, **fields)

    def memory_plan_allocated(self, plan_key: str, **fields) -> None:
        """The deployment's REAL allocation, same components/units."""
        self.memory.allocated(plan_key, **fields)

    # ---- snapshot / export --------------------------------------------
    def snapshot(self) -> Dict:
        """One JSON-ready dict of everything the handle accumulated."""
        snap = {
            "metrics": self.metrics.snapshot(),
            "calibration": self.calibration.report(),
            "memory": self.memory.report(),
            "workload": self.workload.features(),
            "trace": {"events": self.trace.emitted,
                      "dropped": self.trace.dropped},
        }
        if self.profiler is not None:
            snap["profile"] = self.profiler.report()
        return snap

    def export(self, out_dir: str, prefix: str = "telemetry") -> Dict[str, str]:
        """Write ``<prefix>.trace.json`` (Chrome/Perfetto) and
        ``<prefix>.jsonl`` under ``out_dir``; returns both paths.

        The JSONL is the machine-readable artifact ``scripts/trace_report.py``
        consumes: a meta line, one ``{"kind": "event", ...}`` line per trace
        event (trace_event fields, ts/dur in microseconds), then a metrics
        snapshot line and a calibration report line.
        """
        os.makedirs(out_dir, exist_ok=True)
        trace_path = os.path.join(out_dir, f"{prefix}.trace.json")
        jsonl_path = os.path.join(out_dir, f"{prefix}.jsonl")
        self.trace.export_json(trace_path)
        with open(jsonl_path, "w") as f:
            f.write(json.dumps({
                "kind": "telemetry_meta", "version": 1, "ts_unit": "us",
                "events": self.trace.emitted, "dropped": self.trace.dropped,
            }) + "\n")
            for ev in self.trace.trace_events():
                f.write(json.dumps({"kind": "event", **ev}) + "\n")
            f.write(json.dumps({"kind": "metrics",
                                "snapshot": self.metrics.snapshot()}) + "\n")
            f.write(json.dumps({"kind": "calibration",
                                "report": self.calibration.report()}) + "\n")
            f.write(json.dumps({"kind": "memory",
                                "report": self.memory.report()}) + "\n")
            f.write(json.dumps({"kind": "workload",
                                "snapshot": self.workload.snapshot()}) + "\n")
            if self.profiler is not None:
                f.write(json.dumps({"kind": "profile",
                                    "report": self.profiler.report()})
                        + "\n")
            if self.store is not None:
                f.write(json.dumps({"kind": "calibration_store",
                                    "path": self.store.path,
                                    "components": self.store.as_dict()
                                    ["components"],
                                    "applied_scales": self.store.scales()})
                        + "\n")
        return {"trace_json": trace_path, "jsonl": jsonl_path}


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class NullTelemetry:
    """No-op stand-in: every hook returns a constant; ``enabled`` is False
    so instrumented code can skip argument computation entirely."""

    enabled = False

    def now(self):
        return 0.0

    def span(self, *a, **k):
        return _NULL_SPAN

    def instant(self, *a, **k):
        return 0.0

    def counter(self, *a, **k):
        return None

    def request_enqueued(self, *a, **k):
        return 0.0

    def request_admitted(self, *a, **k):
        return 0.0

    def request_prefill_started(self, *a, **k):
        return 0.0

    def request_first_token(self, *a, **k):
        return 0.0

    def request_finished(self, *a, **k):
        return 0.0

    def request_rejected(self, *a, **k):
        return 0.0

    def request_cancelled(self, *a, **k):
        return 0.0

    def request_timed_out(self, *a, **k):
        return 0.0

    def request_preempted(self, *a, **k):
        return 0.0

    def request_failed(self, *a, **k):
        return 0.0

    def prefix_cache_hit(self, *a, **k):
        return 0.0

    def prefix_cache_miss(self, *a, **k):
        return 0.0

    def dispatch_retry(self, *a, **k):
        return 0.0

    def fault_observed(self, *a, **k):
        return 0.0

    def batch_composition(self, *a, **k):
        return None

    def spec_mode_changed(self, *a, **k):
        return 0.0

    def migration_started(self, *a, **k):
        return 0.0

    def migration_completed(self, *a, **k):
        return 0.0

    def migration_rolled_back(self, *a, **k):
        return 0.0

    def replica_up(self, *a, **k):
        return 0.0

    def replica_degraded(self, *a, **k):
        return 0.0

    def replica_quarantined(self, *a, **k):
        return 0.0

    def replica_dead(self, *a, **k):
        return 0.0

    def request_failed_over(self, *a, **k):
        return 0.0

    def fleet_health(self, *a, **k):
        return None

    def brownout_level_changed(self, *a, **k):
        return 0.0

    def lane_shed(self, *a, **k):
        return 0.0

    def lane_deferred(self, *a, **k):
        return None

    def lane_degraded(self, *a, **k):
        return None

    def lane_depths(self, *a, **k):
        return None

    def trace_recorded(self, *a, **k):
        return 0.0

    def replay_started(self, *a, **k):
        return 0.0

    def replay_completed(self, *a, **k):
        return 0.0

    def replay_mismatch(self, *a, **k):
        return 0.0

    def kv_spilled(self, *a, **k):
        return 0.0

    def kv_restored(self, *a, **k):
        return 0.0

    def kv_restore_failed(self, *a, **k):
        return 0.0

    def spec_batch_mix(self, *a, **k):
        return None

    def spec_acceptance(self, *a, **k):
        return 0.0

    def record_plan_prediction(self, *a, **k):
        return None

    def record_plan_measured(self, *a, **k):
        return None

    def kv_usage(self, *a, **k):
        return None

    def memory_plan_predicted(self, *a, **k):
        return None

    def memory_plan_allocated(self, *a, **k):
        return None

    def snapshot(self):
        return {}

    def export(self, *a, **k):
        return {}


NULL_TELEMETRY = NullTelemetry()


def telemetry_or_null(telemetry) -> "Telemetry":
    """Normalize an optional handle: None -> the shared no-op singleton."""
    return telemetry if telemetry is not None else NULL_TELEMETRY
