"""Low-overhead serving trace recorder with Chrome/Perfetto export.

The serving stack's latency story spans a host-side orchestration loop
(RequestManager), async jit dispatches (InferenceManager), and per-stage
pipeline hops (PipelinedInferenceManager) — none of which an XLA/XProf trace
attributes to *requests*.  This recorder captures that host-side story as
typed spans/instants/counters on named tracks, exportable as
``chrome://tracing`` / Perfetto ``trace_event`` JSON (one track per pipeline
stage, so a pp run shows the stage interleave visually) and as JSONL for
``scripts/trace_report.py``.

Overhead contract (the reason this exists as its own layer instead of
piggybacking on ``jax.profiler``):

* **host-side only** — events are Python dicts appended to a ring buffer;
  nothing is ever passed into (or read back from) a jitted program, so
  recording cannot perturb compiled executables or their outputs.  Serve
  results are bit-identical with tracing on or off (pinned by
  tests/test_obs.py).
* **bounded memory** — a ``deque(maxlen=capacity)`` ring: long serving runs
  drop the *oldest* events rather than growing; ``dropped`` counts what fell
  off the ring.
* **hermetically testable** — the clock is injectable (any 0-arg seconds
  callable, default ``time.perf_counter``), so virtual-clock tests pin exact
  timestamps, span nesting, and wraparound behavior.

Timestamps are kept in SECONDS internally (matching the injectable clock)
and scaled to the trace_event format's microseconds at export.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Callable, Dict, List, Optional


class _Span:
    """Context manager recording one complete ("X" phase) event.

    The event is emitted at ``__exit__`` with the entry timestamp, so buffer
    order is completion order; Perfetto sorts by ``ts`` and infers nesting
    from containment on a track, which entry/exit pairing here guarantees
    for same-track spans.
    """

    __slots__ = ("_rec", "_name", "_cat", "_track", "_args", "_t0")

    def __init__(self, rec, name, cat, track, args):
        self._rec = rec
        self._name = name
        self._cat = cat
        self._track = track
        self._args = args

    def __enter__(self):
        self._t0 = self._rec._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        rec = self._rec
        rec._emit("X", self._name, self._cat, self._track, self._t0,
                  rec._clock() - self._t0, self._args)
        return False


class TraceRecorder:
    """Ring-buffered trace-event recorder (see module docstring)."""

    def __init__(self, capacity: int = 65536,
                 clock: Optional[Callable[[], float]] = None, pid: int = 1):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._clock = clock or time.perf_counter
        self._events: deque = deque(maxlen=capacity)
        self._tracks: Dict[str, int] = {}
        self.capacity = capacity
        self.pid = pid
        self.emitted = 0  # lifetime count, incl. events the ring dropped

    # ------------------------------------------------------------------
    def now(self) -> float:
        return self._clock()

    @property
    def dropped(self) -> int:
        return self.emitted - len(self._events)

    def _tid(self, track: str) -> int:
        tid = self._tracks.get(track)
        if tid is None:
            tid = len(self._tracks) + 1
            self._tracks[track] = tid
        return tid

    def _emit(self, ph, name, cat, track, ts, dur, args):
        ev = {"ph": ph, "name": name, "cat": cat, "tid": self._tid(track),
              "ts": ts}
        if dur is not None:
            ev["dur"] = dur
        if args:
            ev["args"] = args
        self._events.append(ev)
        self.emitted += 1

    # ------------------------------------------------------------------
    def span(self, name: str, cat: str = "serve", track: str = "serve",
             **args) -> _Span:
        """``with rec.span("decode_stretch", steps=8): ...`` — a complete
        event covering the body's wall time on ``track``."""
        return _Span(self, name, cat, track, args)

    def instant(self, name: str, cat: str = "serve", track: str = "serve",
                **args) -> float:
        """Zero-duration event; returns its timestamp (callers reuse it for
        derived duration bookkeeping without a second clock read)."""
        ts = self._clock()
        self._emit("i", name, cat, track, ts, None, args)
        return ts

    def counter(self, name: str, value: float,
                track: str = "counters") -> None:
        """Counter-series sample ("C" phase) — Perfetto renders these as a
        stepped line chart (batch occupancy, KV utilization, ...)."""
        self._emit("C", name, "metric", track, self._clock(), None,
                   {"value": float(value)})

    # ------------------------------------------------------------------
    def trace_events(self) -> List[Dict]:
        """Events in ``trace_event`` JSON form (ts/dur in microseconds),
        prefixed with thread_name metadata naming each track."""
        out = []
        for track, tid in self._tracks.items():
            out.append({"ph": "M", "name": "thread_name", "pid": self.pid,
                        "tid": tid, "args": {"name": track}})
        for ev in self._events:
            e = {"name": ev["name"], "cat": ev["cat"], "ph": ev["ph"],
                 "pid": self.pid, "tid": ev["tid"],
                 "ts": round(ev["ts"] * 1e6, 3)}
            if "dur" in ev:
                e["dur"] = round(ev["dur"] * 1e6, 3)
            if ev["ph"] == "i":
                e["s"] = "t"  # thread-scoped instant
            if "args" in ev:
                e["args"] = ev["args"]
            out.append(e)
        return out

    def to_chrome_json(self) -> Dict:
        """The ``chrome://tracing`` / Perfetto-loadable document.

        ``metadata`` carries the ring accounting (lifetime ``emitted`` vs
        ``dropped``): a trace whose oldest events fell off the ring must
        not masquerade as a complete record — viewers ignore the extra
        top-level key, ``scripts/trace_report.py`` warns on it.
        """
        return {
            "traceEvents": self.trace_events(),
            "displayTimeUnit": "ms",
            "metadata": {"trace_events_emitted": self.emitted,
                         "trace_events_dropped": self.dropped,
                         "ring_capacity": self.capacity},
        }

    def export_json(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_json(), f)
        return path

    def clear(self) -> None:
        self._events.clear()
        # emitted/dropped keep counting across clears (lifetime telemetry)
