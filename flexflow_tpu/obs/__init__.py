"""FlexFlow-TPU observability: serving telemetry, metrics, calibration.

The serving stack (RequestManager / InferenceManager /
PipelinedInferenceManager / serve_with_arrivals) is instrumented behind one
:class:`Telemetry` handle — a trace recorder (Chrome/Perfetto export), a
metrics registry, and a predicted-vs-measured calibration ledger.  Host-side
only by construction: telemetry never enters a jitted program, so serve
outputs are bit-identical with it on or off.  See README "Observability".
"""

from .calibration import (
    DEFAULT_STORE_PATH,
    CalibrationLedger,
    CalibrationStore,
    StoreConfig,
)
from .drift import (
    DriftDetector,
    WorkloadProfile,
    drift_score,
    psi,
)
from .memory import (
    KV_OCCUPANCY_HIST,
    MEMORY_GAUGES,
    MemoryLedger,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    percentile,
)
from .plan_health import PlanHealthConfig, PlanHealthMonitor, health_score
from .profiler import (
    COMPONENTS,
    NULL_PROFILER,
    TIME_COMPONENT_FIELDS,
    WORK_COUNTERS,
    NullStepProfiler,
    PlanCostCard,
    StepProfiler,
    plan_cost_card,
    profiler_or_null,
)
from .replay import (
    TRACE_VERSION,
    ReplayHarness,
    TrafficTrace,
    TrafficTraceRecorder,
    VirtualClock,
)
from .report import (
    memory_section,
    summarize_events,
    summarize_jsonl,
    time_budget_section,
    under_load_summary,
    validate_jsonl,
)
from .telemetry import (
    NULL_TELEMETRY,
    NullTelemetry,
    Telemetry,
    telemetry_or_null,
)
from .trace import TraceRecorder

__all__ = [
    "Telemetry",
    "NullTelemetry",
    "NULL_TELEMETRY",
    "telemetry_or_null",
    "TraceRecorder",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "percentile",
    "CalibrationLedger",
    "CalibrationStore",
    "StoreConfig",
    "DEFAULT_STORE_PATH",
    "WorkloadProfile",
    "DriftDetector",
    "drift_score",
    "psi",
    "PlanHealthConfig",
    "PlanHealthMonitor",
    "MemoryLedger",
    "MEMORY_GAUGES",
    "KV_OCCUPANCY_HIST",
    "memory_section",
    "summarize_events",
    "summarize_jsonl",
    "time_budget_section",
    "under_load_summary",
    "validate_jsonl",
    "StepProfiler",
    "NullStepProfiler",
    "NULL_PROFILER",
    "profiler_or_null",
    "PlanCostCard",
    "plan_cost_card",
    "COMPONENTS",
    "TIME_COMPONENT_FIELDS",
    "WORK_COUNTERS",
    "TrafficTraceRecorder",
    "TrafficTrace",
    "ReplayHarness",
    "VirtualClock",
    "TRACE_VERSION",
]
