"""Memory ledger: predicted vs allocated vs live HBM for executed plans.

The byte-side analog of :class:`~flexflow_tpu.obs.calibration.
CalibrationLedger` (which reconciles the TIME side — TPOT/TTFT).  Three
views per plan, reconciled per component:

* **predicted** — what ``plan_memory_bytes`` priced at search time
  (``plan_memory_parts`` decomposes it into ``weights_gb`` / ``kv_gb`` /
  ``transient_gb`` / ``total_gb``);
* **allocated** — what the deployment actually holds: real parameter
  array bytes (int8 weights + scales included) and the
  :class:`~flexflow_tpu.serve.kv_allocator.KVAllocator`'s buffer bytes
  (int8 KV scales and lane padding included);
* **live** — occupied KV positions × bytes/token, tracked as gauges +
  watermarks by the allocator's :meth:`~flexflow_tpu.serve.kv_allocator.
  KVAllocator.observe` through :meth:`Telemetry.kv_usage`.

The predicted-vs-allocated per-component error feeds ``MachineModel``
memory-constant calibration exactly the way time constants already do —
the ledger IS a :class:`CalibrationLedger` whose "measured" side is the
allocation, so ``report()`` emits the same ``suggested_scale`` geometry
and a :class:`~flexflow_tpu.obs.calibration.CalibrationStore` can absorb
``kv_gb``/``weights_gb`` components unchanged.

Host-side bookkeeping only — attaching the ledger can never change serve
outputs (tests/test_kv_allocator.py pins bit-identity with the memory
layer on vs off).
"""

from __future__ import annotations

from typing import Dict, Optional

from .calibration import CalibrationLedger

# the live-side gauge vocabulary: gauge name -> the KVAllocator snapshot
# key it publishes.  Telemetry.kv_usage EMITS by iterating this mapping
# and report.memory_section READS by iterating its keys — one table, so a
# renamed gauge can neither drift from its source field nor silently drop
# from the report
MEMORY_GAUGE_KEYS = {
    "kv_occupancy_frac": "occupancy_frac",
    "kv_headroom_bytes": "headroom_bytes",
    "kv_live_bytes": "live_bytes",
    "kv_live_bytes_hwm": "hwm_bytes",
    "kv_fragmentation_frac": "fragmentation_frac",
}
MEMORY_GAUGES = tuple(MEMORY_GAUGE_KEYS)

# the paged-allocator extension (serve/kv_paged.py): page-pool occupancy
# and sharing/refcount gauges, published by Telemetry.kv_usage only when
# the snapshot carries the page vocabulary (a slot-contiguous allocator
# never emits zeros for pools it doesn't have).  Same one-table contract
# as MEMORY_GAUGE_KEYS: kv_usage EMITS by iterating it, the report READS
# its keys.
PAGED_GAUGE_KEYS = {
    "kv_pages_live": "pages_live",
    "kv_pages_shared": "pages_shared",
    "kv_pages_free": "pages_free",
    "kv_pages_indexed": "pages_indexed",
    "kv_page_cow_copies": "cow_copies",
    "kv_pages_evicted": "pages_evicted",
}
PAGED_GAUGES = tuple(PAGED_GAUGE_KEYS)

# the host-tier extension (serve/kv_paged.py HostPageTier): host-DRAM
# occupancy gauges, published by Telemetry.kv_usage only when the
# snapshot carries the host vocabulary (an allocator with no tier
# attached never emits zeros for a pool it doesn't have).  Same
# one-table contract as MEMORY_GAUGE_KEYS.
HOST_TIER_GAUGE_KEYS = {
    "kv_host_pages": "host_pages",
    "kv_host_bytes": "host_bytes",
    "kv_host_capacity_bytes": "host_capacity_bytes",
    "kv_host_spilled_requests": "host_spilled_requests",
    "kv_host_evictions": "host_evictions",
}
HOST_TIER_GAUGES = tuple(HOST_TIER_GAUGE_KEYS)

# the occupancy distribution (p50/p95 in the report) rides a histogram
# under this registry name
KV_OCCUPANCY_HIST = "kv_occupancy"


def publish_predicted_parts(telemetry, key: str, parts: Dict) -> None:
    """Record a composed ``plan_memory_parts`` dict (BYTES — see
    :func:`~flexflow_tpu.search.simulator.compose_stage_parts`) as the
    predicted side of the memory ledger.  One parts→GB-field mapping for
    EVERY emitter (``search_serve_plan`` and both managers'
    ``publish_memory``), so single-plan, pp, and search-side records can
    never drift in shape under the same plan key."""
    telemetry.memory_plan_predicted(
        key,
        weights_gb=parts["weights"] / 1e9,
        kv_gb=parts["kv_state"] / 1e9,
        transient_gb=parts["transient"] / 1e9,
        static_gb=parts["static"] / 1e9,
        total_gb=parts["total"] / 1e9,
    )


class MemoryLedger(CalibrationLedger):
    """Predicted-vs-allocated HBM accounting (+ live watermarks).

    Component convention: GB fields named ``weights_gb`` / ``kv_gb`` /
    ``transient_gb`` / ``total_gb`` (free-form like the time ledger's
    ``tpot_ms``...).  ``allocated`` is the byte-world name for the
    parent's ``measure`` — the ratio/``suggested_scale`` math is shared,
    so memory components calibrate through the same
    :class:`~flexflow_tpu.obs.calibration.CalibrationStore` path.
    """

    def __init__(self):
        super().__init__()
        self.hwm_bytes = 0.0          # live high-watermark across the run
        self.hwm_tokens = 0
        self.capacity_bytes: Optional[float] = None

    def allocated(self, plan_key: str, **fields) -> None:
        """Record the deployment's REAL allocation for ``plan_key`` (same
        units/fields as the prediction)."""
        self.measure(plan_key, **fields)

    def observe_live(self, live_bytes: float, capacity_bytes: float,
                     live_tokens: int = 0) -> None:
        """Fold one live-occupancy observation into the watermarks (the
        allocator calls this through ``Telemetry.kv_usage``)."""
        if live_bytes > self.hwm_bytes:
            self.hwm_bytes = float(live_bytes)
        if live_tokens > self.hwm_tokens:
            self.hwm_tokens = int(live_tokens)
        if capacity_bytes:
            self.capacity_bytes = float(capacity_bytes)

    def report(self) -> Dict:
        """The calibration-shaped plans/components tables plus the live
        watermark view (``hwm_frac`` is the stamp-ready device field the
        r6–r9 ``hbm_frac`` close-out fills from a real run)."""
        rep = super().report()
        rep["live"] = {
            "hwm_bytes": self.hwm_bytes,
            "hwm_tokens": self.hwm_tokens,
            "capacity_bytes": self.capacity_bytes,
            "hwm_frac": (round(self.hwm_bytes / self.capacity_bytes, 4)
                         if self.capacity_bytes else None),
        }
        return rep
