"""Serving metrics registry: counters, gauges, histograms.

One process-local registry per :class:`~flexflow_tpu.obs.telemetry.Telemetry`
handle, snapshotable to a plain dict — the shared accounting layer that
``bench.py``'s serving sections, ``RequestManager.serve_with_arrivals``, and
``scripts/trace_report.py`` consume instead of each keeping bespoke stat
code.  Pure host-side Python (no jax import): updating a metric can never
touch a jitted program.

Percentile convention matches the bench's historical reduction
(``sorted[min(int(q*n), n-1)]`` — nearest-rank, err-low), so numbers are
comparable across BENCH rounds that predate the registry.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Sequence


def percentile(sorted_xs: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of an already-sorted sequence (None if
    empty) — the one convention every consumer shares."""
    if not sorted_xs:
        return None
    return sorted_xs[min(int(q * len(sorted_xs)), len(sorted_xs) - 1)]


class Counter:
    """Monotonic count (requests admitted, tokens generated, hops...)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: float = 1) -> None:
        self.value += n

    def snapshot(self):
        return self.value


class Gauge:
    """Last-write-wins scalar (batch occupancy, KV utilization...)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def set(self, v: float) -> None:
        self.value = float(v)

    def snapshot(self):
        return self.value


class Histogram:
    """Distribution over a sliding sample window.

    Running count/sum/min/max cover the full lifetime; percentiles come
    from the newest ``window`` observations (a bounded deque, so unbounded
    serving runs cannot grow host memory — consistent with the trace ring).
    """

    __slots__ = ("count", "total", "vmin", "vmax", "_window")

    def __init__(self, window: int = 8192):
        self.count = 0
        self.total = 0.0
        self.vmin = None
        self.vmax = None
        self._window: deque = deque(maxlen=window)

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)
        self._window.append(v)

    def percentile(self, q: float) -> Optional[float]:
        return percentile(sorted(self._window), q)

    def tail(self, since_count: int) -> List[float]:
        """Observations that arrived AFTER lifetime count ``since_count``
        (clipped to the sliding window).  Lets a consumer that polls on
        its own cadence — e.g. the brownout controller's per-window SLO
        attainment (serve/slo.py) — evaluate only FRESH evidence: a
        single old breach must not pin a recovering signal forever."""
        fresh = self.count - max(int(since_count), 0)
        if fresh <= 0:
            return []
        fresh = min(fresh, len(self._window))
        return list(self._window)[len(self._window) - fresh:]

    def snapshot(self) -> Dict:
        xs = sorted(self._window)
        return {
            "count": self.count,
            "mean": (self.total / self.count) if self.count else None,
            "min": self.vmin,
            "max": self.vmax,
            "p50": percentile(xs, 0.50),
            "p95": percentile(xs, 0.95),
        }


class MetricsRegistry:
    """Name -> metric, get-or-create; a name keeps one type for its life."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls, *args):
        m = self._metrics.get(name)
        if m is None:
            m = cls(*args)
            self._metrics[name] = m
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} is a {type(m).__name__}, "
                f"requested as {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, window: int = 8192) -> Histogram:
        return self._get(name, Histogram, window)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def snapshot(self) -> Dict:
        """Plain-dict state: counters/gauges as scalars, histograms as
        their summary dicts — JSON-ready for bench lines and JSONL export."""
        return {name: m.snapshot()
                for name, m in sorted(self._metrics.items())}
