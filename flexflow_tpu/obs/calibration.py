"""Predicted-vs-measured cost accounting for executed serve plans.

FlexFlow's simulator (MLSys'19) and Unity's search (OSDI'22) are only as
good as their calibrated per-op measurements — our ``serve_search`` /
``simulator`` price plans they historically never checked against reality.
This ledger closes the loop: every executed plan records the search's
predicted TPOT/TTFT/memory next to the measured values, and
:meth:`report` turns the pairs into a per-component calibration table
(ratio + signed error per field, aggregated across plans) that says which
``MachineModel`` constant to tune and by how much.

Host-side bookkeeping only; keys are free-form plan names (the serve
search's ``tp{t}_pp{p}_m{m}`` convention by default).
"""

from __future__ import annotations

from typing import Dict


class CalibrationLedger:
    def __init__(self):
        # plan_key -> {"predicted": {field: value}, "measured": {...}}
        self._plans: Dict[str, Dict[str, Dict[str, float]]] = {}

    def _entry(self, plan_key: str) -> Dict:
        return self._plans.setdefault(
            str(plan_key), {"predicted": {}, "measured": {}})

    def predict(self, plan_key: str, **fields) -> None:
        """Record the search/simulator's predictions for a plan (e.g.
        ``predict("tp2_pp1_m1", tpot_ms=7.1, memory_gb=12.3)``)."""
        self._entry(plan_key)["predicted"].update(
            {k: float(v) for k, v in fields.items() if v is not None})

    def measure(self, plan_key: str, **fields) -> None:
        """Record measured values for the same fields, same units."""
        self._entry(plan_key)["measured"].update(
            {k: float(v) for k, v in fields.items() if v is not None})

    # ------------------------------------------------------------------
    def report(self) -> Dict:
        """Per-plan, per-field predicted vs measured, plus the cross-plan
        component aggregation::

            {"plans": {plan: {field: {"predicted", "measured", "ratio",
                                      "error_frac"}}},
             "components": {field: {"mean_ratio", "suggested_scale", "n"}}}

        ``ratio = measured/predicted`` — the factor to multiply the cost
        model's output by (``suggested_scale``) so it lands on reality;
        ``error_frac = (measured-predicted)/predicted`` is the signed
        relative error.  Fields recorded on only one side appear with the
        other side ``None`` and no ratio (coverage gaps stay visible
        instead of silently dropping).
        """
        plans: Dict[str, Dict] = {}
        comp: Dict[str, Dict] = {}
        for key, rec in self._plans.items():
            fields = {}
            for f in sorted(set(rec["predicted"]) | set(rec["measured"])):
                pred = rec["predicted"].get(f)
                meas = rec["measured"].get(f)
                entry = {"predicted": pred, "measured": meas,
                         "ratio": None, "error_frac": None}
                if pred is not None and meas is not None and pred != 0:
                    entry["ratio"] = round(meas / pred, 4)
                    entry["error_frac"] = round((meas - pred) / pred, 4)
                    c = comp.setdefault(f, {"sum_ratio": 0.0, "n": 0})
                    c["sum_ratio"] += meas / pred
                    c["n"] += 1
                fields[f] = entry
            plans[key] = fields
        components = {
            f: {"mean_ratio": round(c["sum_ratio"] / c["n"], 4),
                "suggested_scale": round(c["sum_ratio"] / c["n"], 4),
                "n": c["n"]}
            for f, c in sorted(comp.items())
        }
        return {"plans": plans, "components": components}

    def __bool__(self) -> bool:
        return bool(self._plans)
