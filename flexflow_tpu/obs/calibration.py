"""Predicted-vs-measured cost accounting for executed serve plans.

FlexFlow's simulator (MLSys'19) and Unity's search (OSDI'22) are only as
good as their calibrated per-op measurements — our ``serve_search`` /
``simulator`` price plans they historically never checked against reality.
This ledger closes the loop: every executed plan records the search's
predicted TPOT/TTFT/memory next to the measured values, and
:meth:`report` turns the pairs into a per-component calibration table
(ratio + signed error per field, aggregated across plans) that says which
``MachineModel`` constant to tune and by how much.

The :class:`CalibrationStore` closes it CONTINUOUSLY: a persisted JSON
artifact (default ``artifacts/calibration_store.json``) the ledger commits
its per-component ``suggested_scale`` into after each measured run —
EWMA-smoothed across runs, clamped to a sane range, and gated behind a
minimum sample count — which ``MachineModel.with_store`` and
``search_serve_plan(calibration=...)`` consult automatically on the next
search.  The r8 flow printed ``suggested_scale`` and forgot it; this is
the artifact that remembers.

Host-side bookkeeping only; keys are free-form plan names (the serve
search's ``tp{t}_pp{p}_m{m}`` convention by default).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Dict, Optional

# repo-level default artifact: deliberate persistence only — nothing writes
# here unless an operator (or bench) calls CalibrationStore.save() on it
DEFAULT_STORE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "artifacts", "calibration_store.json",
)


def default_store_path() -> Optional[str]:
    """The store path ``search_serve_plan(calibration="auto")`` consults.

    ``FLEXFLOW_TPU_CALIBRATION_STORE`` overrides the repo artifact — a
    path redirects, the empty string DISABLES auto-consult entirely (the
    hermetic-test setting: tests/conftest.py sets it so a store an
    operator persisted can never silently steer test searches)."""
    env = os.environ.get("FLEXFLOW_TPU_CALIBRATION_STORE")
    if env is not None:
        return env or None
    return DEFAULT_STORE_PATH


class CalibrationLedger:
    def __init__(self):
        # plan_key -> {"predicted": {field: value}, "measured": {...}}
        self._plans: Dict[str, Dict[str, Dict[str, float]]] = {}

    def _entry(self, plan_key: str) -> Dict:
        return self._plans.setdefault(
            str(plan_key), {"predicted": {}, "measured": {}})

    def predict(self, plan_key: str, **fields) -> None:
        """Record the search/simulator's predictions for a plan (e.g.
        ``predict("tp2_pp1_m1", tpot_ms=7.1, memory_gb=12.3)``)."""
        self._entry(plan_key)["predicted"].update(
            {k: float(v) for k, v in fields.items() if v is not None})

    def measure(self, plan_key: str, **fields) -> None:
        """Record measured values for the same fields, same units."""
        self._entry(plan_key)["measured"].update(
            {k: float(v) for k, v in fields.items() if v is not None})

    # ------------------------------------------------------------------
    def report(self) -> Dict:
        """Per-plan, per-field predicted vs measured, plus the cross-plan
        component aggregation::

            {"plans": {plan: {field: {"predicted", "measured", "ratio",
                                      "error_frac"}}},
             "components": {field: {"mean_ratio", "suggested_scale", "n",
                                    "low_confidence"}}}

        ``ratio = measured/predicted`` — the factor to multiply the cost
        model's output by (``suggested_scale``) so it lands on reality;
        ``error_frac = (measured-predicted)/predicted`` is the signed
        relative error.  ``suggested_scale`` is the GEOMETRIC mean of the
        per-plan ratios: ratios are multiplicative corrections, and the
        arithmetic mean over-weights overshoots (ratios 0.5 and 2.0 must
        suggest 1.0, not 1.25).  Non-positive ratios (a sign error in a
        recorded field) stay visible per plan but are excluded from the
        aggregate — log of a non-positive ratio is undefined.  An
        aggregate built from a single pair carries ``low_confidence``
        so downstream consumers (the :class:`CalibrationStore` gate,
        reports) don't over-trust one measurement.  Fields recorded on
        only one side appear with the other side ``None`` and no ratio
        (coverage gaps stay visible instead of silently dropping).
        """
        plans: Dict[str, Dict] = {}
        comp: Dict[str, Dict] = {}
        for key, rec in self._plans.items():
            fields = {}
            for f in sorted(set(rec["predicted"]) | set(rec["measured"])):
                pred = rec["predicted"].get(f)
                meas = rec["measured"].get(f)
                entry = {"predicted": pred, "measured": meas,
                         "ratio": None, "error_frac": None}
                if pred is not None and meas is not None and pred != 0:
                    ratio = meas / pred
                    entry["ratio"] = round(ratio, 4)
                    entry["error_frac"] = round((meas - pred) / pred, 4)
                    if ratio > 0:
                        c = comp.setdefault(f, {"sum_log": 0.0, "n": 0})
                        c["sum_log"] += math.log(ratio)
                        c["n"] += 1
                fields[f] = entry
            plans[key] = fields
        components = {
            f: {"mean_ratio": round(math.exp(c["sum_log"] / c["n"]), 4),
                "suggested_scale": round(math.exp(c["sum_log"] / c["n"]), 4),
                "n": c["n"],
                "low_confidence": c["n"] == 1}
            for f, c in sorted(comp.items())
        }
        return {"plans": plans, "components": components}

    def commit(self, store: "CalibrationStore") -> Dict:
        """Fold this ledger's component aggregation into a persisted store
        (the continuous-calibration write path); returns what changed."""
        return store.update(self.report())

    def __bool__(self) -> bool:
        return bool(self._plans)


# ---------------------------------------------------------------------------
# continuous calibration: the persisted, smoothed scale artifact
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class StoreConfig:
    """Smoothing/trust policy for the persisted calibration scales.

    * ``ewma_alpha`` — weight of the newest run's suggested scale; history
      keeps ``1 - alpha``.  A single wild run (thermal throttle, noisy
      neighbor on a shared chip) moves the applied scale only ``alpha`` of
      the way.
    * ``scale_min``/``scale_max`` — hard clamp on any suggestion before it
      is blended: a 10x outlier is a broken measurement, not a
      calibration, and must not poison the EWMA.
    * ``min_samples`` — cumulative predicted/measured pairs a component
      needs before :meth:`CalibrationStore.scale_for` applies it (below
      the gate the spec-sheet prediction stands).  With the ledger's
      ``low_confidence`` single-pair runs, the default of 2 means one run
      records but does not yet steer.
    """

    ewma_alpha: float = 0.3
    scale_min: float = 0.25
    scale_max: float = 4.0
    min_samples: int = 2


class CalibrationStore:
    """EWMA-smoothed per-component cost scales, persisted as JSON.

    The write path is ``CalibrationLedger.commit(store); store.save()``
    after a measured run; the read path is ``CalibrationStore.load(path)``
    inside ``search_serve_plan`` (field-level scales: ``tpot_ms``,
    ``transfer_ms``, ``memory_gb``, ...) and ``MachineModel.with_store``
    (constant-level scales: ``step_overhead``, ``mxu_efficiency``, ...).
    Missing or malformed files load as an EMPTY store — every scale is 1.0
    — so a corrupted artifact degrades to spec-sheet behavior, never an
    exception on the serving path.
    """

    def __init__(self, path: Optional[str] = None,
                 config: Optional[StoreConfig] = None):
        self.path = path or DEFAULT_STORE_PATH
        self.config = config or StoreConfig()
        self.runs = 0
        # component -> {"scale": ewma, "n": cumulative pairs,
        #               "last_suggested": newest clamped suggestion}
        self.components: Dict[str, Dict] = {}

    # ---- persistence ---------------------------------------------------
    @classmethod
    def load(cls, path: Optional[str] = None,
             config: Optional[StoreConfig] = None) -> "CalibrationStore":
        """Read a store from disk; missing/malformed/partial files yield an
        empty (all-scales-1.0) store at the same path.  The persisted
        policy (``StoreConfig``) travels WITH the artifact — a store
        written with a relaxed min-sample gate keeps it on reload — unless
        the caller overrides with an explicit ``config``."""
        store = cls(path, config)
        try:
            with open(store.path) as f:
                doc = json.load(f)
            if config is None and isinstance(doc.get("config"), dict):
                known = {f.name for f in dataclasses.fields(StoreConfig)}
                store.config = StoreConfig(**{
                    k: v for k, v in doc["config"].items() if k in known})
            store.runs = int(doc.get("runs", 0))
            comps = doc.get("components", {})
            if isinstance(comps, dict):
                for name, e in comps.items():
                    if not isinstance(e, dict) or "scale" not in e:
                        continue
                    store.components[str(name)] = {
                        "scale": float(e["scale"]),
                        "n": int(e.get("n", 0)),
                        "last_suggested": float(
                            e.get("last_suggested", e["scale"])),
                    }
        except (OSError, ValueError, TypeError):
            store.components = {}
            store.runs = 0
        return store

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.as_dict(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    def as_dict(self) -> Dict:
        return {"version": 1, "runs": self.runs,
                "config": dataclasses.asdict(self.config),
                "components": {k: dict(v)
                               for k, v in sorted(self.components.items())}}

    # ---- update / read -------------------------------------------------
    def _clamp(self, s: float) -> float:
        return min(max(s, self.config.scale_min), self.config.scale_max)

    def update(self, report: Dict) -> Dict:
        """Blend one ledger ``report()``'s components in (EWMA over runs;
        first observation seeds the average).  Returns the per-component
        ``{"scale", "n", "applied"}`` view after the blend — ``applied``
        is whether the min-sample gate passes now."""
        alpha = self.config.ewma_alpha
        for name, comp in report.get("components", {}).items():
            suggested = comp.get("suggested_scale")
            if suggested is None or suggested <= 0:
                continue
            suggested = self._clamp(float(suggested))
            entry = self.components.get(name)
            if entry is None:
                entry = self.components[name] = {"scale": suggested, "n": 0}
            else:
                entry["scale"] = ((1.0 - alpha) * entry["scale"]
                                  + alpha * suggested)
            entry["scale"] = round(self._clamp(entry["scale"]), 6)
            entry["n"] = entry.get("n", 0) + int(comp.get("n", 1))
            entry["last_suggested"] = round(suggested, 6)
        self.runs += 1
        return {name: {"scale": e["scale"], "n": e["n"],
                       "applied": e["n"] >= self.config.min_samples}
                for name, e in sorted(self.components.items())}

    def scale_for(self, component: str, default: float = 1.0) -> float:
        """The applied scale for one component: the smoothed EWMA when the
        cumulative sample count clears ``min_samples``, else ``default``
        (the prediction stands un-corrected until there is evidence)."""
        e = self.components.get(component)
        if e is None or e.get("n", 0) < self.config.min_samples:
            return default
        return float(e["scale"])

    def scales(self) -> Dict[str, float]:
        """All components that clear the min-sample gate, name -> scale."""
        return {name: float(e["scale"])
                for name, e in sorted(self.components.items())
                if e.get("n", 0) >= self.config.min_samples}

    def __bool__(self) -> bool:
        return bool(self.components)
