"""Step-level cost attribution: per-phase time budgets + deterministic
work counters for the serving stack.

The calibration loop (obs/calibration.py, r8/r10) reconciles predicted vs
measured at WHOLE-PLAN granularity — one ``tpot_ms`` scalar per plan key —
so when prediction error appears it cannot say whether attention, the LM
head, the ICI hop, or host overhead is mispriced.  This module is the
decomposed half:

* :class:`StepProfiler` — one handle threaded through the managers
  exactly like :class:`~flexflow_tpu.obs.telemetry.Telemetry`
  (``RequestManager(..., profiler=StepProfiler())``; the manager syncs it
  onto the InferenceManager / every pipeline stage).  It

  - **times each serve tick's phases** on the injectable clock:
    admission/slot-fill/arrival parsing (``host_admit``), host batch
    preparation (``host_prepare``), jit dispatch (``dispatch``;
    per-stage ``stage{i}`` under pp), the inter-stage activation hop
    (``hop``), and the sample readback (``readback``) — the host-side
    time-budget decomposition of a tick;
  - **accumulates deterministic work counters** per tick and per request
    (:data:`WORK_COUNTERS`): flops executed, HBM bytes read/written, KV
    bytes touched, dispatch count, jit-recompile count, host-device
    syncs, pages mapped / copy-on-written.  "Deterministic" means the
    numbers are computed from host bookkeeping (token counts, batch
    shapes, the compiled plan) via the SAME arithmetic the serve search
    already prices with (``simulator._step_flops`` / ``Linear.flops`` /
    ``_step_param_bytes`` / the KVAllocator's ``bytes_per_token``), so
    two runs of the same workload produce identical counters with no
    device attached — the basis of the ``scripts/bench_compare.py``
    perf-regression guardrail.

* :class:`PlanCostCard` — the per-deployment constants that accounting
  uses, derived once per compiled plan (per stage under pp) from the
  plan's own sharded cost arithmetic.

**Deterministic accounting model** (the contract tests/test_profiler.py
cross-checks against ``Linear.flops`` / ``plan_memory_parts``):

* ``flops`` — fed tokens × (attention + mlp per-token flops at the
  compiled batch shape) + logit rows × per-row LM-head flops;
* ``hbm_bytes_read`` — model passes × streamed weight bytes (each scan
  step / micro-batch pass re-reads the weights) + KV read bytes;
* ``hbm_bytes_written`` — fed tokens × KV bytes/token (the committed
  cache write);
* ``kv_bytes_touched`` — KV read + written bytes, where a token at cache
  depth ``d`` reads the ``d``-deep causally-live prefix (a decode
  stretch of ``n`` steps starting at depth ``s`` reads
  ``n*s + n*(n-1)/2`` positions per row);
* ``dispatches`` — host program launches (per stage per micro-batch
  under pp); per-request ``dispatches`` counts the model passes whose
  batch carried the request's tokens;
* ``recompiles_total`` — jit cache misses: the registered jitted
  callables' ``_cache_size()`` growth since registration (a silent
  steady-state recompile is the most likely invisible perf bug);
* ``host_syncs`` — device→host result materializations (multi-step
  decode must perform exactly ONE, the final readback — the r7 "never
  host-syncs" claim, now a pinned counter);
* ``pages_mapped`` / ``pages_cow`` — the paged allocator's cumulative
  page-table activity (serve/kv_paged.py).

**Host-side only, guaranteed.**  Nothing here is ever traced into a
jitted program and no hook reads a device value, so serve outputs are
bit-identical with the profiler on or off — pinned across
step/generate/arrivals/pp2/int8/paged/spec/migration by
tests/test_profiler.py, the same contract telemetry carries.

The per-component TIME vocabulary (:data:`COMPONENTS` →
``attention_ms``/``mlp_ms``/``lm_head_ms``/``kv_stream_ms``/``comms_ms``/
``hop_ms``/``host_overhead_ms``) is shared with the serve search's
decomposed pricing (``search.serve_search.pp_serve_cost`` returns the
same fields; ``search_serve_plan`` records them into the calibration
ledger and consults the store's component-level ``suggested_scale``
entries when re-pricing), so the CalibrationLedger reconciles
predicted-vs-executed PER COMPONENT and a mispriced hop corrects only
the hop.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# the per-component time vocabulary: calibration-ledger field names are
# f"{component}_ms" (TIME_COMPONENT_FIELDS).  pp_serve_cost EMITS this
# decomposition, search_serve_plan records/consults it, the profiler's
# report and trace_report's time-budget section render it — one tuple, so
# a renamed component cannot drift between the pricing and the report.
COMPONENTS = ("attention", "mlp", "lm_head", "kv_stream", "comms", "hop",
              "host_overhead")
TIME_COMPONENT_FIELDS = tuple(f"{c}_ms" for c in COMPONENTS)

# the deterministic work-counter vocabulary (see the accounting model in
# the module docstring).  report.py folds these into the under-load /
# time-budget sections and scripts/bench_compare.py treats every field
# with one of these names as an exact-by-default regression guard.
WORK_COUNTERS = (
    "flops", "hbm_bytes_read", "hbm_bytes_written", "kv_bytes_touched",
    "dispatches", "recompiles_total", "host_syncs",
    "pages_mapped", "pages_cow",
)

# per-request attribution subset (stamped into serve_with_arrivals
# records — satellite: bench_compare gets deterministic per-run fields
# even with no device attached)
REQUEST_WORK_COUNTERS = ("flops", "kv_bytes_touched", "dispatches")

@dataclasses.dataclass
class PlanCostCard:
    """Per-deployment accounting constants, derived from the compiled
    plan(s) with the serve search's own arithmetic:

    * ``attn_flops_per_token`` / ``mlp_flops_per_token`` — per-device
      flops per fed token at the compiled batch shape
      (``simulator._step_flops`` over the plan steps, divided by the
      graph's flat token-batch rows);
    * ``lm_head_flops_per_row`` — per LOGIT ROW (the gated-prefill unit;
      ``Linear.flops``'s ``cost_logit_rows`` discount is the same
      arithmetic);
    * ``weight_bytes`` — per-device weight bytes one model pass streams
      (summed across pp stages: a pass traverses every stage);
    * ``kv_bytes_per_token`` — the allocator's committed-KV price (int8
      scales + lane padding included — the admission gate's number);
      falls back to the plan's registered-state arithmetic before the
      caches are allocated.
    """

    attn_flops_per_token: float = 0.0
    mlp_flops_per_token: float = 0.0
    lm_head_flops_per_row: float = 0.0
    weight_bytes: float = 0.0
    kv_bytes_per_token: float = 0.0

    def flops_for(self, n_tokens: int, logit_rows: int) -> float:
        return (n_tokens * (self.attn_flops_per_token
                            + self.mlp_flops_per_token)
                + logit_rows * self.lm_head_flops_per_row)


def plan_cost_card(im) -> PlanCostCard:
    """Build a :class:`PlanCostCard` for an InferenceManager-like object
    (``im.plan`` or ``im.stage_plans``) — the ONE place the profiler's
    deterministic counters read their constants, and it reads them from
    the same ``_step_flops``/``_step_param_bytes`` the serve search
    prices with (a counter that disagreed with the search's arithmetic
    would make the reconciliation circular)."""
    from ..search.simulator import (
        HEAVY_OPS,
        _step_flops,
        _step_param_bytes,
        serve_component_of,
    )

    plans = list(getattr(im, "stage_plans", None) or [im.plan])
    rows = int(getattr(im, "max_tokens", 0)) or 1
    attn_fl = mlp_fl = lm_fl = 0.0
    lm_rows = 0
    w_bytes = 0.0
    for plan in plans:
        mesh = plan.mesh
        for step in plan.steps:
            if step.is_parallel:
                continue
            op = step.node.op
            w_bytes += _step_param_bytes(step, plan, mesh)
            if op.type_name not in HEAVY_OPS:
                continue
            fl = _step_flops(step, mesh)
            # ONE classifier shared with pp_serve_cost's decomposition
            # (simulator.serve_component_of) — the counters and the
            # pricing may never disagree on an op's family
            fam = serve_component_of(op)
            if fam == "attention":
                attn_fl += fl
            elif fam == "lm_head":
                lm_fl += fl
                lm_rows = min(rows, int(op.cost_logit_rows)) or 1
            else:
                mlp_fl += fl
    kv_bpt = 0.0
    kv = getattr(im, "kv", None)
    if kv is not None:
        kv_bpt = kv.bytes_per_token() or 0.0
    if not kv_bpt:
        # caches unallocated: the plan's registered serve-state buffers
        # over the row x seq capacity (unpadded — the model-side price)
        from ..search.simulator import step_state_bytes

        state = sum(
            step_state_bytes(step, plan.mesh)
            for plan in plans for step in plan.steps if not step.is_parallel
        )
        cap = (getattr(im, "max_requests", 0)
               * getattr(im, "max_seq_len", 0)) or 1
        kv_bpt = state / cap
    return PlanCostCard(
        attn_flops_per_token=attn_fl / rows,
        mlp_flops_per_token=mlp_fl / rows,
        lm_head_flops_per_row=(lm_fl / lm_rows) if lm_rows else 0.0,
        weight_bytes=w_bytes,
        kv_bytes_per_token=kv_bpt,
    )


class _Phase:
    """Context manager accumulating one phase's wall time (entry/exit on
    the profiler's injectable clock — mirrors trace._Span)."""

    __slots__ = ("_prof", "_name", "_t0")

    def __init__(self, prof, name):
        self._prof = prof
        self._name = name

    def __enter__(self):
        self._t0 = self._prof._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._prof._phase_done(self._name, self._prof._clock() - self._t0)
        return False


class StepProfiler:
    """See the module docstring.  One instance per serving session;
    shared by the RequestManager and its InferenceManager(s) like the
    Telemetry handle (and carried across a live plan migration, so one
    rid space keeps one attribution table)."""

    enabled = True

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._clock = clock or time.perf_counter
        self.phase_s: Dict[str, float] = {}
        self.phase_counts: Dict[str, int] = {}
        self.work: Dict[str, float] = {k: 0 for k in WORK_COUNTERS}
        # rid -> {flops, kv_bytes_touched, dispatches}
        self.per_request: Dict[int, Dict[str, float]] = {}
        self.ticks = 0
        self.last_tick: Dict = {}
        self.telemetry = None   # bound via bind(); step_profile instants
        # jitted callables polled for cache growth, per deployment:
        # id(im) -> [(name, fn, base_size)] (keyed so uninstall() can
        # release a retired deployment's programs)
        self._jits: Dict[int, List[Tuple[str, object, int]]] = {}
        # compile counts already folded in from uninstalled deployments
        self._retired_compiles = 0
        self._installed: set = set()
        # paged allocators polled for cumulative page activity:
        # id(im) -> (kv, {counter: last_seen})
        self._paged: Dict[int, Tuple[object, Dict[str, int]]] = {}
        self._cards: Dict[int, PlanCostCard] = {}
        self._tick_mark: Optional[Dict] = None
        # scheduling annotations for the CURRENT tick (note()): merged
        # into the tick's step_profile instant and last_tick, then cleared
        self._tick_notes: Dict[str, float] = {}

    # ---- wiring -------------------------------------------------------
    def bind(self, telemetry) -> None:
        """Attach a Telemetry handle: the export grows a ``profile``
        JSONL line, each tick emits a ``step_profile`` instant, and the
        ``recompiles_total`` gauge lands in the metrics registry."""
        if telemetry is not None and getattr(telemetry, "enabled", False):
            self.telemetry = telemetry
            telemetry.profiler = self

    def install(self, im) -> None:
        """Register a deployment: its jitted step callables join the
        recompile poll and its paged allocator (if any) the page poll.
        Idempotent per ``im``; called by the RequestManager when the
        handle is synced (and again by a migration's successor)."""
        key = id(im)
        if key in self._installed:
            return
        self._installed.add(key)
        label = type(im).__name__
        jits = self._jits.setdefault(key, [])
        for name in ("_step", "_scan", "_pscan", "_advance", "_join"):
            fn = getattr(im, name, None)
            if fn is not None and hasattr(fn, "_cache_size"):
                jits.append((f"{label}{name}", fn, fn._cache_size()))
        for s, stage in enumerate(getattr(im, "stages", None) or []):
            fn = getattr(stage, "step", None)
            if fn is not None and hasattr(fn, "_cache_size"):
                jits.append((f"{label}.stage{s}", fn, fn._cache_size()))
        kv = getattr(im, "kv", None)
        if kv is not None and getattr(kv, "paged", False):
            # baseline NOW (registration), so page activity from the very
            # first tick counts — only pre-existing history is excluded
            self._paged[key] = (kv, {
                "pages_mapped": int(getattr(kv, "pages_mapped", 0)),
                "pages_cow": int(getattr(kv, "cow_copies", 0))})

    def uninstall(self, im) -> None:
        """Release a RETIRED deployment (live-migration incumbent
        teardown): its jitted callables leave the recompile poll — their
        compiles-so-far fold into a retained total, so the counter stays
        monotonic — and its cost card / page poll entries drop.  Without
        this, a long-migrating session would pin every retired manager's
        programs (and their buffers) alive through the poll list."""
        key = id(im)
        self._installed.discard(key)
        for _, fn, base in self._jits.pop(key, ()):  # noqa: B007
            self._retired_compiles += max(fn._cache_size() - base, 0)
        self._cards.pop(key, None)
        self._paged.pop(key, None)

    def card_for(self, im) -> PlanCostCard:
        """The deployment's accounting constants, built lazily once per
        ``im`` (the KV byte price needs allocated caches to include the
        real padding/scale planes)."""
        key = id(im)
        card = self._cards.get(key)
        if card is None:
            card = self._cards[key] = plan_cost_card(im)
        return card

    # ---- phase timing -------------------------------------------------
    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    def _phase_done(self, name: str, dt: float) -> None:
        self.phase_s[name] = self.phase_s.get(name, 0.0) + dt
        self.phase_counts[name] = self.phase_counts.get(name, 0) + 1

    # ---- deterministic counters ---------------------------------------
    def count(self, name: str, n: float = 1) -> None:
        self.work[name] = self.work.get(name, 0) + n

    def host_sync(self, n: int = 1) -> None:
        """One device→host result materialization (np.asarray of a
        dispatch's output)."""
        self.work["host_syncs"] += n

    def note(self, **kw) -> None:
        """Stamp scheduling decisions into the CURRENT tick's
        ``step_profile`` record (e.g. ``decode_quantum`` — the stretch
        length the scheduler chose — or ``stretch_segments`` /
        ``stretch_joins``).  Values must be JSON-scalar; keys are merged
        into the tick instant at ``tick_end`` and cleared per tick, so
        they never accumulate across ticks."""
        self._tick_notes.update(kw)

    def account(self, card: PlanCostCard,
                rows: Sequence[Tuple[int, int, int]],
                passes: int = 1,
                logit_rows: Optional[int] = None) -> None:
        """Fold one dispatch group's deterministic work in.

        ``rows``: ``[(rid, n_tokens_fed, kv_read_tokens)]`` — per-request
        host bookkeeping (see the module docstring's accounting model).
        ``passes``: model passes this group executes (a decode scan of n
        steps streams the weights n times and includes every row n
        times).  ``logit_rows``: logit rows materialized (gated prefill:
        the sample points; everything else: the fed tokens).
        """
        if not rows:
            return
        total = sum(n for _, n, _ in rows)
        if total <= 0:
            return
        lr = total if logit_rows is None else logit_rows
        flops = card.flops_for(total, lr)
        read_tokens = sum(r for _, _, r in rows)
        kv_w = total * card.kv_bytes_per_token
        kv_r = read_tokens * card.kv_bytes_per_token
        w = self.work
        w["flops"] += flops
        w["hbm_bytes_read"] += passes * card.weight_bytes + kv_r
        w["hbm_bytes_written"] += kv_w
        w["kv_bytes_touched"] += kv_r + kv_w
        per_tok = (card.attn_flops_per_token + card.mlp_flops_per_token
                   + (lr / total) * card.lm_head_flops_per_row)
        for rid, n, r in rows:
            rec = self.per_request.get(rid)
            if rec is None:
                rec = self.per_request[rid] = {
                    k: 0.0 for k in REQUEST_WORK_COUNTERS}
            rec["flops"] += n * per_tok
            rec["kv_bytes_touched"] += (n + r) * card.kv_bytes_per_token
            rec["dispatches"] += passes

    def request_work(self, rid: int) -> Dict[str, float]:
        """The per-request attribution (zeros for an unseen rid) —
        stamped into ``serve_with_arrivals`` records."""
        rec = self.per_request.get(rid)
        if rec is None:
            return {k: 0.0 for k in REQUEST_WORK_COUNTERS}
        return dict(rec)

    # ---- polled counters ----------------------------------------------
    def recompiles(self) -> int:
        """Jit cache misses since registration, summed over the
        registered callables (``_cache_size()`` growth — a compile per
        new (shapes, static args) signature), plus retired deployments'
        folded totals."""
        return self._retired_compiles + int(sum(
            max(fn._cache_size() - base, 0)
            for jits in self._jits.values() for _, fn, base in jits))

    def _poll(self) -> None:
        self.work["recompiles_total"] = self.recompiles()
        for kv, seen in self._paged.values():
            for name, attr in (("pages_mapped", "pages_mapped"),
                               ("pages_cow", "cow_copies")):
                cur = int(getattr(kv, attr, 0))
                if cur > seen[name]:
                    self.work[name] += cur - seen[name]
                seen[name] = cur

    # ---- tick boundaries ----------------------------------------------
    def tick_begin(self) -> None:
        self._tick_mark = {"work": dict(self.work),
                           "phase_s": dict(self.phase_s)}

    def tick_end(self) -> None:
        self._poll()
        self.ticks += 1
        mark = self._tick_mark or {"work": {}, "phase_s": {}}
        self._tick_mark = None
        dwork = {k: self.work[k] - mark["work"].get(k, 0)
                 for k in self.work if self.work[k] != mark["work"].get(k, 0)}
        dphase = {k: round((self.phase_s[k]
                            - mark["phase_s"].get(k, 0.0)) * 1e3, 6)
                  for k in self.phase_s
                  if self.phase_s[k] != mark["phase_s"].get(k, 0.0)}
        notes = self._tick_notes
        self._tick_notes = {}
        self.last_tick = {"tick": self.ticks, "work": dwork,
                          "phases_ms": dphase}
        if notes:
            self.last_tick["notes"] = notes
        tel = self.telemetry
        if tel is not None and tel.enabled:
            tel.instant("step_profile", cat="profile", track="profile",
                        tick=self.ticks, **notes, **dwork)
            tel.metrics.gauge("recompiles_total").set(
                self.work["recompiles_total"])

    # ---- report -------------------------------------------------------
    def report(self) -> Dict:
        """JSON-ready accumulation: the phase time budget, the work
        counters, and the per-request attribution summary (counts only —
        the full table rides ``serve_with_arrivals`` records)."""
        self._poll()
        total_ms = sum(self.phase_s.values()) * 1e3
        phases = {
            name: {"ms": round(self.phase_s[name] * 1e3, 6),
                   "count": self.phase_counts.get(name, 0),
                   "frac": (round(self.phase_s[name] * 1e3 / total_ms, 4)
                            if total_ms else None)}
            for name in sorted(self.phase_s)
        }
        return {
            "ticks": self.ticks,
            "phases": phases,
            "work": {k: self.work[k] for k in WORK_COUNTERS},
            "requests_attributed": len(self.per_request),
        }


class NullStepProfiler:
    """No-op stand-in (the shared default): every hook returns a
    constant; ``enabled`` is False so instrumented code skips argument
    construction entirely."""

    enabled = False

    def bind(self, *a, **k):
        return None

    def install(self, *a, **k):
        return None

    def uninstall(self, *a, **k):
        return None

    def card_for(self, *a, **k):
        return None

    def phase(self, *a, **k):
        return _NULL_PHASE

    def count(self, *a, **k):
        return None

    def host_sync(self, *a, **k):
        return None

    def note(self, *a, **k):
        return None

    def account(self, *a, **k):
        return None

    def request_work(self, *a, **k):
        return {}

    def recompiles(self):
        return 0

    def tick_begin(self):
        return None

    def tick_end(self):
        return None

    def report(self):
        return {}


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_PHASE = _NullPhase()

NULL_PROFILER = NullStepProfiler()


def profiler_or_null(profiler) -> "StepProfiler":
    """Normalize an optional handle: None -> the shared no-op singleton."""
    return profiler if profiler is not None else NULL_PROFILER
