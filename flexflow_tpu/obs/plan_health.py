"""Plan-health monitoring: is the executing serve plan still the right one?

Closes the last third of the observe->calibrate->re-plan loop (ISSUE 6 /
ROADMAP "online re-planning"): the search predicted a TPOT/TTFT for the
plan it picked, the operator has SLO targets, and the plan was priced for
one workload profile — this monitor watches all three and, when any
breaks, re-runs the serve search on the DRIFTED profile and emits a
``replan_recommended`` instant carrying the candidate plan.  With a
:class:`~flexflow_tpu.serve.kv_allocator.KVAllocator` attached it also
watches the BYTE side: projected KV growth from the live workload
profile vs the allocator's real headroom, breaching as
``memory_pressure`` (r12's memory-observability layer) — capacity is the
binding constraint for serving, so running out of HBM is a plan-health
failure exactly like missing an SLO.

**The monitor recommends; the MigrationController acts.**  Everything
here is host-side arithmetic over the metrics registry and the workload
profile — attaching a monitor cannot change serve outputs (bit-identity
pinned in tests/test_plan_health.py, including a pp2 virtual-mesh
config).  A :class:`~flexflow_tpu.serve.migration.MigrationController`
attached to the serving RequestManager consumes ``recommendation``
(which carries the full candidate plan dict) and executes the live plan
switch over the r9 preemption-and-recompute path — drain/rebuild/
readmit with rollback; see ``serve/migration.py``.  After a completed
switch the controller calls :meth:`PlanHealthMonitor.rebase` so the
monitor watches the NEW plan against a fresh reference window.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

from .drift import DriftDetector
from .telemetry import telemetry_or_null


@dataclasses.dataclass
class PlanHealthConfig:
    """Thresholds for the three health checks.

    * SLO targets (``slo_ttft_p95_s`` / ``slo_tpot_p95_s``): None disables
      that check — not every deployment has an explicit SLO.
    * ``max_tpot_error_frac``: tolerated |measured - predicted| / predicted
      on the plan's own TPOT prediction before the cost model is declared
      out of touch with reality (the calibration loop should be shrinking
      this; a breach means the search ranked candidates with a broken
      ruler).
    * ``drift_threshold`` / ``drift_min_samples``: forwarded to the
      :class:`~flexflow_tpu.obs.drift.DriftDetector` (PSI units: >0.25 is
      the classic "population has shifted" line).
    * ``min_requests``: finished requests before latency checks engage —
      percentile comparisons over a handful of requests are noise.
    * ``replan_cooldown_ticks``: checks that must pass after a
      ``replan_recommended`` emission before ANOTHER may fire — the flap
      guard.  The historical dedup is "once per distinct candidate",
      which an OSCILLATING candidate pair defeats (A, B, A, B … each
      differs from its predecessor, so every check emits); the cooldown
      suppresses both the instant and the ``recommendation`` update for
      that many checks, so a downstream MigrationController cannot be
      whipsawed between two plans.  0 keeps the historical behavior.
    * ``memory_pressure_frac``: the OOM-risk line — breach when the
      PROJECTED live KV (current occupied positions + every live request
      growing by the workload profile's mean output length) exceeds this
      fraction of the :class:`~flexflow_tpu.serve.kv_allocator.
      KVAllocator`'s byte capacity.  The projection deliberately errs
      high (each live request is priced at the FULL mean output, not the
      remainder) — for OOM risk, a false alarm costs a re-search, a miss
      costs the deployment.  1.0 = breach only when projected past
      capacity; lower it to leave admission headroom.
    """

    slo_ttft_p95_s: Optional[float] = None
    slo_tpot_p95_s: Optional[float] = None
    max_tpot_error_frac: float = 0.5
    drift_threshold: float = 0.25
    drift_min_samples: int = 16
    min_requests: int = 8
    replan_cooldown_ticks: int = 0
    memory_pressure_frac: float = 1.0


class PlanHealthMonitor:
    """Compare live latencies/traffic against the executing plan.

    ``plan``: the dict ``search_serve_plan`` returned for the incumbent
    (``plan_key`` + predicted ``tpot_ms``/``ttft_ms`` are read).
    ``reference``: the workload-profile snapshot the plan was searched for
    (default: the telemetry handle's CURRENT window — capture the monitor
    right after planning so "reference" really is the planned-for mix).
    ``search_fn``: 0-arg callable re-running the serve search on the LIVE
    profile, returning a plan dict — injected so hermetic tests (and
    deployments with custom search wiring) control it; None degrades to
    report-only health checks.
    ``kv_allocator``: the deployment's
    :class:`~flexflow_tpu.serve.kv_allocator.KVAllocator` — or a LIST of
    allocators for multi-deployment serving (the spec manager wires
    [target, draft] so projection and capacity cover both caches) —
    enables the OOM-risk check (projected KV growth vs real headroom).
    The RequestManager wires its manager's allocator in automatically
    when the monitor is attached without one; None skips the memory
    check.

    :meth:`check` returns the health report and, when any check fails AND
    the re-search returns a plan whose key differs from the incumbent,
    emits ``replan_recommended`` (once per distinct candidate while the
    condition persists — a monitor polled every few ticks must not spam
    the ring with identical recommendations).
    """

    def __init__(self, telemetry, plan: Dict, reference=None,
                 config: Optional[PlanHealthConfig] = None,
                 search_fn: Optional[Callable[[], Dict]] = None,
                 kv_allocator=None, slo=None, brownout=None):
        # None degrades to the no-op handle: checks still run (drift
        # against an empty window, latencies unavailable), nothing emits
        self.telemetry = telemetry_or_null(telemetry)
        self.plan = dict(plan)
        self.config = config or PlanHealthConfig()
        self._reset_reference(reference)
        self.search_fn = search_fn
        self.kv_allocator = kv_allocator
        # SLO-class lanes (serve/slo.py): with an attached SLOPolicy the
        # monitor ALSO checks each class's own p95s against the class's
        # targets — a breach on a NON-degradable (latency-critical)
        # class joins the replan reasons, while a degradable-class
        # breach escalates an attached BrownoutController FIRST
        # (degrading batch work is cheaper than a plan switch; only a
        # ladder already at its max level lets the breach recommend
        # replan).
        self.slo = slo
        self.brownout = brownout
        self.checks = 0
        self.recommendation: Optional[Dict] = None
        # the most recent check() report — the fleet router's least-load
        # dispatch reads it (via health_score) so an unhealthy replica's
        # routing weight degrades without re-running the checks per tick
        self.last_report: Optional[Dict] = None
        self._last_candidate_key: Optional[str] = None
        self._last_emit_check: Optional[int] = None
        self._mem_pressure_active = False

    def _reset_reference(self, reference) -> None:
        """(Re)build the drift detector against ``reference`` (None = the
        handle's CURRENT workload window) — shared by __init__ and
        :meth:`rebase` so their wiring cannot diverge."""
        if reference is None and self.telemetry.enabled:
            reference = self.telemetry.workload.snapshot()
        self.detector = DriftDetector(
            reference or {"dims": {}},
            threshold=self.config.drift_threshold,
            min_samples=self.config.drift_min_samples)

    def rebase(self, plan: Dict, reference=None, kv_allocator=None) -> None:
        """Re-point the monitor at a NEW executing plan (the
        MigrationController calls this after a completed switch): the
        candidate becomes the incumbent, the drift reference resets to
        the CURRENT workload window (the plan was searched on the live
        profile, so "planned-for" is exactly now), the stale
        recommendation/dedup/edge-trigger/cooldown state clears (a NEW
        plan's first recommendation must not be suppressed by the OLD
        plan's emission window), and — when the rebuild swapped
        allocators — the OOM-risk check re-wires to the new deployment's
        caches."""
        self.plan = dict(plan)
        self._reset_reference(reference)
        if kv_allocator is not None:
            self.kv_allocator = kv_allocator
        self.recommendation = None
        self._last_candidate_key = None
        self._last_emit_check = None
        self._mem_pressure_active = False

    # ------------------------------------------------------------------
    def _hist(self, name: str) -> Dict:
        snap = self.telemetry.metrics.histogram(name).snapshot() \
            if self.telemetry.enabled else {}
        return snap or {}

    def check(self) -> Dict:
        """One health evaluation: latency vs prediction, latency vs SLO,
        live workload vs reference.  Host-side only."""
        cfg = self.config
        tel = self.telemetry
        self.checks += 1
        plan_key = self.plan.get("plan_key", "?")
        report: Dict = {"plan": plan_key, "checks": self.checks,
                        "reasons": []}
        reasons = report["reasons"]

        ttft = self._hist("ttft_s")
        tpot = self._hist("tpot_s")
        enough = (tpot.get("count") or 0) >= cfg.min_requests

        # 1. predicted-vs-measured TPOT (the plan's own fidelity)
        pred_tpot_s = (self.plan.get("tpot_ms") or 0.0) / 1e3
        meas_tpot_s = tpot.get("p50")
        report["tpot_predicted_ms"] = round(pred_tpot_s * 1e3, 4)
        report["tpot_measured_p50_ms"] = (
            round(meas_tpot_s * 1e3, 4) if meas_tpot_s is not None else None)
        if enough and pred_tpot_s > 0 and meas_tpot_s is not None:
            err = (meas_tpot_s - pred_tpot_s) / pred_tpot_s
            report["tpot_error_frac"] = round(err, 4)
            if tel.enabled:
                tel.metrics.gauge("plan_tpot_error_frac").set(err)
            if abs(err) > cfg.max_tpot_error_frac:
                reasons.append("prediction_error")

        # 2. SLO targets on the live p95s
        if enough and cfg.slo_ttft_p95_s is not None \
                and ttft.get("p95") is not None \
                and ttft["p95"] > cfg.slo_ttft_p95_s:
            report["ttft_p95_s"] = round(ttft["p95"], 6)
            reasons.append("slo_ttft")
        if enough and cfg.slo_tpot_p95_s is not None \
                and tpot.get("p95") is not None \
                and tpot["p95"] > cfg.slo_tpot_p95_s:
            report["tpot_p95_s"] = round(tpot["p95"], 6)
            reasons.append("slo_tpot")

        # 2b. PER-CLASS SLO targets (serve/slo.py): each class's own
        # p95s vs the class's targets.  Routing is class-aware — a
        # latency-critical breach recommends replan; a degradable
        # (batch) breach escalates the brownout ladder first and only
        # recommends replan once the ladder is maxed out (degradation
        # has nothing left to give).
        if self.slo is not None:
            from ..serve.slo import MAX_LEVEL

            escalated = []
            for name, cls in sorted(self.slo.classes.items()):
                breaches = []
                for metric, target in (("ttft_s", cls.ttft_p95_s),
                                       ("tpot_s", cls.tpot_p95_s)):
                    if target is None:
                        continue
                    snap = self._hist(f"{metric}_cls_{name}")
                    if (snap.get("count") or 0) < cfg.min_requests:
                        continue
                    p95 = snap.get("p95")
                    if p95 is not None and p95 > target:
                        breaches.append(metric)
                        report[f"{metric}_cls_{name}_p95_s"] = round(p95, 6)
                if not breaches:
                    continue
                bo = self.brownout
                if (cls.degradable and bo is not None
                        and bo.level < MAX_LEVEL):
                    bo.note_slo_breach(name)
                    escalated.append(name)
                else:
                    for metric in breaches:
                        reasons.append(f"slo_class_{metric}:{name}")
            if escalated:
                report["brownout_escalated"] = escalated

        # 3. workload drift vs the planned-for reference
        drift = self.detector.check(
            tel.workload if tel.enabled else {"dims": {}},
            telemetry=tel)
        report["drift"] = drift
        if drift["drifted"]:
            reasons.append("workload_drift")

        # 4. OOM risk (the byte-side check): project the live KV forward
        # by the workload profile's mean output length per live request
        # and compare against the allocator's REAL byte capacity — the
        # one arithmetic admission and preemption already share.  Errs
        # high by design (full mean output per request, not the
        # remainder); a breach rides the same replan machinery as the
        # time-side checks, and the edge-triggered ``memory_pressure``
        # instant carries the projection so the report can show how close
        # the deployment came.
        kvs = self.kv_allocator
        kvs = (list(kvs) if isinstance(kvs, (list, tuple))
               else [kvs] if kvs is not None else [])
        per_toks = [kv.bytes_per_token() for kv in kvs]
        if kvs and all(per_toks):
            # one buffer walk per allocator per check; each deployment's
            # cache prices at its OWN bytes/token (target and draft
            # differ), composed by summing bytes
            cap_b = sum(kv.capacity_tokens * p
                        for kv, p in zip(kvs, per_toks))
            live_tok = sum(kv.live_tokens() for kv in kvs)
            live_b = sum(kv.live_tokens() * p
                         for kv, p in zip(kvs, per_toks))
            mean_out = (tel.workload.features().get("mean_output_len", 0.0)
                        if tel.enabled else 0.0)
            # every live request grows EVERY cache it holds by the
            # expected remaining output
            n_live = max((kv.live_requests() for kv in kvs), default=0)
            projected = live_b + sum(n_live * mean_out * p
                                     for p in per_toks)
            proj_frac = projected / cap_b if cap_b else 0.0
            report["memory"] = {
                "live_tokens": live_tok,
                "live_bytes": round(live_b, 1),
                "projected_bytes": round(projected, 1),
                "capacity_bytes": round(cap_b, 1),
                "projected_frac": round(proj_frac, 4),
            }
            # host-tier occupancy view (serve/kv_paged.py HostPageTier):
            # spilled pages waiting off-device are recoverable state the
            # projection above doesn't count (restores re-enter via the
            # page pool's own admission) — surfaced so the report shows
            # how much of the deployment's KV is parked in host DRAM
            tiers = [kv.host_tier for kv in kvs
                     if getattr(kv, "host_tier", None) is not None]
            if tiers:
                report["memory"]["host_tier"] = {
                    "bytes": sum(t.bytes_used for t in tiers),
                    "capacity_bytes": sum(t.capacity_bytes for t in tiers),
                    "pages": sum(t.pages_held() for t in tiers),
                    "spilled_requests": sum(len(t._spills) for t in tiers),
                    "evictions": sum(t.evictions for t in tiers),
                }
            if tel.enabled:
                tel.metrics.gauge("kv_projected_frac").set(proj_frac)
            if cap_b and proj_frac > cfg.memory_pressure_frac:
                reasons.append("memory_pressure")
                if tel.enabled and not self._mem_pressure_active:
                    tel.instant(
                        "memory_pressure", cat="plan", track="plan_health",
                        projected_bytes=round(projected, 1),
                        capacity_bytes=round(cap_b, 1),
                        live_tokens=live_tok,
                        headroom_bytes=round(cap_b - live_b, 1))
                    tel.metrics.counter("memory_pressure_events").inc()
                self._mem_pressure_active = True
            else:
                self._mem_pressure_active = False
        else:
            # a skipped memory check (caches freed/unallocated) must not
            # carry a stale edge-trigger into the next allocated epoch —
            # a fresh excursion there is a NEW event
            self._mem_pressure_active = False

        report["healthy"] = not reasons
        if tel.enabled:
            tel.metrics.gauge("plan_health_ok").set(0.0 if reasons else 1.0)

        # 5. unhealthy -> re-search on the live profile (recommendation
        # only; the candidate must actually differ to be worth emitting)
        if reasons and self.search_fn is not None:
            try:
                candidate = self.search_fn()
            except Exception as e:  # a failed re-search must not kill serving
                report["replan_error"] = f"{type(e).__name__}: {e}"[:120]
                candidate = None
            if candidate is not None:
                cand_key = candidate.get("plan_key", "?")
                report["candidate"] = {
                    "plan_key": cand_key,
                    "tpot_ms": candidate.get("tpot_ms"),
                    "ttft_ms": candidate.get("ttft_ms"),
                }
                if cand_key != plan_key:
                    # flap guard (``replan_cooldown_ticks``): a NEW
                    # candidate inside the cooldown window after the last
                    # emission is suppressed entirely — no instant, no
                    # ``recommendation`` update — so an oscillating
                    # candidate pair cannot whipsaw a downstream
                    # MigrationController (re-recommending the SAME
                    # candidate stays allowed: it refreshes the payload
                    # without emitting, the historical dedup)
                    cooling = (cfg.replan_cooldown_ticks > 0
                               and self._last_emit_check is not None
                               and self.checks - self._last_emit_check
                               < cfg.replan_cooldown_ticks
                               and cand_key != self._last_candidate_key)
                    if cooling:
                        report["replan_suppressed"] = True
                    else:
                        self.recommendation = {
                            "incumbent": plan_key, "candidate": cand_key,
                            "reasons": list(reasons),
                            "candidate_tpot_ms": candidate.get("tpot_ms"),
                            "drift_score": drift["score"],
                            # the full plan dict, so a MigrationController
                            # can rebuild without re-running the search
                            "candidate_plan": dict(candidate),
                        }
                        report["replan_recommended"] = True
                        if cand_key != self._last_candidate_key:
                            self._last_emit_check = self.checks
                            if tel.enabled:
                                tel.instant(
                                    "replan_recommended", cat="plan",
                                    track="plan_health",
                                    incumbent=plan_key, candidate=cand_key,
                                    reasons=",".join(reasons),
                                    candidate_tpot_ms=candidate.get(
                                        "tpot_ms"),
                                    drift_score=drift["score"])
                                tel.metrics.counter(
                                    "replans_recommended").inc()
                        self._last_candidate_key = cand_key
                else:
                    report["incumbent_reaffirmed"] = True
        if not reasons:
            # condition cleared: a future excursion may re-emit
            self._last_candidate_key = None
        self.last_report = report
        return report


def health_score(report: Optional[Dict]) -> float:
    """Routing penalty derived from a health report (None/healthy = 0.0;
    +1 per breached check reason).  The fleet router
    (``serve/fleet.py``) adds it to a replica's least-load score so a
    replica whose attached monitor reports SLO misses, drift, or memory
    pressure attracts fewer new dispatches — host-side arithmetic only,
    no effect without an attached monitor."""
    if not report:
        return 0.0
    return float(len(report.get("reasons", ()) or ()))
