"""Pipeline-parallel strategy search: stage partitioning + bubble cost.

Reference: the reference searches placements (MachineViews) jointly with
parallelization; pipeline stage assignment is part of its strategy space
(``src/runtime/graph.cc`` placement enumeration).  VERDICT r2 weak #6: the
GPipe executor (``parallel/pipeline.py``) existed outside the search — no
cost model could propose it.  This module closes that:

* :func:`chain_partition` — optimal contiguous partition of the op chain
  into K stages minimizing the max per-stage time (DP over prefix sums; the
  chain-partition problem is poly-time, so unlike the per-op sharding space
  no MCMC is needed).
* :func:`simulate_pipeline` — GPipe bubble model: per-microbatch stage time
  ``t = max_i(stage_i)``, schedule length ``(M + K - 1) * t``, plus the
  boundary activations shipped stage-to-stage over ICI each microbatch.
* :func:`propose_pipeline` — per-op times from the same simulator the MCMC
  uses (measured probes + roofline), per-boundary bytes from the graph's
  tensor specs, returns the stage map and simulated iteration time so
  callers can compare against the pure-GSPMD strategy's cost under the SAME
  cost model and pick the winner.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.graph import TensorSpec
from ..core.pcg import PCG
from .machine_model import MachineModel
from .simulator import _step_compute_time, _step_param_bytes


def _microbatch_step(step, n_micro: int):
    """The step with its activations' batch (leading) dim scaled to one
    microbatch — weight-bound ops keep their cost, batch-bound ops shrink;
    costing goes through the SAME roofline/probe path as full steps."""
    def scale(spec):
        if not spec.shape:
            return spec
        b = max(spec.shape[0] // n_micro, 1)
        return TensorSpec((b,) + tuple(spec.shape[1:]), spec.dtype)

    return dataclasses.replace(
        step,
        in_specs=[scale(s) for s in step.in_specs],
        out_specs=[scale(s) for s in step.out_specs],
    )


def chain_partition(costs: Sequence[float], k: int) -> List[int]:
    """Split ``costs`` into ``k`` contiguous groups minimizing the max group
    sum; returns the group index per element.  DP over prefix sums."""
    n = len(costs)
    k = min(k, n)
    prefix = np.concatenate([[0.0], np.cumsum(costs)])

    def span(i, j):  # cost of elements [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    best = np.full((n + 1, k + 1), INF)
    cut = np.zeros((n + 1, k + 1), np.int64)
    best[0, 0] = 0.0
    for j in range(1, k + 1):
        for end in range(1, n + 1):
            for start in range(j - 1, end):
                c = max(best[start, j - 1], span(start, end))
                if c < best[end, j]:
                    best[end, j] = c
                    cut[end, j] = start
    bounds = [n]
    for j in range(k, 0, -1):
        bounds.append(int(cut[bounds[-1], j]))
    bounds = bounds[::-1]  # [0, c1, ..., n]
    out = []
    for stage, (a, b) in enumerate(zip(bounds[:-1], bounds[1:])):
        out += [stage] * (b - a)
    return out


def simulate_pipeline(
    stage_costs: Sequence[float],
    boundary_bytes: Sequence[float],
    n_micro: int,
    mm: MachineModel,
    axes: Tuple[str, ...],
    mesh,
    training: bool = True,
) -> float:
    """GPipe iteration time for per-MICROBATCH stage costs.

    ``boundary_bytes``: activation bytes crossing each stage cut per
    microbatch (backward doubles it: gradients flow back).
    """
    k = len(stage_costs)
    t = max(stage_costs) if stage_costs else 0.0
    comm = sum(
        mm.collective_time(b * (2.0 if training else 1.0), axes, mesh)
        for b in boundary_bytes
    )
    return (n_micro + k - 1) * t + n_micro * comm


def propose_pipeline(
    graph,
    mesh,
    pp_axis: str,
    n_micro: int = 8,
    machine: Optional[MachineModel] = None,
    measured: Optional[Dict] = None,
    strategy: Optional[Dict] = None,
    training: bool = True,
    memory_limit: Optional[float] = None,
    groups: Optional[Dict[str, int]] = None,
) -> Tuple[Dict[str, int], float]:
    """Optimal stage map for the graph's op chain + simulated iteration time.

    Per-op times come from the planned PCG under ``strategy`` (non-pp axes
    only) with per-microbatch shapes — the SAME simulator path the MCMC
    scores, so the returned cost is comparable with ``simulate()`` totals.

    ``groups``: optional node-name -> group-index map (contiguous in op
    order) of SESE segments that must land in one stage — residual blocks
    collapsed to supernodes (VERDICT r4 #3); the partition then runs over
    per-group cost sums and expands back to nodes.
    """
    k = dict(mesh.shape)[pp_axis]
    mm = machine or MachineModel.for_mesh(mesh)
    plan = PCG(graph, mesh, strategy or {}, output_tids=None).plan()
    steps = [s for s in plan.steps if not s.is_parallel]
    # same VMEM weight-residency rule as simulate(), but per STAGE: each
    # stage device holds ~1/k of the weights, so smaller models stream
    # nothing — without this the pipeline side would pay full weight
    # streaming while the GSPMD candidate gets the residency discount
    param_total = sum(_step_param_bytes(s, plan, mesh) for s in steps)
    per_stage = param_total / max(k, 1)
    stream_frac = (max(0.0, 1.0 - mm.spec.vmem_resident_bytes / per_stage)
                   if per_stage > 0 else 0.0)
    times = [
        _step_compute_time(
            _microbatch_step(s, n_micro), mesh, mm, measured, training,
            param_bytes=_step_param_bytes(s, plan, mesh) * stream_frac)
        for s in steps
    ]
    if groups:
        gids = [groups.get(s.node.name, 0) for s in steps]
        order = sorted(set(gids))
        gsum = {g: 0.0 for g in order}
        for t, g in zip(times, gids):
            gsum[g] += t
        g_stage = chain_partition([gsum[g] for g in order], k)
        stage_by_gid = dict(zip(order, g_stage))
        stage_of_idx = [stage_by_gid[g] for g in gids]
    else:
        stage_of_idx = chain_partition(times, k)

    # boundary activation bytes per microbatch, PER DEVICE (the producing
    # tensor may be sharded over non-pp axes by the inner strategy, and
    # collective_time expects per-device bytes)
    from .simulator import _local_size

    nid_stage = {s.node.nid: stg for s, stg in zip(steps, stage_of_idx)}
    out_sharding = {}
    for s in steps:
        for tid_like, spec, sh in zip(s.node.outputs, s.out_specs,
                                      s.out_shardings):
            out_sharding[tid_like] = (spec, sh)
    boundary = [0.0] * max(k - 1, 1)
    # a skip connection spanning several stages traverses EVERY cut between
    # producer and its furthest consumer in a GPipe schedule (intermediate
    # stages forward it) — charge each crossed cut ONCE per tensor, not per
    # consumer edge
    far_stage: Dict[int, int] = {}
    for s, stg in zip(steps, stage_of_idx):
        for tid in s.node.inputs:
            far_stage[tid] = max(far_stage.get(tid, 0), stg)
    for tid, stg in far_stage.items():
        prod = graph.producer.get(tid)
        if prod is None:
            continue
        src_stage = nid_stage.get(prod[0])
        if src_stage is None or src_stage >= stg:
            continue
        spec, sh = out_sharding.get(tid, (graph.spec(tid), None))
        if sh is not None:
            local = _local_size(spec, sh, mesh) * (
                spec.nbytes() // max(spec.size, 1))
        else:
            local = spec.nbytes()
        for cut in range(src_stage, stg):
            boundary[cut] += local / n_micro

    stage_costs = [0.0] * k
    for t, stg in zip(times, stage_of_idx):
        stage_costs[stg] += t
    cost = simulate_pipeline(
        stage_costs, boundary, n_micro, mm, (pp_axis,), mesh,
        training=training,
    )
    if memory_limit:
        # per-stage footprint: that stage's params (x4 training: weight +
        # grad + two optimizer slots, matching plan_memory_bytes) + its
        # activations for all in-flight microbatches
        stage_mem = [0.0] * k
        for s, stg in zip(steps, stage_of_idx):
            stage_mem[stg] += _step_param_bytes(s, plan, mesh) * (
                4.0 if training else 1.0
            )
            for spec in s.out_specs:
                stage_mem[stg] += spec.nbytes()
        if max(stage_mem) > memory_limit:
            cost = float("inf")
    return {s.node.name: stg for s, stg in zip(steps, stage_of_idx)}, cost


def pipeline_or_gspmd(
    graph,
    mesh,
    pp_axis: str = "pp",
    n_micro: int = 8,
    machine: Optional[MachineModel] = None,
    measured: Optional[Dict] = None,
    budget: int = 200,
    seed: int = 0,
    training: bool = True,
    memory_limit: Optional[float] = None,
    groups: Optional[Dict[str, int]] = None,
):
    """Search both worlds and return the better plan under the cost model.

    * GSPMD candidate: ``graph_optimize`` over ALL mesh axes (the pp axis
      acts as extra sharding degree).
    * Pipeline candidate: ``graph_optimize`` over the non-pp axes for
      per-op configs, then the optimal chain partition over the pp axis.

    Returns ``(kind, strategy, stage_of, cost)`` with ``kind`` in
    {"gspmd", "pipeline"} and ``stage_of`` None for gspmd.
    """
    from .search import graph_optimize
    from .simulator import simulate

    mm = machine or MachineModel.for_mesh(mesh)

    try:
        gspmd = graph_optimize(graph, mesh, budget=budget, machine=mm,
                               measured=measured, seed=seed,
                               training=training, memory_limit=memory_limit,
                               on_infeasible="raise")
        cost_gspmd = simulate(
            PCG(graph, mesh, gspmd).plan(), mm, training=training,
            measured=measured,
        ).total
    except ValueError:  # no GSPMD strategy fits the memory limit
        gspmd, cost_gspmd = None, float("inf")

    # per-op configs restricted to the non-pp axes: build a sub-mesh view by
    # searching on the same mesh but forbidding the pp axis in candidates —
    # graph_optimize enumerates axes with size > 1, so temporarily treat pp
    # as degree 1 via a masked mesh wrapper
    class _MaskedMesh:
        def __init__(self, mesh, hide):
            self._mesh = mesh
            self._hide = hide

        @property
        def axis_names(self):
            return self._mesh.axis_names

        @property
        def shape(self):
            d = dict(self._mesh.shape)
            d[self._hide] = 1
            return d

        def __getattr__(self, name):
            return getattr(self._mesh, name)

    masked = _MaskedMesh(mesh, pp_axis)
    # inner search runs without the memory guard: the masked view cannot
    # see that the pipeline divides params across stages — stage-level
    # feasibility is checked by propose_pipeline itself
    inner = graph_optimize(graph, masked, budget=budget, machine=mm,
                           measured=measured, seed=seed, training=training,
                           memory_limit=0)
    # partition on the REAL mesh (k = pp degree); the inner strategy uses
    # only non-pp axes, so planning under it is identical on either view
    stage_of, cost_pp = propose_pipeline(
        graph, mesh, pp_axis, n_micro=n_micro, machine=mm,
        measured=measured, strategy=inner, training=training,
        memory_limit=memory_limit, groups=groups,
    )
    if cost_pp == float("inf") and cost_gspmd == float("inf"):
        raise ValueError(
            "neither a GSPMD strategy nor a pipeline partition fits the "
            "memory limit"
        )
    if cost_pp < cost_gspmd:
        return "pipeline", inner, stage_of, cost_pp
    return "gspmd", gspmd, None, cost_gspmd
