"""Joint TP x PP serve search: price stage-split decode under the HBM cap.

SURVEY §4's inference matrix is "model x precision x TP/PP configs"; Unity
(OSDI'22) searches joint parallelization including pipeline stages.  This
module extends the calibrated serve search to that axis: every (tp, pp)
factorization of the chip budget is stage-split with the same machinery the
executor uses (``serve.pp.serve_stage_split`` / ``build_stage_plans``), gated
by PER-STAGE ``plan_memory_bytes`` against the per-chip HBM capacity, and
priced with a decode cost model that accounts for what the generic
``simulate`` cannot see:

* **weight re-streaming per micro-batch** — decode is weight-bandwidth-bound
  and every micro-batch through a stage re-reads that stage's weights, so
  micro-batching trades bubble fraction against weight traffic;
* **KV-prefix streaming** — each request's causally-live cache rows move once
  per macro-step regardless of micro-batch count;
* **inter-stage activation transfer** — one boundary hop per micro-batch per
  adjacent stage pair (``MachineModel.transfer_time``);
* **the pipeline bubble** — steady-state decode re-services a micro-batch
  every ``max(m, pp)`` ticks: below ``m = pp`` stages idle ``(pp-m)/pp``
  of the time, at ``m = pp`` the pipeline is full, and ``m > pp`` buys no
  bubble win while re-streaming stage weights (see :func:`pp_serve_cost`).

The returned plan is what ``PipelinedInferenceManager`` executes; the search
and the executor share the stage split, so "fits per stage" means the same
thing in both places.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .machine_model import MachineModel
from .simulator import (
    HEAVY_OPS,
    _step_flops,
    _step_param_bytes,
    plan_memory_bytes,
    step_state_bytes,
)

_KV_BUFS = frozenset({"k", "v", "k_scale", "v_scale"})


def _stage_kv_bytes(plan) -> float:
    """Local committed-KV bytes (k/v + int8 scales) of a stage plan — the
    per-macro-step cache read bound (err-high: counts the full registered
    capacity, not the instantaneous live prefix, consistent with
    ``plan_memory_bytes``'s reject-safe contract)."""
    return sum(
        step_state_bytes(step, plan.mesh, names=_KV_BUFS)
        for step in plan.steps if not step.is_parallel
    )


def pp_serve_cost(stage_plans, machine: MachineModel, n_micro: int = 1,
                  boundary_bytes: float = 0.0, pp_axes=()) -> Dict:
    """Simulated STEADY-STATE decode cost for a stage-split serve plan.

    The graph's flat batch (``R_tot`` concurrent decode slots) splits into
    ``m = n_micro`` micro-batches that cycle through the ``S`` stages
    continuously — the multi-step decode scan never drains between tokens,
    so a micro-batch is re-serviced every ``max(m, S)`` ticks:

    * tick (one micro-batch through the bottleneck stage):
      ``W_stage/bw + (flops/mxu + KV/bw + tp_comm)/m + step_overhead + hop``
      — the stage's WEIGHTS re-stream for every micro-batch, while the
      macro-batch's flops / causally-live KV / TP collectives split 1/m
      per micro-batch; ``hop`` is the inter-stage boundary transfer
      (``MachineModel.transfer_time``, one handoff per tick on the
      critical path).
    * per-request TPOT = ``max(m, S) * tick``: with ``m >= S`` the pipeline
      is full and PP is latency-neutral capacity scaling (TPOT ~= the
      single-chip step at the same total concurrency, with 1/S of the
      weights+KV per chip); with ``m < S`` stages idle
      ``(S - m)/S`` of the time — the decode bubble.  Fill/drain costs
      ``(S-1)`` extra ticks once per scan, amortized over its length
      (not counted here).

    Returns ``{tpot_s, tick_s, bubble_frac, transfer_s, stage_ticks}``.
    """
    spec = machine.spec
    ticks: List[float] = []
    for plan in stage_plans:
        mesh = plan.mesh
        w = fl = comm = 0.0
        for step in plan.steps:
            if step.is_parallel:
                op = step.node.op
                b = op.comm_bytes(step.in_specs[0], step.in_shardings[0],
                                  mesh)
                comm += machine.collective_time(
                    b, getattr(op, "axes", ()), mesh)
                continue
            w += _step_param_bytes(step, plan, mesh)
            if step.node.op.type_name in HEAVY_OPS:
                fl += _step_flops(step, mesh)
        kv = _stage_kv_bytes(plan)
        tick = (
            w / spec.hbm_bandwidth
            + (fl / (spec.peak_flops_bf16 * spec.mxu_efficiency)
               + kv / spec.hbm_bandwidth + comm) / n_micro
            + spec.step_overhead
        )
        ticks.append(tick)
    s = len(stage_plans)
    hop = machine.transfer_time(boundary_bytes / max(n_micro, 1), pp_axes) \
        if s > 1 else 0.0
    tick = max(ticks) + hop
    tpot = max(n_micro, s) * tick
    return {
        "tpot_s": tpot,
        "tick_s": tick,
        "bubble_frac": max(0, s - n_micro) / s,
        "transfer_s": hop,
        "stage_ticks": ticks,
    }


def _boundary_bytes(graph, split) -> float:
    """Worst-case bytes crossing a stage boundary (full macro-batch): the
    widest exit live set's tensor bytes."""
    import jax.numpy as jnp

    worst = 0.0
    for _, _, exit_tids in split[:-1]:
        b = sum(
            graph.spec(t).size * jnp.dtype(graph.spec(t).dtype).itemsize
            for t in exit_tids
        )
        worst = max(worst, b)
    return worst


def search_serve_plan(
    model,
    n_chips: int,
    machine: Optional[MachineModel] = None,
    hbm_cap: Optional[float] = None,
    n_micro: Sequence[int] = (1, 2, 4),
    devices=None,
    spec_name: Optional[str] = None,
    telemetry=None,
) -> Dict:
    """Pick the best (tp, pp, n_micro) for serving ``model``'s graph on
    ``n_chips`` chips.

    ``telemetry``: optional :class:`~flexflow_tpu.obs.Telemetry` — the
    winning plan's predicted TPOT/bubble/transfer/memory are recorded in
    its calibration ledger under ``tp{t}_pp{p}_m{m}``, so the executing
    side only has to add measured values for the predicted-vs-measured
    report (the MachineModel tuning loop).

    The graph must already carry its serve capacities
    (``register_serve_capacities`` — InferenceManager/PipelinedInferenceManager
    do this in ``__init__``; callers searching BEFORE building a manager call
    it directly) and any int8 annotations (``annotate_int8``), so per-stage
    ``plan_memory_bytes`` prices the deployment's real buffers.

    Every tp x pp = n_chips factorization whose tp divides the attention
    kv-heads is stage-split, memory-gated PER STAGE against ``hbm_cap``
    (default: the machine spec's per-chip HBM), and priced by
    :func:`pp_serve_cost` at each micro-batch count.  Returns the best
    admissible plan plus the full candidate table::

        {"tp", "pp", "n_micro", "tpot_ms", "bubble_frac", "transfer_ms",
         "per_stage_gb", "candidates": {"tp{t}_pp{p}": {...}}}

    Raises ValueError when nothing fits — the caller must shard further or
    shrink capacities, never silently over-subscribe HBM.
    """
    import jax

    from ..parallel.mesh import make_mesh
    from ..serve.inference_manager import tensor_parallel_strategy
    from ..serve.ops import IncMultiHeadSelfAttention
    from ..serve.pp import build_stage_plans, serve_stage_split

    graph = model.graph if hasattr(model, "graph") else model
    devices = list(devices if devices is not None else jax.devices())
    kv_heads = None
    n_layers = 0
    for node in graph.nodes:
        if isinstance(node.op, IncMultiHeadSelfAttention):
            kv_heads = node.op.num_kv_heads
            n_layers += 1
    if not n_layers:
        raise ValueError("graph has no serve attention ops")

    candidates: Dict[str, Dict] = {}
    best = None
    for tp in range(1, n_chips + 1):
        if n_chips % tp or kv_heads % tp:
            continue
        pp = n_chips // tp
        if pp > n_layers or tp > len(devices):
            continue
        # costing mesh: shardings are symbolic, so every stage prices over
        # the same tp-wide device slice
        mesh = make_mesh({"tp": tp}, devices[:tp])
        mm = machine or MachineModel.for_mesh(mesh, spec_name=spec_name)
        cap = hbm_cap if hbm_cap is not None else mm.spec.hbm_capacity
        try:
            split = serve_stage_split(graph, pp)
        except ValueError as e:
            candidates[f"tp{tp}_pp{pp}"] = {"error": str(e)[:80]}
            continue
        strategy = tensor_parallel_strategy(graph, ("tp",), mesh) \
            if tp > 1 else {}
        plans = build_stage_plans(graph, split, strategy, [mesh] * pp)
        mem = [plan_memory_bytes(p, training=False) for p in plans]
        entry = {
            "tp": tp, "pp": pp,
            "per_stage_gb": [round(b / 1e9, 3) for b in mem],
            "fits": max(mem) <= cap,
        }
        bbytes = _boundary_bytes(graph, split)
        by_m = {}
        for m in sorted(set(int(x) for x in n_micro)):
            if m < 1:
                continue
            cost = pp_serve_cost(plans, mm, n_micro=m,
                                 boundary_bytes=bbytes)
            by_m[str(m)] = {
                "tpot_ms": round(cost["tpot_s"] * 1e3, 4),
                "bubble_frac": round(cost["bubble_frac"], 4),
                "transfer_ms": round(cost["transfer_s"] * 1e3, 5),
            }
            if entry["fits"] and (best is None
                                  or cost["tpot_s"] < best["tpot_s"]):
                best = {
                    "tp": tp, "pp": pp, "n_micro": m,
                    "tpot_s": cost["tpot_s"],
                    "tpot_ms": round(cost["tpot_s"] * 1e3, 4),
                    "bubble_frac": round(cost["bubble_frac"], 4),
                    "transfer_ms": round(cost["transfer_s"] * 1e3, 5),
                    "per_stage_gb": entry["per_stage_gb"],
                }
        entry["by_micro"] = by_m
        candidates[f"tp{tp}_pp{pp}"] = entry

    if best is None:
        raise ValueError(
            f"no tp x pp = {n_chips} plan fits the per-chip HBM cap; "
            f"candidates: { {k: v.get('per_stage_gb') for k, v in candidates.items()} }"
        )
    best["candidates"] = candidates
    best["plan_key"] = f"tp{best['tp']}_pp{best['pp']}_m{best['n_micro']}"
    if telemetry is not None and getattr(telemetry, "enabled", False):
        telemetry.record_plan_prediction(
            best["plan_key"],
            tpot_ms=best["tpot_ms"],
            bubble_frac=best["bubble_frac"],
            transfer_ms=best["transfer_ms"],
            memory_gb=max(best["per_stage_gb"]),
        )
    return best
